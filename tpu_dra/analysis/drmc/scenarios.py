"""drmc scenarios: small, terminating models of the risky subsystems.

Two families share the scenario registry:

**Interleaving scenarios** (for explore.explore) spawn controlled tasks
against real components and assert the chaos tier's safety invariants
at every terminal state: no device double-allocation
(simcluster.chaos.chip_conflicts), allocation index == truth
(AllocationIndex.diff_against), checkpoint/CDI consistency, and an
acyclic lock-order graph (the witness runs under every schedule).
``sched-churn`` drives the MULTI-WORKER WorkQueue pool + sharded
AllocationIndex pair the parallel scheduler core (SURVEY §15) is built
on, with an explicit per-key serialization probe; ``shard-dispatch``
drives the partitioned informer's ShardDispatcher (bounded per-shard
FIFOs, overflow shedding, relist healing, mid-stream stop()) against
the same AllocationIndex truth discipline; ``batch-prepare``
drives concurrent DeviceState prepare/unprepare/health batches. ``racy-index``
is the deliberately-buggy fixture — an unserialized check-then-act on
the index — whose violating schedule the tests record and replay.

**Crash scenarios** (for crash.enumerate_crashes) run a durable-op
sequence once per enumerated crash point and assert the recovery
invariants after restart: recovery never throws, externalized successes
are durable, externalized failures stay rolled back, CDI specs never
outlive their checkpoint entries, and a faultless replay converges to
the expected final state. ``batch-prepare-crash`` is the mixed-outcome
batch (one member fails mid-apply while its siblings group-commit)
under the node flock — the exact pipeline ROADMAP item 5's journal
refactor will rewrite.

Scenarios must be deterministic given a schedule: no wall-clock
branching (zero-delay rate limiter), no unseeded randomness, bounded
work per task.
"""

from __future__ import annotations

import itertools
import os
import shutil
import tempfile
import threading
from typing import Dict, List, Optional, Tuple

from tpu_dra.infra.workqueue import RateLimiter, WorkQueue

_DRIVER = "tpu.k8s.tpu.dev"
_POOL = "drmc-node"


class _ZeroLimiter(RateLimiter):
    """No backoff: ready_at == enqueue time, so heap order is push
    order and schedules never depend on the wall clock."""

    def when(self, item_id: int) -> float:
        return 0.0


def _trace_snapshot():
    """Open-span ids at scenario build time: terminal-state checks
    assert only spans THIS scenario began were drained (SURVEY §19) —
    a sibling test's leaked span must not fail the model checker."""
    from tpu_dra.infra import trace
    return trace.TRACER.open_ids()


def _open_span_violations(snapshot) -> List[str]:
    """The span-closure invariant at every terminal state — including
    crash-recovery replays: the prepare pipeline's finally must leave
    only CLOSED (possibly abandoned) spans behind, whatever the
    interleaving or crash point did."""
    from tpu_dra.infra import trace
    return trace.open_span_violations(snapshot,
                                      context="at terminal state")


def _mk_claim(name: str, devices: List[str], rv: int,
              uid: Optional[str] = None) -> Dict:
    return {
        "apiVersion": "resource.k8s.io/v1", "kind": "ResourceClaim",
        "metadata": {"name": name, "namespace": "default",
                     "uid": uid or f"uid-{name}",
                     "resourceVersion": str(rv)},
        "spec": {"devices": {"requests": [{"name": "tpu"}]}},
        "status": {"allocation": {"devices": {"results": [
            {"request": "tpu", "driver": _DRIVER, "pool": _POOL,
             "device": d} for d in devices], "config": []}}},
    }


# ---------------------------------------------------------------------------
# sched-churn: WorkQueue + AllocationIndex under controlled interleaving
# ---------------------------------------------------------------------------

class SchedChurnScenario:
    """A MULTI-WORKER queue pool (two controlled consumers) processing
    keyed bind/unbind reconciles against a sharded AllocationIndex,
    while two producers enqueue (same-key dedupe included) and a
    stopper shuts the queue down mid-stream. Which pods end up bound is
    schedule-dependent BY DESIGN (an unbind racing its bind is real
    churn); the invariants are the safety properties that must hold
    under every ordering — including the pool's per-key serialization
    contract: two items sharing a key must NEVER be mid-callback on two
    workers at once (the deferral path in WorkQueue._get), witnessed by
    an explicit overlap probe rather than trusted."""

    name = "sched-churn"

    def build(self, sched) -> Dict:
        from tpu_dra.simcluster.scheduler import AllocationIndex

        queue = WorkQueue(rate_limiter=_ZeroLimiter())
        index = AllocationIndex()
        truth: Dict[str, Dict] = {}
        truth_lock = threading.Lock()   # witnessed: created under install
        rvs = itertools.count(1)
        devices = ["chip-0", "chip-1", "chip-2"]
        # Per-key overlap probe: counts callbacks mid-flight per key.
        # Kept under its own witnessed lock; any count > 1 is a
        # violation of the pool's client-go parallelism contract.
        active: Dict[str, int] = {}
        overlaps: List[str] = []
        probe_lock = threading.Lock()

        def keyed(key: str, body):
            def cb(_obj) -> None:
                with probe_lock:
                    n = active.get(key, 0) + 1
                    active[key] = n
                    if n > 1:
                        overlaps.append(
                            f"key {key}: {n} callbacks mid-flight — "
                            "per-key serialization broken")
                try:
                    body()
                finally:
                    with probe_lock:
                        active[key] -= 1
            return cb

        def bind(key: str):
            def body() -> None:
                # Serialized check-then-act: the pick, the index apply
                # and the truth record commit atomically under the
                # truth lock — the discipline racy-index drops.
                with truth_lock:
                    used = {d for c in truth.values()
                            for _, _, d in _entries(c)}
                    free = sorted(set(devices) - used)
                    if not free or key in truth:
                        return
                    claim = _mk_claim(key, [free[0]], next(rvs))
                    index.apply(claim)
                    truth[key] = claim
            return keyed(key, body)

        def unbind(key: str):
            def body() -> None:
                with truth_lock:
                    claim = truth.pop(key, None)
                    if claim is not None:
                        index.remove(claim, force=True)
            return keyed(key, body)

        def producer1() -> None:
            queue.enqueue(None, bind("pod-a"), key="pod-a")
            queue.enqueue(None, bind("pod-b"), key="pod-b", dedupe=True)
            # Same-key storm: absorbs into the queued pod-b item while
            # it has not been handed to a worker; once it HAS, this
            # enqueues a second pod-b item — which the pool must then
            # defer, never run concurrently with the first.
            queue.enqueue(None, bind("pod-b"), key="pod-b", dedupe=True)

        def producer2() -> None:
            queue.enqueue(None, bind("pod-c"), key="pod-c")
            queue.enqueue(None, unbind("pod-a"), key="pod-a")

        def stopper() -> None:
            queue.shutdown()

        sched.spawn("worker0", queue.run)
        sched.spawn("worker1", queue.run)
        sched.spawn("producer1", producer1)
        sched.spawn("producer2", producer2)
        sched.spawn("stopper", stopper)
        return {"queue": queue, "index": index, "truth": truth,
                "overlaps": overlaps, "trace_snap": _trace_snapshot()}

    def check(self, ctx) -> List[str]:
        from tpu_dra.simcluster.chaos import chip_conflicts

        queue, index, truth = ctx["queue"], ctx["index"], ctx["truth"]
        # Quiesce: a shutdown racing the producers legitimately strands
        # queued AND deferred items; drain both the way a restarted
        # worker would (single-threaded here, so serialization holds).
        import heapq
        while queue._heap or queue._deferred:
            while queue._heap:
                _, _, item = heapq.heappop(queue._heap)
                item.callback(item.obj)
            for key in sorted(queue._deferred):
                for item in queue._deferred.pop(key):
                    item.callback(item.obj)
        violations = list(ctx["overlaps"])
        claims = [truth[k] for k in sorted(truth)]
        violations.extend(index.diff_against(claims))
        violations.extend(chip_conflicts(claims))
        violations.extend(_open_span_violations(ctx["trace_snap"]))
        return violations

    def cleanup(self, ctx) -> None:
        ctx["queue"].shutdown()


def _entries(claim: Dict):
    from tpu_dra.simcluster.scheduler import claim_entries
    return claim_entries(claim)


# ---------------------------------------------------------------------------
# shard-dispatch: the sched-churn family's sharded fan-out probe
# ---------------------------------------------------------------------------

class ShardDispatchScenario:
    """The partitioned informer's ShardDispatcher driven as explicit
    interleaved tasks: a producer offering claim deltas into BOUNDED
    per-shard FIFOs (cap 1, so overflow is reachable in most orderings),
    one drainer per shard, a relist task healing dirty shards from the
    intent record, and a stopper calling the real ``stop()`` mid-stream.
    This is the overflow-vs-relist-vs-shutdown race surface behind the
    10k-node fan-out: a shed delta MUST be healed by a shard relist, a
    relist racing fresh deltas must not resurrect stale state (seq
    gating — the scheduler's resourceVersion discipline), and a stop()
    racing live drains must strand nothing. Invariant at every terminal
    state: after the single-threaded quiesce, applied state == intended
    state per key, the AllocationIndex matches truth exactly, and no
    chip is double-booked."""

    name = "shard-dispatch"

    def __init__(self):
        # Observability for the tests: how many offers shed in the last
        # run (check() records it) — proves the probe exercises the
        # overflow path rather than vacuously passing.
        self._last_overflows = 0

    def build(self, sched) -> Dict:
        from tpu_dra.k8s.informer import ShardDispatcher
        from tpu_dra.simcluster.scheduler import AllocationIndex

        index = AllocationIndex(n_shards=2)
        truth: Dict[str, Dict] = {}
        # intent[key] = (seq, devices|None): what the apiserver said
        # last, recorded BEFORE the offer — the watch event exists even
        # when the dispatch sheds it, which is exactly why a shed must
        # mark the shard dirty.
        intent: Dict[str, Tuple[int, Optional[List[str]]]] = {}
        applied_seq: Dict[str, int] = {}
        dirty: set = set()
        truth_lock = threading.Lock()   # witnessed: created under install

        disp = ShardDispatcher(2, cap=1, name="drmc",
                               on_overflow=lambda sid, why: dirty.add(sid))
        # Two keys per shard, found deterministically (crc32 is stable).
        by_shard: Dict[int, List[str]] = {0: [], 1: []}
        i = 0
        while any(len(v) < 1 for v in by_shard.values()) or i < 4:
            k = f"pool-{i}"
            if len(by_shard[disp.route(k)]) < 2:
                by_shard[disp.route(k)].append(k)
            i += 1
        key_a, key_b = by_shard[0][0], by_shard[1][0]

        def apply_intent(key: str, seq: int,
                         devices: Optional[List[str]]) -> None:
            # Seq-gated apply: an old delta drained AFTER a relist (or a
            # relist re-reading already-applied intent) must be a no-op,
            # never a regression — the RV discipline in miniature.
            with truth_lock:
                if seq <= applied_seq.get(key, 0):
                    return
                applied_seq[key] = seq
                old = truth.pop(key, None)
                if old is not None:
                    index.remove(old, force=True)
                if devices is not None:
                    claim = _mk_claim(key, devices, seq)
                    index.apply(claim)
                    truth[key] = claim

        def delta(key: str, seq: int, devices: Optional[List[str]]):
            return lambda: apply_intent(key, seq, devices)

        def offer(key: str, seq: int,
                  devices: Optional[List[str]]) -> None:
            with truth_lock:
                intent[key] = (seq, devices)
            disp.offer(disp.route(key), delta(key, seq, devices))

        def producer() -> None:
            offer(key_a, 1, ["chip-0"])
            offer(key_b, 2, ["chip-1"])
            offer(key_a, 3, ["chip-2"])   # rebind: remove + apply
            offer(key_b, 4, None)         # unbind

        def drainer(sid: int):
            def run() -> None:
                for _ in range(4):
                    disp.drain_one(sid)
            return run

        def relist() -> None:
            # Heal pass racing everything else: clear the flag FIRST so
            # a shed that lands after our truth read re-dirties the
            # shard for the terminal heal in check().
            for sid in (0, 1):
                if sid in dirty:
                    dirty.discard(sid)
                    for key in by_shard[sid]:
                        rec = intent.get(key)
                        if rec is not None:
                            apply_intent(key, rec[0], rec[1])

        def stopper() -> None:
            disp.stop()

        sched.spawn("producer", producer)
        sched.spawn("drain0", drainer(0))
        sched.spawn("drain1", drainer(1))
        sched.spawn("relist", relist)
        sched.spawn("stopper", stopper)
        return {"disp": disp, "index": index, "truth": truth,
                "intent": intent, "applied_seq": applied_seq,
                "dirty": dirty, "by_shard": by_shard}

    def check(self, ctx) -> List[str]:
        from tpu_dra.simcluster.chaos import chip_conflicts

        disp, index, truth = ctx["disp"], ctx["index"], ctx["truth"]
        self._last_overflows = disp.overflows
        # Quiesce the way the informer's stop() + scheduler resync
        # would: drain stranded thunks single-threaded, then run the
        # shard relist for anything still marked dirty.
        for sid in (0, 1):
            while disp.drain_one(sid):
                pass
        for sid in sorted(ctx["dirty"]):
            for key in ctx["by_shard"][sid]:
                rec = ctx["intent"].get(key)
                if rec is not None:
                    seq, devices = rec
                    if seq > ctx["applied_seq"].get(key, 0):
                        ctx["applied_seq"][key] = seq
                        old = truth.pop(key, None)
                        if old is not None:
                            index.remove(old, force=True)
                        if devices is not None:
                            claim = _mk_claim(key, devices, seq)
                            index.apply(claim)
                            truth[key] = claim
        violations: List[str] = []
        for key, (seq, devices) in sorted(ctx["intent"].items()):
            if ctx["applied_seq"].get(key, 0) != seq:
                violations.append(
                    f"key {key}: intended seq {seq} never applied "
                    f"(got {ctx['applied_seq'].get(key, 0)}) — "
                    "shed delta not healed by relist")
            have = ([d for _, _, d in _entries(truth[key])]
                    if key in truth else None)
            if have != devices:
                violations.append(
                    f"key {key}: terminal devices {have} != intended "
                    f"{devices}")
        claims = [truth[k] for k in sorted(truth)]
        violations.extend(index.diff_against(claims))
        violations.extend(chip_conflicts(claims))
        return violations

    def cleanup(self, ctx) -> None:
        ctx["disp"].stop()


# ---------------------------------------------------------------------------
# racy-index: the deliberately-buggy fixture (violation demo + replay)
# ---------------------------------------------------------------------------

class RacyIndexScenario:
    """Check-then-act on the AllocationIndex WITHOUT serializing the
    pick against the apply: two reconciles can both observe the one
    free device between each other's index lock sections and
    double-allocate it. drmc must find a violating schedule, and the
    recorded trace must replay to the identical violation — the
    seeded-replay acceptance test."""

    name = "racy-index"

    def build(self, sched) -> Dict:
        from tpu_dra.simcluster.scheduler import AllocationIndex

        index = AllocationIndex()
        truth: Dict[str, Dict] = {}
        rvs = itertools.count(1)

        def racy_bind(key: str):
            def body() -> None:
                # BUG (on purpose): the is_taken read and the apply
                # each take the index lock, but nothing serializes the
                # pair — a sibling can interleave between them.
                if index.is_taken(_DRIVER, _POOL, "chip-0"):
                    return
                claim = _mk_claim(key, ["chip-0"], next(rvs))
                index.apply(claim)
                truth[key] = claim
            return body

        sched.spawn("bind-a", racy_bind("pod-a"))
        sched.spawn("bind-b", racy_bind("pod-b"))
        return {"index": index, "truth": truth}

    def check(self, ctx) -> List[str]:
        from tpu_dra.simcluster.chaos import chip_conflicts
        claims = [ctx["truth"][k] for k in sorted(ctx["truth"])]
        violations = list(ctx["index"].diff_against(claims))
        violations.extend(chip_conflicts(claims))
        return violations

    def cleanup(self, ctx) -> None:
        pass


class _BoundedStore:
    """A capacity-bounded admission set: the minimal model of every
    check-then-act surface in the tree (slot claims, quota admission,
    the allocation index). ``count`` reads under the lock; ``admit``
    writes under the lock; NOTHING ties the pair together — that is
    the caller's job, and the stale-read probe exercises both ways of
    doing it."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        self.admitted: List[str] = []
        self._lock = threading.Lock()

    def count(self) -> int:
        with self._lock:
            return len(self.admitted)

    def admit(self, key: str) -> None:
        with self._lock:
            self.admitted.append(key)

    # drflow: REVALIDATES:admitted
    def try_admit(self, key: str) -> bool:
        """The sanctioned act: re-validates the capacity bound against
        LIVE state under the lock (the try_commit shape drflow R14's
        REVALIDATES annotation documents)."""
        with self._lock:
            if len(self.admitted) >= self.capacity:
                return False
            self.admitted.append(key)
            return True


class StaleReadProbeScenario:
    """Check-then-act on a STALE SNAPSHOT across a lock release: the
    capacity check reads under the store's lock, the lock releases at
    return, and the admit runs on the stale decision — two takers can
    both observe the free slot and overrun the bound. drmc must find
    the violating schedule; drflow R14 flags the same source shape
    statically (tests assert both directions, the R13-R15 analog of
    racy-index's seeded-replay acceptance)."""

    name = "stale-read-probe"

    def build(self, sched) -> Dict:
        store = _BoundedStore(capacity=1)

        def taker(key: str):
            def body() -> None:
                # BUG (on purpose): count() releases the lock before
                # admit() re-acquires it — nothing revalidates the
                # bound (static analog: drflow R14).
                n = store.count()
                if n < store.capacity:
                    store.admit(key)  # dralint: ignore[R14] — deliberately racy probe fixture: drmc finds the interleaving; test_flowanalysis asserts the static finding
            return body

        sched.spawn("take-a", taker("a"))
        sched.spawn("take-b", taker("b"))
        return {"store": store}

    def check(self, ctx) -> List[str]:
        store = ctx["store"]
        if len(store.admitted) > store.capacity:
            return [f"capacity {store.capacity} overrun: "
                    f"{sorted(store.admitted)} all admitted on a stale "
                    "count"]
        return []

    def cleanup(self, ctx) -> None:
        pass


class StaleReadFixedScenario:
    """The SANCTIONED counterpart: the act routes through try_admit,
    which re-validates the bound under the lock (the REVALIDATES
    protocol). No schedule may overrun — this one IS a gate scenario,
    so the protocol the static annotation documents stays dynamically
    proven."""

    name = "stale-read-fixed"

    def build(self, sched) -> Dict:
        store = _BoundedStore(capacity=1)

        def taker(key: str):
            def body() -> None:
                if store.count() < store.capacity:
                    store.try_admit(key)
            return body

        sched.spawn("take-a", taker("a"))
        sched.spawn("take-b", taker("b"))
        return {"store": store}

    def check(self, ctx) -> List[str]:
        store = ctx["store"]
        if len(store.admitted) > store.capacity:
            return [f"capacity {store.capacity} overrun through "
                    "try_admit: the revalidating commit is broken"]
        return []

    def cleanup(self, ctx) -> None:
        pass


# ---------------------------------------------------------------------------
# evict-churn: eviction racing the optimistic bind pipeline (SURVEY §18)
# ---------------------------------------------------------------------------

class EvictChurnScenario:
    """Evict-vs-prepare and evict-vs-commit: binders place claims
    through the REAL optimistic pipeline (snapshot -> pick ->
    try_commit reservation -> truth write -> apply -> release) while an
    evictor kills a device mid-stream and releases its holder the way
    the scheduler's evict scan does (truth removal mirrored by
    remove(force=True)), then re-drives the victim through the queue.
    Which claims end up bound is schedule-dependent BY DESIGN; the
    safety properties under EVERY ordering:

    - no device double-allocation (a reservation the evictor interleaves
      with must still be all-or-nothing);
    - index == truth at quiesce;
    - no claim bound to the dead device once the eviction has run —
      a bind racing the eviction must abort via the dead-set check and
      release its reservation, never commit onto dead hardware."""

    name = "evict-churn"

    def build(self, sched) -> Dict:
        from tpu_dra.simcluster.scheduler import AllocationIndex

        queue = WorkQueue(rate_limiter=_ZeroLimiter())
        index = AllocationIndex()
        truth: Dict[str, Dict] = {}
        dead: set = set()
        evicted: List[str] = []
        truth_lock = threading.Lock()   # witnessed: created under install
        rvs = itertools.count(1)
        devices = ["chip-0", "chip-1", "chip-2"]

        def bind(key: str):
            def body(_obj=None) -> None:
                for _attempt in range(4):
                    view = index.snapshot(_POOL)
                    with truth_lock:
                        if key in truth:
                            return
                        free = [d for d in devices
                                if d not in dead
                                and not view.is_taken(_DRIVER, d)]
                    if not free:
                        return
                    entries = ((_DRIVER, _POOL, free[0]),)
                    if not index.try_commit(_POOL, [(key, entries)]):
                        continue  # conflict: re-scan a fresh snapshot
                    claim = _mk_claim(key, [free[0]], next(rvs))
                    with truth_lock:
                        if free[0] in dead:
                            # The device died between the reservation
                            # and the write: abort — committing would
                            # bind onto dead hardware.
                            index.release(_POOL, [key])
                            return
                        # Truth write + index apply commit atomically
                        # (the apiserver-serialized mutation-cache
                        # discipline, same as sched-churn): the evictor
                        # must never observe a truth entry whose index
                        # apply has not landed, or its higher-rv
                        # dealloc has no routing home to supersede.
                        truth[key] = claim
                        index.apply(claim)
                    index.release(_POOL, [key])
                    return
            return body

        def evictor() -> None:
            # chip-0 dies: release every holder through the real
            # pipeline — a DEALLOCATED claim write at a HIGHER rv,
            # mirrored into the index via apply (exactly what
            # _after_claim_write does) — then re-drive the victims.
            # NOT remove(force=True): that only advances the watermark
            # to the victim's OWN rv, so a binder's delayed same-rv
            # apply would pass the strict staleness check and
            # resurrect the evicted entry (the real scheduler never
            # has this problem because eviction IS a new higher-RV
            # write; the miniature must model the same thing).
            victims = []
            with truth_lock:
                dead.add("chip-0")
                for k in sorted(truth):
                    if any(d == "chip-0"
                           for _dr, _p, d in _entries(truth[k])):
                        claim = truth.pop(k)
                        index.apply(_mk_claim(
                            k, [], next(rvs),
                            uid=claim["metadata"]["uid"]))
                        victims.append(k)
                        evicted.append(k)
            for k in victims:
                queue.enqueue(None, bind(k), key=k, dedupe=True)

        def producer1() -> None:
            queue.enqueue(None, bind("pod-a"), key="pod-a")
            queue.enqueue(None, bind("pod-b"), key="pod-b", dedupe=True)

        def producer2() -> None:
            queue.enqueue(None, bind("pod-c"), key="pod-c")

        def stopper() -> None:
            queue.shutdown()

        sched.spawn("worker0", queue.run)
        sched.spawn("worker1", queue.run)
        sched.spawn("producer1", producer1)
        sched.spawn("producer2", producer2)
        sched.spawn("evictor", evictor)
        sched.spawn("stopper", stopper)
        return {"queue": queue, "index": index, "truth": truth,
                "dead": dead, "evicted": evicted,
                "trace_snap": _trace_snapshot()}

    def check(self, ctx) -> List[str]:
        import heapq

        from tpu_dra.simcluster.chaos import chip_conflicts

        queue, index, truth = ctx["queue"], ctx["index"], ctx["truth"]
        # Quiesce drain, as in sched-churn: a shutdown racing the
        # producers/evictor legitimately strands queued re-binds.
        while queue._heap or queue._deferred:
            while queue._heap:
                _, _, item = heapq.heappop(queue._heap)
                item.callback(item.obj)
            for key in sorted(queue._deferred):
                for item in queue._deferred.pop(key):
                    item.callback(item.obj)
        violations: List[str] = []
        claims = [truth[k] for k in sorted(truth)]
        violations.extend(index.diff_against(claims))
        violations.extend(chip_conflicts(claims))
        dead = ctx["dead"]
        if dead:  # the evictor ran: nobody may hold the dead device
            for key in sorted(truth):
                on_dead = [d for _dr, _p, d in _entries(truth[key])
                           if d in dead]
                if on_dead:
                    violations.append(
                        f"claim {key} bound to dead device(s) "
                        f"{on_dead} after eviction")
        violations.extend(_open_span_violations(ctx["trace_snap"]))
        return violations

    def cleanup(self, ctx) -> None:
        ctx["queue"].shutdown()


# ---------------------------------------------------------------------------
# takeover-resync: deposed-leader commits vs. HA takeover (SURVEY §22)
# ---------------------------------------------------------------------------

class TakeoverScenario:
    """Deposed-leader-commit vs. takeover-resync, against the REAL
    fencing reactor on a real FakeCluster: an old scheduler incarnation
    (generation 1, device picks baked from a pre-takeover snapshot —
    the stale standby view) commits claim allocations while the new
    incarnation bumps the lease (leaseTransitions 1 -> 2), re-lists
    cluster truth (_full_resync's rebuild), and re-drives whatever is
    still unallocated under generation 2. The explorer owns the
    interleaving of every cluster op; under ALL of them:

    - never two acting leaders' commits both land for one claim (a
      deposed write arriving after the bump is refused by the fencing
      reactor; one landing anyway would also surface as the old
      leader's stale device pick double-allocating a chip the new
      leader handed out);
    - no device double-allocation across the takeover;
    - the new leader is never fenced (its stamp IS the current
      generation) and leaks no claim: every claim is allocated at
      quiesce, by exactly one incarnation;
    - the rebuilt index matches cluster truth."""

    name = "takeover-resync"

    def build(self, sched) -> Dict:
        from tpu_dra.infra.leaderelect import (
            FENCING_ANNOTATION, LEASE_NAME, LEASE_NAMESPACE,
            install_fencing,
        )
        from tpu_dra.k8s import LEASES, RESOURCECLAIMS
        from tpu_dra.k8s.client import ConflictError
        from tpu_dra.k8s.fake import FakeCluster, new_lease
        from tpu_dra.simcluster.scheduler import AllocationIndex

        cluster = FakeCluster()  # witnessed: locks created under install
        install_fencing(cluster)
        # Fixed clock: the reactor reads only leaseTransitions, so a
        # frozen renewTime keeps the scenario schedule-deterministic.
        cluster.create(LEASES, new_lease(
            LEASE_NAME, LEASE_NAMESPACE, "old", 1.0, 0.0))
        for key in ("pod-a", "pod-b"):
            cluster.create(RESOURCECLAIMS, {
                "apiVersion": "resource.k8s.io/v1",
                "kind": "ResourceClaim",
                "metadata": {"name": key, "namespace": "default"},
                "spec": {"devices": {"requests": [{"name": "tpu"}]}},
            })
        index = AllocationIndex()
        log: Dict[str, List[str]] = {
            "old_landed": [], "old_refused": [],
            "new_landed": [], "new_refused": []}
        devices = ["chip-0", "chip-1"]

        def commit(key: str, device: str, gen: int,
                   landed: List[str], refused: List[str]) -> None:
            obj = cluster.get(RESOURCECLAIMS, key, "default")
            obj["metadata"].setdefault("annotations", {})[
                FENCING_ANNOTATION] = str(gen)
            obj["status"] = {"allocation": {"devices": {"results": [
                {"request": "tpu", "driver": _DRIVER, "pool": _POOL,
                 "device": device}], "config": []}}}
            try:
                updated = cluster.update(RESOURCECLAIMS, obj, "default")
            except ConflictError:
                refused.append(key)
                return
            landed.append(key)
            if gen == 2:  # only the new incarnation maintains the index
                index.apply(updated)

        def old_leader() -> None:
            # Device picks frozen from the pre-takeover free set: the
            # deposed leader acting on a world that moved without it.
            commit("pod-a", devices[0], 1,
                   log["old_landed"], log["old_refused"])
            commit("pod-b", devices[1], 1,
                   log["old_landed"], log["old_refused"])

        def takeover() -> None:
            # Bump-then-resync, the elector's _takeover + promote() in
            # miniature: after the CAS lands, every pre-bump commit is
            # visible to the re-list and every post-bump deposed write
            # is refused, so the rebuilt view is linearized.
            lease = cluster.get(LEASES, LEASE_NAME, LEASE_NAMESPACE)
            lease["spec"]["holderIdentity"] = "new"
            lease["spec"]["leaseTransitions"] = 2
            cluster.update(LEASES, lease, LEASE_NAMESPACE)
            claims = cluster.list(RESOURCECLAIMS, namespace="default")
            taken, pending = set(), []
            for c in claims:
                entries = [r.get("device") for r in
                           ((c.get("status") or {}).get("allocation")
                            or {}).get("devices", {}).get("results", [])]
                if entries:
                    taken.update(entries)
                    index.apply(c)
                else:
                    pending.append(c["metadata"]["name"])
            free = [d for d in devices if d not in taken]
            # Reversed re-drive order: if a deposed write lands where
            # it must not, its stale pick collides with a chip handed
            # out here instead of silently shadowing the same one.
            for key in sorted(pending, reverse=True):
                commit(key, free.pop(0), 2,
                       log["new_landed"], log["new_refused"])

        sched.spawn("old-leader", old_leader)
        sched.spawn("takeover", takeover)
        return {"cluster": cluster, "index": index, "log": log,
                "trace_snap": _trace_snapshot()}

    def check(self, ctx) -> List[str]:
        from tpu_dra.k8s import RESOURCECLAIMS
        from tpu_dra.simcluster.chaos import chip_conflicts

        cluster, index, log = ctx["cluster"], ctx["index"], ctx["log"]
        violations: List[str] = []
        claims = cluster.list(RESOURCECLAIMS, namespace="default")
        violations.extend(chip_conflicts(claims))
        violations.extend(index.diff_against(claims))
        for c in claims:
            name = c["metadata"]["name"]
            results = ((c.get("status") or {}).get("allocation")
                       or {}).get("devices", {}).get("results", [])
            if not results:
                violations.append(
                    f"claim {name} leaked across takeover "
                    f"(unallocated at quiesce)")
        both = set(log["old_landed"]) & set(log["new_landed"])
        if both:
            violations.append(
                f"two acting leaders' commits both landed for "
                f"{sorted(both)}")
        if log["new_refused"]:
            violations.append(
                f"acting leader fenced on its own generation: "
                f"{log['new_refused']}")
        violations.extend(_open_span_violations(ctx["trace_snap"]))
        return violations

    def cleanup(self, ctx) -> None:
        pass


# ---------------------------------------------------------------------------
# batch-prepare: concurrent DeviceState batches under controlled scheduling
# ---------------------------------------------------------------------------

class BatchPrepareScenario:
    """Two prepare batches and a health-event storm interleaved against
    one DeviceState: the global state lock, the per-chip locks and the
    group-commit checkpoint pipeline under every explored ordering.
    Terminal invariants are the chaos harness's: checkpoint == expected
    completed set, CDI specs == checkpoint, idempotent re-prepare, and
    the health marks fully reversed."""

    name = "batch-prepare"

    def build(self, sched) -> Dict:
        from tpu_dra.cdi.handler import CDIHandler
        from tpu_dra.native.tpuinfo import FakeBackend, default_fake_chips
        from tpu_dra.tpuplugin.checkpoint import CheckpointManager
        from tpu_dra.tpuplugin.device_state import DeviceState

        tmp = tempfile.mkdtemp(prefix="drmc-bp-")
        backend = FakeBackend(default_fake_chips(4, "v5p",
                                                 slice_id="drmc"))
        cdi = CDIHandler(os.path.join(tmp, "cdi"),
                         driver_root=os.path.join(tmp, "drv"))
        # async_cdi off: the writer-pool thread is not a controlled
        # task, so its scheduling would leak uncontrolled concurrency
        # into the explored interleavings.
        state = DeviceState(
            backend=backend, cdi=cdi,
            checkpoints=CheckpointManager(os.path.join(tmp, "plugin")),
            driver_name=_DRIVER, node_name=_POOL, async_cdi=False)

        claims = {n: _mk_claim(n, [f"chip-{i}"], rv=1)
                  for i, n in enumerate(("ca", "cb", "cc"))}
        results: Dict[str, Dict] = {}

        def batch1() -> None:
            res = state.prepare_batch([claims["ca"], claims["cb"]])
            results.update({uid: r.error for uid, r in res.items()})
            errs = state.unprepare_batch([claims["ca"]["metadata"]["uid"]])
            results["unprep-ca"] = errs[claims["ca"]["metadata"]["uid"]]

        def batch2() -> None:
            res = state.prepare_batch([claims["cc"]])
            results.update({uid: r.error for uid, r in res.items()})

        def health() -> None:
            state.mark_unhealthy(3)
            state.healthy_devices()
            state.mark_healthy(3)

        sched.spawn("batch1", batch1)
        sched.spawn("batch2", batch2)
        sched.spawn("health", health)
        return {"tmp": tmp, "state": state, "cdi": cdi,
                "claims": claims, "results": results,
                "trace_snap": _trace_snapshot()}

    def check(self, ctx) -> List[str]:
        from tpu_dra.tpuplugin.checkpoint import PREPARE_COMPLETED

        state, claims = ctx["state"], ctx["claims"]
        v: List[str] = []
        for key, err in sorted(ctx["results"].items()):
            if err:
                v.append(f"operation {key} failed: {err}")
        want = {claims["cb"]["metadata"]["uid"],
                claims["cc"]["metadata"]["uid"]}
        snap = state.checkpoint_snapshot()
        if set(snap.claims) != want:
            v.append(f"checkpoint {sorted(snap.claims)} != "
                     f"expected {sorted(want)}")
        for uid, pc in snap.claims.items():
            if pc.state != PREPARE_COMPLETED:
                v.append(f"claim {uid} left {pc.state}")
        specs = set(ctx["cdi"].list_claim_uids())
        if specs != want:
            v.append(f"CDI specs {sorted(specs)} != expected "
                     f"{sorted(want)}")
        # Idempotent re-prepare (uncontrolled: the run is over).
        res = state.prepare_batch([claims["cb"]])
        err = res[claims["cb"]["metadata"]["uid"]].error
        if err:
            v.append(f"idempotent re-prepare failed: {err}")
        if len(state.healthy_devices()) != len(state.allocatable):
            v.append("health marks not fully reversed")
        v.extend(_open_span_violations(ctx["trace_snap"]))
        return v

    def cleanup(self, ctx) -> None:
        try:
            ctx["state"].close()
        finally:
            shutil.rmtree(ctx["tmp"], ignore_errors=True)


# ---------------------------------------------------------------------------
# batch-prepare-crash: the crash-point scenario (crash.enumerate_crashes)
# ---------------------------------------------------------------------------

class BatchPrepareCrashScenario:
    """A mixed-outcome prepare batch (member `cb` fails mid-apply via
    the prepare.batch_apply fault site; its siblings group-commit) and
    a follow-up unprepare, both under the node flock — then a crash at
    every durable op. Recovery invariants per the ISSUE: recovery never
    throws, externalized successes are durable, the externalized loser
    stays rolled back, CDI specs never outlive checkpoint entries, and
    the kubelet-style faultless replay converges."""

    name = "batch-prepare-crash"

    def setup(self) -> Dict:
        from tpu_dra.cdi.handler import CDIHandler
        from tpu_dra.native.tpuinfo import FakeBackend, default_fake_chips
        from tpu_dra.tpuplugin.checkpoint import CheckpointManager
        from tpu_dra.tpuplugin.device_state import DeviceState

        tmp = tempfile.mkdtemp(prefix="drmc-crash-")
        backend = FakeBackend(default_fake_chips(4, "v5p",
                                                 slice_id="drmc"))
        cdi = CDIHandler(os.path.join(tmp, "cdi"),
                         driver_root=os.path.join(tmp, "drv"))
        # async_cdi is bypassed anyway while the recorder is installed
        # (determinism of the durable-op sequence); journal_compact_lag
        # is forced low so the body CROSSES the compaction threshold —
        # the compaction's slot store + segment retirement (fresh
        # segment create, old-chain unlinks, dir sync) get
        # crash-enumerated too — and segment_roll_bytes is forced tiny
        # so appends between compactions ALSO cross the size-roll
        # rotation (ISSUE 17: settle-old-tail fdatasync, new-segment
        # create, deferred dir sync).
        state = DeviceState(
            backend=backend, cdi=cdi,
            checkpoints=CheckpointManager(os.path.join(tmp, "plugin"),
                                          journal_compact_lag=2,
                                          segment_roll_bytes=64),
            driver_name=_DRIVER, node_name=_POOL, async_cdi=False)
        claims = {n: _mk_claim(n, [f"chip-{i}"], rv=1)
                  for i, n in enumerate(("ca", "cb", "cc"))}
        return {"tmp": tmp, "state": state, "cdi": cdi,
                "claims": claims, "externalized": {},
                "trace_snap": _trace_snapshot()}

    def body(self, ctx) -> None:
        from tpu_dra.infra.faults import FAULTS, Always
        from tpu_dra.infra.flock import Flock

        state, claims = ctx["state"], ctx["claims"]
        ext: Dict[str, str] = ctx["externalized"]
        loser = claims["cb"]["metadata"]["uid"]

        def fail_loser(claim_uid=None, **_ctx) -> None:
            if claim_uid == loser:
                raise RuntimeError("drmc injected mid-apply failure")

        lock = Flock(os.path.join(ctx["tmp"], "prep.lock"))
        with lock:
            with FAULTS.armed("prepare.batch_apply", Always(),
                              action=fail_loser):
                res = state.prepare_batch(
                    [claims["ca"], claims["cb"], claims["cc"]])
        # The RPC returned: these outcomes are now externalized — from
        # here on, a crash may not un-happen them.
        for uid, r in res.items():
            ext[uid] = "failed" if r.error else "completed"
        # Once the unprepare is REQUESTED the claim is transitioning by
        # kubelet's own intent: a crash may legitimately land on either
        # side of its removal, so the survival invariant relaxes to
        # "completed or cleanly gone" until the result externalizes.
        uid_ca = claims["ca"]["metadata"]["uid"]
        ext[uid_ca] = "unprepare-requested"
        with lock:
            errs = state.unprepare_batch([uid_ca])
        if errs[uid_ca] is None:
            ext[uid_ca] = "unprepared"

    def dispose(self, ctx) -> None:
        """The simulated process death: release fds, store nothing."""
        ctx["state"].close()

    def recover_and_check(self, ctx) -> List[str]:
        from tpu_dra.cdi.handler import CDIHandler
        from tpu_dra.native.tpuinfo import FakeBackend, default_fake_chips
        from tpu_dra.tpuplugin.checkpoint import (
            PREPARE_COMPLETED, CheckpointManager,
        )
        from tpu_dra.tpuplugin.device_state import DeviceState

        tmp, claims = ctx["tmp"], ctx["claims"]
        ext: Dict[str, str] = ctx["externalized"]
        v: List[str] = []
        state2 = None
        try:
            backend = FakeBackend(default_fake_chips(4, "v5p",
                                                     slice_id="drmc"))
            cdi2 = CDIHandler(os.path.join(tmp, "cdi"),
                              driver_root=os.path.join(tmp, "drv"))
            try:
                state2 = DeviceState(
                    backend=backend, cdi=cdi2,
                    checkpoints=CheckpointManager(
                        os.path.join(tmp, "plugin")),
                    driver_name=_DRIVER, node_name=_POOL,
                    async_cdi=False)
            except Exception as e:  # noqa: BLE001 — THE invariant:
                # recovery must never be unable to come up.
                return [f"recovery failed to start: {e}"]
            snap = state2.checkpoint_snapshot()
            for uid, status in sorted(ext.items()):
                pc = snap.claims.get(uid)
                if status == "completed" and (
                        pc is None or pc.state != PREPARE_COMPLETED):
                    v.append(f"externalized success for {uid} lost "
                             "(success before the terminal sync?)")
                elif status == "failed" and pc is not None \
                        and pc.state == PREPARE_COMPLETED:
                    v.append(f"externalized failure for {uid} "
                             "resurrected as completed")
                elif status == "unprepared" and pc is not None:
                    v.append(f"externalized unprepare of {uid} "
                             "resurrected")
                elif status == "unprepare-requested" and pc is not None \
                        and pc.state != PREPARE_COMPLETED:
                    v.append(f"in-flight unprepare left {uid} in "
                             f"{pc.state} (neither committed nor gone)")
            orphans = set(cdi2.list_claim_uids()) - set(snap.claims)
            if orphans:
                v.append(f"CDI specs outlive checkpoint: {sorted(orphans)}")

            # Kubelet-style faultless replay: re-issue both RPCs; the
            # pipeline must be idempotent from ANY crash image and
            # converge to the canonical final state.
            res = state2.prepare_batch(
                [claims["ca"], claims["cb"], claims["cc"]])
            for uid, r in sorted(res.items()):
                if r.error:
                    v.append(f"replay prepare of {uid} failed: {r.error}")
            errs = state2.unprepare_batch(
                [claims["ca"]["metadata"]["uid"]])
            err = errs[claims["ca"]["metadata"]["uid"]]
            if err is not None:
                v.append(f"replay unprepare failed: {err}")
            final = state2.checkpoint_snapshot()
            want = {claims["cb"]["metadata"]["uid"],
                    claims["cc"]["metadata"]["uid"]}
            if set(final.claims) != want:
                v.append(f"replay converged to {sorted(final.claims)}, "
                         f"expected {sorted(want)}")
            for uid, pc in final.claims.items():
                if pc.state != PREPARE_COMPLETED:
                    v.append(f"replay left {uid} {pc.state}")
            specs = set(cdi2.list_claim_uids())
            if specs != want:
                v.append(f"replay CDI specs {sorted(specs)} != "
                         f"{sorted(want)}")
            # Span closure INCLUDING crash-recovery replays: the crash
            # unwound prepare_batch through its finally (spans
            # abandoned, never leaked), and the replay closed its own.
            v.extend(_open_span_violations(ctx["trace_snap"]))
            return v
        finally:
            if state2 is not None:
                state2.close()
            shutil.rmtree(tmp, ignore_errors=True)


# ---------------------------------------------------------------------------
# quarantine-crash: the quarantine ledger's journal ops crash-enumerated
# ---------------------------------------------------------------------------

class QuarantineCrashScenario:
    """The quarantine ladder's durable ops (SURVEY §18) under the crash
    enumerator, INTERLEAVED with a real claim lifecycle so quarantine
    snapshots and claim upsert/remove deltas coexist in one journal:
    a claim prepares, two chips flap to graduation (journal append +
    group sync each), an operator clear follows, the claim unprepares —
    then a crash after EVERY durable op in every variant. Recovery
    invariants: the rebuilt DeviceState always comes up; an
    externalized transition (the call RETURNED) is durable — quarantine
    AND claim alike; a crash can never half-quarantine; and the
    faultless replay converges to the canonical final state from ANY
    crash image."""

    name = "quarantine-crash"

    def setup(self) -> Dict:
        from tpu_dra.cdi.handler import CDIHandler
        from tpu_dra.native.tpuinfo import FakeBackend, default_fake_chips
        from tpu_dra.tpuplugin.checkpoint import CheckpointManager
        from tpu_dra.tpuplugin.device_state import DeviceState

        tmp = tempfile.mkdtemp(prefix="drmc-quar-")
        backend = FakeBackend(default_fake_chips(4, "v5p",
                                                 slice_id="drmc"))
        cdi = CDIHandler(os.path.join(tmp, "cdi"),
                         driver_root=os.path.join(tmp, "drv"))
        state = DeviceState(
            backend=backend, cdi=cdi,
            checkpoints=CheckpointManager(os.path.join(tmp, "plugin")),
            driver_name=_DRIVER, node_name=_POOL, async_cdi=False,
            quarantine_threshold=2, quarantine_window_s=3600.0)
        uuids = {c.index: c.uuid for c in backend.chips()}
        return {"tmp": tmp, "state": state, "uuids": uuids,
                "claims": {"qa": _mk_claim("qa", ["chip-2"], rv=1)},
                "externalized": {}, "trace_snap": _trace_snapshot()}

    @staticmethod
    def _ladder(state, chip: int) -> None:
        """Two flaps: transition in, recover, transition in — crosses
        threshold=2 and graduates on the second mark_unhealthy."""
        state.mark_unhealthy(chip)
        state.mark_healthy(chip)
        state.mark_unhealthy(chip)

    def body(self, ctx) -> None:
        state, uuids = ctx["state"], ctx["uuids"]
        ext: Dict[str, str] = ctx["externalized"]
        uid_qa = ctx["claims"]["qa"]["metadata"]["uid"]
        res = state.prepare_batch([ctx["claims"]["qa"]])
        ext["claim"] = "failed" if res[uid_qa].error else "completed"
        self._ladder(state, 0)
        if uuids[0] in state.quarantined_chips():
            ext[uuids[0]] = "quarantined"
        self._ladder(state, 1)
        if uuids[1] in state.quarantined_chips():
            ext[uuids[1]] = "quarantined"
        # Once the operator clear is REQUESTED the record is going away
        # by intent: a crash may land on either side of its removal, so
        # the survival invariant relaxes until the call returns (the
        # same relaxation as batch-prepare-crash's unprepare-requested).
        ext[uuids[0]] = "clear-requested"
        state.clear_quarantine(0)
        ext[uuids[0]] = "cleared"
        ext["claim"] = "unprepare-requested"
        errs = state.unprepare_batch([uid_qa])
        if errs[uid_qa] is None:
            ext["claim"] = "unprepared"

    def dispose(self, ctx) -> None:
        ctx["state"].close()

    def recover_and_check(self, ctx) -> List[str]:
        from tpu_dra.cdi.handler import CDIHandler
        from tpu_dra.native.tpuinfo import FakeBackend, default_fake_chips
        from tpu_dra.tpuplugin.checkpoint import CheckpointManager
        from tpu_dra.tpuplugin.device_state import DeviceState

        tmp, uuids = ctx["tmp"], ctx["uuids"]
        ext: Dict[str, str] = ctx["externalized"]
        v: List[str] = []
        state2 = None
        try:
            backend = FakeBackend(default_fake_chips(4, "v5p",
                                                     slice_id="drmc"))
            try:
                state2 = DeviceState(
                    backend=backend,
                    cdi=CDIHandler(os.path.join(tmp, "cdi"),
                                   driver_root=os.path.join(tmp, "drv")),
                    checkpoints=CheckpointManager(
                        os.path.join(tmp, "plugin")),
                    driver_name=_DRIVER, node_name=_POOL,
                    async_cdi=False,
                    quarantine_threshold=2, quarantine_window_s=3600.0)
            except Exception as e:  # noqa: BLE001 — THE invariant
                return [f"recovery failed to start: {e}"]
            from tpu_dra.tpuplugin.checkpoint import PREPARE_COMPLETED

            uid_qa = ctx["claims"]["qa"]["metadata"]["uid"]
            q = set(state2.quarantined_chips())
            for uuid, status in sorted(ext.items()):
                if status == "quarantined" and uuid not in q:
                    v.append(f"externalized quarantine of {uuid} lost")
                elif status == "cleared" and uuid in q:
                    v.append(f"externalized clear of {uuid} "
                             "resurrected as quarantined")
                # "clear-requested": mid-clear crash — quarantined or
                # cleared are BOTH legal images; replay converges below.
            pc = state2.checkpoint_snapshot().claims.get(uid_qa)
            claim_ext = ext.get("claim")
            if claim_ext == "completed" and (
                    pc is None or pc.state != PREPARE_COMPLETED):
                v.append("externalized prepare lost alongside the "
                         "quarantine journal ops")
            elif claim_ext == "unprepared" and pc is not None:
                v.append("externalized unprepare resurrected")
            elif claim_ext == "unprepare-requested" and pc is not None \
                    and pc.state != PREPARE_COMPLETED:
                v.append(f"in-flight unprepare left {uid_qa} {pc.state}")
            # Half-quarantine is impossible by construction: a chip is
            # quarantined iff its ledger record exists; verify the
            # ledger and the publish exclusion agree.
            names = {d["name"] for d in state2.healthy_devices()}
            for uuid in q:
                leaked = [n for n in names
                          if state2.allocatable[n].chip.uuid == uuid]
                if leaked:
                    v.append(f"quarantined chip {uuid} still "
                             f"published: {leaked}")

            # Faultless replay: the same lifecycle from ANY crash image
            # must converge to {chip1 quarantined, chip0 clear, no
            # claims}.
            res = state2.prepare_batch([ctx["claims"]["qa"]])
            if res[uid_qa].error:
                v.append(f"replay prepare failed: {res[uid_qa].error}")
            self._ladder(state2, 0)
            self._ladder(state2, 1)
            state2.clear_quarantine(0)
            errs = state2.unprepare_batch([uid_qa])
            if errs[uid_qa] is not None:
                v.append(f"replay unprepare failed: {errs[uid_qa]}")
            final = set(state2.quarantined_chips())
            if final != {uuids[1]}:
                v.append(f"replay converged to {sorted(final)}, "
                         f"expected {{{uuids[1]}}}")
            if state2.checkpoint_snapshot().claims:
                v.append("replay left checkpoint claims behind")
            names = {d["name"] for d in state2.healthy_devices()}
            if any(state2.allocatable[n].chip.uuid == uuids[1]
                   for n in names):
                v.append("replayed quarantine of chip 1 still published")
            v.extend(_open_span_violations(ctx["trace_snap"]))
            return v
        finally:
            if state2 is not None:
                state2.close()
            shutil.rmtree(tmp, ignore_errors=True)


INTERLEAVING_SCENARIOS = {
    SchedChurnScenario.name: SchedChurnScenario,
    ShardDispatchScenario.name: ShardDispatchScenario,
    BatchPrepareScenario.name: BatchPrepareScenario,
    EvictChurnScenario.name: EvictChurnScenario,
    TakeoverScenario.name: TakeoverScenario,
    RacyIndexScenario.name: RacyIndexScenario,
    StaleReadProbeScenario.name: StaleReadProbeScenario,
    StaleReadFixedScenario.name: StaleReadFixedScenario,
}

# Scenarios the CI gate runs (racy-index and stale-read-probe are the
# negative fixtures: they are SUPPOSED to violate, so they live in
# tests, not the gate; stale-read-fixed keeps the REVALIDATES protocol
# dynamically proven).
GATE_SCENARIOS = (SchedChurnScenario.name, ShardDispatchScenario.name,
                  BatchPrepareScenario.name,
                  EvictChurnScenario.name, StaleReadFixedScenario.name,
                  TakeoverScenario.name)

CRASH_SCENARIOS = {
    BatchPrepareCrashScenario.name: BatchPrepareCrashScenario,
    QuarantineCrashScenario.name: QuarantineCrashScenario,
}
