"""drmc controlled scheduler: deterministic interleaving of real threads.

The substrate both drmc engines share (SURVEY §13). Real project code
runs on real threads, but every thread a scenario spawns is *gated*: it
may only execute between two yield points when the cooperative
scheduler has granted it the next step, and at most ONE controlled
thread runs at any instant. Yield points are the concurrency
primitives' own instrumentation seams — no scenario-side annotations:

- witnessed ``Lock``/``RLock`` acquire/release
  (``infra/lockwitness.set_yield_hook``; drmc installs the witness, so
  every lock tpu_dra code creates during a scenario is both modeled
  here and checked for order cycles there);
- ``infra/workqueue`` enqueue/pop (labeled with the item key — the
  DPOR conflict label) and its condition wait/notify, which drmc
  *virtualizes*: a controlled wait releases the queue lock through the
  instrumented path, parks in the scheduler's model, and re-acquires
  on wakeup, never touching the real ``Condition`` waiter list.

Because the scheduler knows, from the model, which locks are held and
by whom, a granted ``lock.acquire`` can never block for real: a thread
is only schedulable into an acquire when the model says the lock is
free (or self-held, for reentry). Timed condition waits are modeled as
"wakes when notified, or by timeout as a last resort" — a waiting task
becomes schedulable on its own only when nothing else can run, which
keeps bounded scenarios terminating under every schedule while
preserving the spurious-wakeup-tolerant loop contract real timed waits
have.

A run records its full decision trace (chosen task id at every grant).
Feeding the same trace back replays the identical execution — the
replay seam hack/drmc.sh prints on violation. Deadlocks (every live
task blocked on a held lock) and livelocks (step budget exhausted) are
reported as violations with each task's pending operation.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from tpu_dra.infra import lockwitness, workqueue

# States a controlled task moves through.
_STARTING = "starting"  # thread spawned, has not parked yet
_PARKED = "parked"      # at a yield point, waiting for a grant
_RUNNING = "running"    # granted; executing real code
_DONE = "done"
_FAILED = "failed"      # its function raised


class ScheduleError(Exception):
    """Harness-level failure (replay divergence, handshake timeout) —
    distinct from a scenario invariant violation."""


class _Aborted(BaseException):
    """Unwinds a task thread when the scheduler aborts a run; a
    BaseException so scenario code's ``except Exception`` cannot eat
    it (mirrors how a real thread dies with its process)."""


@dataclass(frozen=True)
class Op:
    """One pending operation at a yield point."""
    kind: str                      # lock.acquire|lock.release|queue.add|...
    key: Optional[str]             # lock class (creation site) / queue key
    instance: Optional[int]        # per-run lock/cond identity
    blocking: bool = True

    def conflict_key(self) -> Optional[Tuple[str, str]]:
        """The DPOR-lite conflict label: two pending ops are reorder-
        relevant only when they touch the same lock class or the same
        queue key (ISSUE 6's stated reduction rule). Releases carry no
        label — their order against a same-lock acquire is already
        forced by the enabledness model."""
        if self.kind == "lock.acquire":
            return ("lock", self.key or "")
        if self.kind in ("queue.add", "queue.get"):
            return ("queue", self.key or "")
        if self.kind in ("cond.wait", "cond.notify"):
            return ("cond", self.key or "")
        return None

    def describe(self) -> str:
        return f"{self.kind}({self.key})" if self.key else self.kind


@dataclass
class _Task:
    tid: int
    name: str
    fn: Callable[[], None]
    thread: Optional[threading.Thread] = None
    gate: threading.Event = field(default_factory=threading.Event)
    state: str = _STARTING
    pending: Op = field(default_factory=lambda: Op("task.start", None, None))
    notified: bool = False         # cond.wait wakeup posted
    error: Optional[str] = None


@dataclass
class RunResult:
    trace: List[int] = field(default_factory=list)   # chosen tid per grant
    ops: List[str] = field(default_factory=list)     # "tid:op" per grant
    # (step index, untried-alternative tids) — the explorer's backtrack
    # points, computed under the DPOR-lite conflict rule.
    branches: List[Tuple[int, List[int]]] = field(default_factory=list)
    violations: List[str] = field(default_factory=list)
    steps: int = 0
    complete: bool = False


def scenario_lock():
    """A Lock allocated from tpu_dra code, so the witness's creation-
    site filter instruments it: scenario fixtures living under tests/
    (whose own allocations the witness deliberately ignores) get their
    locks modeled by creating them here. All such locks share one
    creation-site class; the scheduler's model is per-instance, so
    enabledness and deadlock detection are unaffected."""
    return threading.Lock()


class _QueueHooks:
    """The workqueue-facing half of the seam (workqueue.set_drmc_hooks)."""

    def __init__(self, sched: "CooperativeScheduler"):
        self._sched = sched

    def yield_op(self, kind: str, key: str) -> None:
        self._sched.simple_yield(kind, key)

    def wait(self, cond, timeout: float) -> bool:
        return self._sched.controlled_wait(cond)

    def notify(self, cond, all_waiters: bool) -> bool:
        return self._sched.controlled_notify(cond, all_waiters)


class CooperativeScheduler:
    """One controlled run. Usage: ``spawn()`` tasks, then ``run()`` —
    which installs the yield hooks, drives the schedule to completion,
    uninstalls, and returns the :class:`RunResult`."""

    # A controlled thread failing to reach its next yield point within
    # this window means scenario code blocked outside the model (a raw
    # lock, real I/O stall) — abort loudly rather than hang CI.
    HANDSHAKE_TIMEOUT_S = 30.0

    def __init__(self, schedule: Optional[List[int]] = None,
                 max_steps: int = 5000):
        self._schedule = list(schedule or [])
        self._max_steps = max_steps
        self._tasks: List[_Task] = []
        self._by_thread: Dict[int, _Task] = {}
        self._sched_evt = threading.Event()   # a task parked or finished
        self._aborted = False
        # Lock model: instance id -> [owner tid, depth].
        self._owners: Dict[int, List[int]] = {}
        self.result = RunResult()

    # -- scenario surface ----------------------------------------------------

    def spawn(self, name: str, fn: Callable[[], None]) -> int:
        """Register a task. Threads start parked at ``task.start``;
        nothing executes until run() grants it."""
        task = _Task(tid=len(self._tasks), name=name, fn=fn)
        self._tasks.append(task)
        return task.tid

    def run(self) -> RunResult:
        hooks = _QueueHooks(self)
        lockwitness.set_yield_hook(self._lock_hook)
        workqueue.set_drmc_hooks(hooks)
        try:
            for task in self._tasks:
                task.thread = threading.Thread(
                    target=self._task_main, args=(task,),
                    name=f"drmc-{task.name}", daemon=True)
                task.thread.start()
            self._loop()
        finally:
            lockwitness.clear_yield_hook()
            workqueue.clear_drmc_hooks()
            self._release_all()
            for task in self._tasks:
                if task.thread is not None:
                    task.thread.join(timeout=5.0)
                    if task.thread.is_alive():
                        self.result.violations.append(
                            f"harness: task {task.name} did not exit")
        return self.result

    # -- task side -----------------------------------------------------------

    def _task_main(self, task: _Task) -> None:
        self._by_thread[task.thread.ident] = task
        try:
            self._park(task)          # pending == task.start
            task.fn()
            task.state = _DONE
        except _Aborted:
            task.state = _DONE
        except BaseException as e:  # noqa: BLE001 — scenario bug/violation
            task.state = _FAILED
            task.error = f"{type(e).__name__}: {e}"
        finally:
            self._drop_owned(task)
            self._sched_evt.set()

    def _current(self) -> Optional[_Task]:
        return self._by_thread.get(threading.get_ident())

    def _park(self, task: _Task, op: Optional[Op] = None) -> None:
        """Hand control to the scheduler; returns once granted. Order
        matters: the gate is cleared and the pending op published
        BEFORE the parked state becomes visible — the scheduler may
        grant the instant it sees _PARKED, and a clear() after that
        grant would drop it."""
        task.gate.clear()
        if op is not None:
            task.pending = op
        task.state = _PARKED
        self._sched_evt.set()
        task.gate.wait()
        if self._aborted:
            raise _Aborted()

    # -- yield-point entry (lockwitness hook) --------------------------------

    def _lock_hook(self, kind: str, key: str, instance: int,
                   blocking: bool) -> None:
        task = self._current()
        if task is None or task.state == _DONE:
            return  # uncontrolled thread (scenario setup, drains)
        if kind == "lock.acquired":
            own = self._owners.get(instance)
            if own is not None and own[0] == task.tid:
                own[1] += 1           # RLock reentry
            else:
                self._owners[instance] = [task.tid, 1]
            return                    # bookkeeping only, no yield
        self._park(task, Op(kind, key, instance, blocking))
        if kind in ("lock.release", "lock.release_save"):
            own = self._owners.get(instance)
            if own is not None and own[0] == task.tid:
                if kind == "lock.release_save" or own[1] <= 1:
                    del self._owners[instance]
                else:
                    own[1] -= 1

    # -- yield-point entry (workqueue hooks) ---------------------------------

    def simple_yield(self, kind: str, key: Optional[str]) -> None:
        task = self._current()
        if task is None:
            return
        self._park(task, Op(kind, key, None))

    @staticmethod
    def _cond_identity(cond) -> Tuple[str, int]:
        lock = cond._lock
        key = getattr(lock, "_key", None)
        if key is None:
            raise ScheduleError(
                "controlled wait on an unwitnessed condition lock — the "
                "queue must be created while drmc's witness is installed")
        return key, id(lock)

    def controlled_wait(self, cond) -> bool:
        task = self._current()
        if task is None:
            return False              # uncontrolled thread: real wait
        key, inst = self._cond_identity(cond)
        # Release through the instrumented path (its own yield point +
        # model release), park as a waiter, re-acquire when granted.
        cond._lock.release()
        self._park(task, Op("cond.wait", key, inst))
        task.notified = False
        cond._lock.acquire()  # dralint: ignore[R11] — the controlled scheduler IS the instrument: it re-enters a parked waiter's Condition lock by design; the witness models the inner lock itself
        return True

    def controlled_notify(self, cond, all_waiters: bool) -> bool:
        task = self._current()
        if task is None:
            return False
        key, inst = self._cond_identity(cond)
        self._park(task, Op("cond.notify", key, inst))
        waiters = [t for t in self._tasks
                   if t.state == _PARKED and t.pending.kind == "cond.wait"
                   and t.pending.instance == inst and not t.notified]
        for t in (waiters if all_waiters else waiters[:1]):
            t.notified = True
        return True

    # -- scheduler loop ------------------------------------------------------

    def _live(self) -> List[_Task]:
        return [t for t in self._tasks if t.state not in (_DONE, _FAILED)]

    def _enabled(self) -> List[_Task]:
        parked = [t for t in self._tasks if t.state == _PARKED]
        out = []
        for t in parked:
            op = t.pending
            if op.kind == "lock.acquire" and op.blocking:
                own = self._owners.get(op.instance)
                if own is not None and own[0] != t.tid:
                    continue          # held by another task
            if op.kind == "cond.wait" and not t.notified:
                continue              # woken by notify — or timeout, below
            out.append(t)
        if not out:
            # Timeout wakeups as last resort: a timed wait CAN fire, but
            # scheduling it only when nothing else is runnable keeps
            # bounded scenarios from spinning through infinite schedules.
            out = [t for t in parked if t.pending.kind == "cond.wait"]
        return out

    def _wait_all_parked(self) -> None:
        """Block until no controlled task is in flight — the single
        granted task parked again / finished, and every fresh thread
        reached its initial park (a STARTING task is about to park, so
        treating it as runnable would double-grant its first step)."""
        def in_flight():
            return any(t.state in (_RUNNING, _STARTING)
                       for t in self._tasks)
        while in_flight():
            self._sched_evt.clear()
            if in_flight():
                if not self._sched_evt.wait(self.HANDSHAKE_TIMEOUT_S):
                    running = [t.name for t in self._tasks
                               if t.state in (_RUNNING, _STARTING)]
                    raise ScheduleError(
                        f"task(s) {running} never reached a yield point "
                        f"within {self.HANDSHAKE_TIMEOUT_S}s (blocked "
                        "outside the model?)")

    def _loop(self) -> None:
        res = self.result
        try:
            while True:
                self._wait_all_parked()
                if not self._live():
                    res.complete = True
                    break
                enabled = self._enabled()
                if not enabled:
                    res.violations.append(
                        "deadlock: all live tasks blocked — "
                        + "; ".join(
                            f"{t.name} at {t.pending.describe()}"
                            for t in self._live()))
                    break
                if res.steps >= self._max_steps:
                    res.violations.append(
                        f"livelock: schedule exceeded {self._max_steps} "
                        "steps without terminating")
                    break
                chosen = self._choose(enabled)
                self._record(chosen, enabled)
                if chosen.pending.kind == "cond.wait":
                    chosen.notified = True  # grant IS the (timeout) wakeup
                res.steps += 1
                # Flip to RUNNING here, not on the task thread: the next
                # _wait_all_parked must already see the grant in flight.
                chosen.state = _RUNNING
                chosen.gate.set()
        except ScheduleError as e:
            res.violations.append(f"harness: {e}")
        finally:
            failed = [t for t in self._tasks if t.state == _FAILED]
            for t in failed:
                res.violations.append(f"task {t.name} raised: {t.error}")

    def _choose(self, enabled: List[_Task]) -> _Task:
        step = len(self.result.trace)
        if step < len(self._schedule):
            want = self._schedule[step]
            for t in enabled:
                if t.tid == want:
                    return t
            raise ScheduleError(
                f"replay divergence at step {step}: scheduled tid {want} "
                f"not enabled (enabled: {[t.tid for t in enabled]})")
        return min(enabled, key=lambda t: t.tid)

    def _record(self, chosen: _Task, enabled: List[_Task]) -> None:
        res = self.result
        step = len(res.trace)
        res.trace.append(chosen.tid)
        res.ops.append(f"{chosen.name}:{chosen.pending.describe()}")
        if len(enabled) > 1 and step >= len(self._schedule):
            ck = chosen.pending.conflict_key()
            alts = [t.tid for t in enabled if t is not chosen
                    and (t.pending.kind == "task.start"  # next op unknown:
                         #   branch conservatively or start order is fixed
                         or (ck is not None
                             and t.pending.conflict_key() == ck))]
            if alts:
                res.branches.append((step, alts))

    # -- teardown ------------------------------------------------------------

    def _drop_owned(self, task: _Task) -> None:
        for inst in [i for i, own in self._owners.items()
                     if own[0] == task.tid]:
            # A task that exits while owning a modeled lock left the
            # REAL lock held too — the deadlock it causes for siblings
            # is reported by the enabledness model; drop the entry so
            # teardown doesn't wedge.
            del self._owners[inst]

    def _release_all(self) -> None:
        self._aborted = True
        for t in self._tasks:
            t.gate.set()
