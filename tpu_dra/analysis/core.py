"""dralint core: AST lint framework for project invariants.

The reference driver keeps its heavily threaded control plane honest
with `go vet`, golangci-lint and `go test -race` (Makefile:96). This
package is the Python reproduction's analog, except the rules are not
generic style checks — they are THIS project's concurrency and
ownership invariants (SURVEY §§8-12), machine-checked:

- visitor-based rules over ``ast`` trees (one parse per file, every
  rule sees every module);
- findings carry ``file:line:col``, a stable rule id, and a message;
- ``# dralint: ignore[R2]`` (or bare ``# dralint: ignore``) on the
  finding's line or the line directly above suppresses it — the
  suppression count is reported, so waivers stay visible;
- human (``path:line:col: Rn message``) and ``--json`` output;
- cross-file rules (orphan detection) run in a ``finalize`` phase
  after every module has been scanned.

Registries (fault sites, the metric catalog, feature-gate names) are
parsed from the infra modules' ASTs, not imported — linting must not
execute project code or depend on import-time side effects.
"""

from __future__ import annotations

import ast
import io
import json
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Set

_SUPPRESS_RE = re.compile(
    r"#\s*dralint:\s*ignore(?:\[(?P<rules>[^\]]*)\])?")


@dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a source location."""
    rule: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_dict(self) -> Dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message}


@dataclass
class Module:
    """One parsed source file plus its suppression map."""
    path: Path
    relpath: str          # repo-root-relative, for stable output
    source: str
    tree: ast.AST
    # line -> None (suppress all rules) or the set of suppressed rule ids
    suppressions: Dict[int, Optional[Set[str]]] = field(default_factory=dict)

    @property
    def is_test(self) -> bool:
        parts = Path(self.relpath).parts
        return "tests" in parts or Path(self.relpath).name.startswith("test_")

    @property
    def is_chaos(self) -> bool:
        return "chaos" in Path(self.relpath).name

    def suppressed(self, rule: str, line: int) -> bool:
        """A finding at `line` is waived by an ignore comment on the
        same line or the line directly above it."""
        for ln in (line, line - 1):
            rules = self.suppressions.get(ln, _MISSING)
            if rules is _MISSING:
                continue
            if rules is None or rule in rules:
                return True
        return False


_MISSING = object()


def _parse_suppressions(source: str) -> Dict[int, Optional[Set[str]]]:
    out: Dict[int, Optional[Set[str]]] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            raw = m.group("rules")
            if raw is None:
                out[tok.start[0]] = None
            else:
                rules = {r.strip() for r in raw.split(",") if r.strip()}
                prev = out.get(tok.start[0], _MISSING)
                if prev is None:
                    continue  # bare ignore on the same line already wins
                merged = rules if prev is _MISSING else (prev | rules)
                out[tok.start[0]] = merged
    except (tokenize.TokenError, IndentationError):
        pass  # unparseable comments: no suppressions, findings stand
    return out


def parse_module(path: Path, root: Path) -> Optional[Module]:
    source = path.read_text(encoding="utf-8")
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError:
        return None  # compileall (hack/lint.sh) owns syntax errors
    try:
        rel = str(path.relative_to(root))
    except ValueError:
        rel = str(path)
    return Module(path=path, relpath=rel, source=source, tree=tree,
                  suppressions=_parse_suppressions(source))


# ---------------------------------------------------------------------------
# Project registries (parsed, never imported)
# ---------------------------------------------------------------------------

@dataclass
class ProjectContext:
    """Shared state for a lint run: the project registries plus anchors
    for cross-file orphan findings."""
    root: Path
    fault_sites: Dict[str, int] = field(default_factory=dict)   # site -> line
    fault_sites_path: str = ""
    metric_catalog: Dict[str, int] = field(default_factory=dict)
    metric_catalog_path: str = ""
    gate_names: Set[str] = field(default_factory=set)
    # Relpaths this run scanned. Orphan rules (R4/R5) only report
    # registry entries as unused when the registry's own file was in
    # view — a single-file lint is not evidence of project-wide orphans.
    scanned: Set[str] = field(default_factory=set)

    @classmethod
    def load(cls, root: Path) -> "ProjectContext":
        ctx = cls(root=root)
        faults = root / "tpu_dra" / "infra" / "faults.py"
        if faults.exists():
            ctx.fault_sites_path = str(faults.relative_to(root))
            ctx.fault_sites = _dict_literal_keys(faults, "SITES")
        metrics = root / "tpu_dra" / "infra" / "metrics.py"
        if metrics.exists():
            ctx.metric_catalog_path = str(metrics.relative_to(root))
            ctx.metric_catalog = _dict_literal_keys(metrics, "METRICS_CATALOG")
        gates = root / "tpu_dra" / "infra" / "featuregates.py"
        if gates.exists():
            ctx.gate_names = _string_constants(gates)
        return ctx


def _dict_literal_keys(path: Path, name: str) -> Dict[str, int]:
    """String keys (and their line numbers) of the module-level dict
    literal assigned to `name`."""
    tree = ast.parse(path.read_text(encoding="utf-8"))
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for t in targets:
            if (isinstance(t, ast.Name) and t.id == name
                    and isinstance(node.value, ast.Dict)):
                return {k.value: k.lineno for k in node.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)}
    return {}


def _string_constants(path: Path) -> Set[str]:
    """Module-level ``Name = "Name"`` assignments — the feature-gate
    constant idiom (featuregates.py)."""
    tree = ast.parse(path.read_text(encoding="utf-8"))
    out: Set[str] = set()
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
                and node.targets[0].id == node.value.value):
            out.add(node.value.value)
    return out


# ---------------------------------------------------------------------------
# Rule registry
# ---------------------------------------------------------------------------

class Rule:
    """One lint rule. ``scan`` runs per module; ``finalize`` once after
    all modules (cross-file orphan checks). Rules are instantiated per
    run — they may keep collection state between scan and finalize."""

    rule_id: str = ""
    title: str = ""

    def scan(self, module: Module, ctx: ProjectContext) -> Iterator[Finding]:
        return iter(())

    def finalize(self, ctx: ProjectContext) -> Iterator[Finding]:
        return iter(())


_RULE_CLASSES: List[type] = []


def register(cls: type) -> type:
    _RULE_CLASSES.append(cls)
    return cls


def all_rules() -> List[Rule]:
    return [cls() for cls in _RULE_CLASSES]


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------

@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    files: int = 0
    # The context the run was performed against (registries + scanned
    # set) — lets callers (e.g. --sites-report) reuse the parse.
    ctx: Optional["ProjectContext"] = None

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> Dict:
        return {"files": self.files,
                "findings": [f.to_dict() for f in self.findings],
                "suppressed": [f.to_dict() for f in self.suppressed]}


def iter_python_files(paths: Sequence[Path]) -> Iterator[Path]:
    seen: Set[Path] = set()
    for p in paths:
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if "__pycache__" in f.parts or f in seen:
                    continue
                seen.add(f)
                yield f
        elif p.suffix == ".py" and p not in seen:
            seen.add(p)
            yield p


def find_root(start: Path) -> Path:
    """The repo root: the nearest ancestor holding the infra registries."""
    cur = start.resolve()
    if cur.is_file():
        cur = cur.parent
    while True:
        if (cur / "tpu_dra" / "infra" / "faults.py").exists():
            return cur
        if cur.parent == cur:
            return start.resolve() if start.is_dir() else start.parent
        cur = cur.parent


def run(paths: Sequence[Path], root: Optional[Path] = None,
        rules: Optional[Iterable[Rule]] = None,
        rule_ids: Optional[Set[str]] = None) -> Report:
    paths = [Path(p) for p in paths]
    root = Path(root) if root else find_root(paths[0] if paths else Path("."))
    ctx = ProjectContext.load(root)
    active = list(rules) if rules is not None else all_rules()
    if rule_ids:
        active = [r for r in active if r.rule_id in rule_ids]
    report = Report(ctx=ctx)
    modules: List[Module] = []
    for f in iter_python_files(paths):
        mod = parse_module(f, root)
        if mod is not None:
            modules.append(mod)
    report.files = len(modules)
    ctx.scanned = {m.relpath for m in modules}
    for mod in modules:
        for rule in active:
            for finding in rule.scan(mod, ctx):
                if mod.suppressed(finding.rule, finding.line):
                    report.suppressed.append(finding)
                else:
                    report.findings.append(finding)
    by_rel = {m.relpath: m for m in modules}
    for rule in active:
        for finding in rule.finalize(ctx):
            mod = by_rel.get(finding.path)
            if mod is not None and mod.suppressed(finding.rule, finding.line):
                report.suppressed.append(finding)
            else:
                report.findings.append(finding)
    report.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    report.suppressed.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return report


def lint_source(source: str, relpath: str = "fixture.py",
                ctx: Optional[ProjectContext] = None,
                rule_ids: Optional[Set[str]] = None) -> List[Finding]:
    """Lint a source string (the test seam): returns UNSUPPRESSED
    findings, using a synthetic context unless one is given."""
    ctx = ctx or ProjectContext(root=Path("."))
    tree = ast.parse(source)
    mod = Module(path=Path(relpath), relpath=relpath, source=source,
                 tree=tree, suppressions=_parse_suppressions(source))
    # The test seam acts as a full-project run: orphan rules see the
    # registries as in-view so fixtures can exercise both directions.
    ctx.scanned = ({mod.relpath, ctx.fault_sites_path,
                    ctx.metric_catalog_path} | ctx.scanned)
    out: List[Finding] = []
    for rule in all_rules():
        if rule_ids and rule.rule_id not in rule_ids:
            continue
        for finding in rule.scan(mod, ctx):
            if not mod.suppressed(finding.rule, finding.line):
                out.append(finding)
        for finding in rule.finalize(ctx):
            if (finding.path != mod.relpath
                    or not mod.suppressed(finding.rule, finding.line)):
                out.append(finding)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def render(report: Report, as_json: bool = False,
           show_suppressed: bool = False) -> str:
    if as_json:
        return json.dumps(report.to_dict(), indent=2)
    lines = [f.format() for f in report.findings]
    if show_suppressed:
        lines += [f"{f.format()} (suppressed)" for f in report.suppressed]
    lines.append(f"dralint: {report.files} files, "
                 f"{len(report.findings)} findings, "
                 f"{len(report.suppressed)} suppressed")
    return "\n".join(lines)
