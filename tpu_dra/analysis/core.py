"""dralint core: AST lint framework for project invariants.

The reference driver keeps its heavily threaded control plane honest
with `go vet`, golangci-lint and `go test -race` (Makefile:96). This
package is the Python reproduction's analog, except the rules are not
generic style checks — they are THIS project's concurrency and
ownership invariants (SURVEY §§8-12), machine-checked:

- visitor-based rules over ``ast`` trees (one parse per file, every
  rule sees every module);
- findings carry ``file:line:col``, a stable rule id, and a message;
- ``# dralint: ignore[R2]`` (or bare ``# dralint: ignore``) on the
  finding's line or the line directly above suppresses it — the
  suppression count is reported, so waivers stay visible;
- human (``path:line:col: Rn message``) and ``--json`` output;
- cross-file rules (orphan detection) run in a ``finalize`` phase
  after every module has been scanned.

Registries (fault sites, the metric catalog, feature-gate names) are
parsed from the infra modules' ASTs, not imported — linting must not
execute project code or depend on import-time side effects.
"""

from __future__ import annotations

import ast
import io
import json
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import (
    Dict, Iterable, Iterator, List, Optional, Sequence, Set, Tuple,
)

from tpu_dra.infra.metrics import DefaultRegistry as _METRICS

# Lint observability (METRICS_CATALOG, R5-checked like every other
# metric): findings emitted and per-file cache hits, so CI can trend
# both the invariant debt and the incremental cache's effectiveness.
_LINT_FINDINGS = _METRICS.counter(
    "tpu_dra_lint_findings_total",
    "dralint findings emitted across runs in this process")
_LINT_CACHE_HITS = _METRICS.counter(
    "tpu_dra_lint_cache_hits_total",
    "dralint per-file result-cache hits (stat or content-hash tier)")

_SUPPRESS_RE = re.compile(
    r"#\s*dralint:\s*ignore(?:\[(?P<rules>[^\]]*)\])?(?P<rest>[^#]*)")

# A suppression is JUSTIFIED when the comment carries prose beyond the
# ignore tag (``# dralint: ignore[R7] — rollback is the caller's``).
# hack/lint.sh gates unjustified suppressions to zero, so the waiver
# count can never grow without a visible reason in the diff.
_JUSTIFY_MIN_CHARS = 3


@dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a source location."""
    rule: str
    path: str
    line: int
    col: int
    message: str

    def format(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} {self.message}"

    def to_dict(self) -> Dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message}


@dataclass
class Module:
    """One parsed source file plus its suppression map."""
    path: Path
    relpath: str          # repo-root-relative, for stable output
    source: str
    tree: ast.AST
    # line -> None (suppress all rules) or the set of suppressed rule ids
    suppressions: Dict[int, Optional[Set[str]]] = field(default_factory=dict)
    # line -> the ignore comment carries a justification string
    justified: Dict[int, bool] = field(default_factory=dict)

    @property
    def is_test(self) -> bool:
        parts = Path(self.relpath).parts
        return "tests" in parts or Path(self.relpath).name.startswith("test_")

    @property
    def is_chaos(self) -> bool:
        return "chaos" in Path(self.relpath).name

    def suppressed(self, rule: str, line: int) -> bool:
        """A finding at `line` is waived by an ignore comment on the
        same line or the line directly above it."""
        return _lookup_suppressed(self.suppressions, rule, line)

    def suppression_justified(self, rule: str, line: int) -> bool:
        return _lookup_justified(self.suppressions, self.justified,
                                 rule, line)


_MISSING = object()


def _lookup_suppressed(lines: Dict[int, Optional[Set[str]]],
                       rule: str, line: int) -> bool:
    """The one definition of waiver semantics (same line or line
    directly above; None = all rules), shared by parsed modules and the
    result cache's replayed suppression maps — warm and cold runs must
    agree byte for byte."""
    for ln in (line, line - 1):
        rules = lines.get(ln, _MISSING)
        if rules is _MISSING:
            continue
        if rules is None or rule in rules:
            return True
    return False


def _lookup_justified(lines: Dict[int, Optional[Set[str]]],
                      justified: Dict[int, bool],
                      rule: str, line: int) -> bool:
    """Whether the comment that suppresses (rule, line) carries a
    justification string — resolved against the same line-or-above
    comment `_lookup_suppressed` matched."""
    for ln in (line, line - 1):
        rules = lines.get(ln, _MISSING)
        if rules is _MISSING:
            continue
        if rules is None or rule in rules:
            return justified.get(ln, False)
    return False


def _parse_suppressions(
        source: str) -> Tuple[Dict[int, Optional[Set[str]]],
                              Dict[int, bool]]:
    out: Dict[int, Optional[Set[str]]] = {}
    just: Dict[int, bool] = {}
    try:
        tokens = tokenize.generate_tokens(io.StringIO(source).readline)
        for tok in tokens:
            if tok.type != tokenize.COMMENT:
                continue
            m = _SUPPRESS_RE.search(tok.string)
            if not m:
                continue
            rest = (m.group("rest") or "").strip(" \t-—:.,")
            has_reason = len(rest) >= _JUSTIFY_MIN_CHARS
            ln = tok.start[0]
            just[ln] = just.get(ln, False) or has_reason
            raw = m.group("rules")
            if raw is None:
                out[ln] = None
            else:
                rules = {r.strip() for r in raw.split(",") if r.strip()}
                prev = out.get(ln, _MISSING)
                if prev is None:
                    continue  # bare ignore on the same line already wins
                merged = rules if prev is _MISSING else (prev | rules)
                out[ln] = merged
    except (tokenize.TokenError, IndentationError):
        pass  # unparseable comments: no suppressions, findings stand
    return out, just


def parse_module(path: Path, root: Path,
                 source: Optional[str] = None) -> Optional[Module]:
    if source is None:
        source = path.read_text(encoding="utf-8")
    try:
        tree = ast.parse(source, filename=str(path))
    except SyntaxError:
        return None  # compileall (hack/lint.sh) owns syntax errors
    try:
        rel = str(path.relative_to(root))
    except ValueError:
        rel = str(path)
    suppressions, justified = _parse_suppressions(source)
    return Module(path=path, relpath=rel, source=source, tree=tree,
                  suppressions=suppressions, justified=justified)


# ---------------------------------------------------------------------------
# Project registries (parsed, never imported)
# ---------------------------------------------------------------------------

@dataclass
class ProjectContext:
    """Shared state for a lint run: the project registries plus anchors
    for cross-file orphan findings."""
    root: Path
    fault_sites: Dict[str, int] = field(default_factory=dict)   # site -> line
    # site -> declared degradation-helper name (faults.DEGRADATIONS):
    # drflow R15 requires handlers guarding these sites to route there.
    fault_degradations: Dict[str, str] = field(default_factory=dict)
    fault_sites_path: str = ""
    metric_catalog: Dict[str, int] = field(default_factory=dict)
    metric_catalog_path: str = ""
    gate_names: Set[str] = field(default_factory=set)
    # Relpaths this run scanned. Orphan rules (R4/R5) only report
    # registry entries as unused when the registry's own file was in
    # view — a single-file lint is not evidence of project-wide orphans.
    scanned: Set[str] = field(default_factory=set)

    @classmethod
    def load(cls, root: Path) -> "ProjectContext":
        ctx = cls(root=root)
        faults = root / "tpu_dra" / "infra" / "faults.py"
        if faults.exists():
            ctx.fault_sites_path = str(faults.relative_to(root))
            ctx.fault_sites = _dict_literal_keys(faults, "SITES")
            ctx.fault_degradations = _dict_literal_items(
                faults, "DEGRADATIONS")
        metrics = root / "tpu_dra" / "infra" / "metrics.py"
        if metrics.exists():
            ctx.metric_catalog_path = str(metrics.relative_to(root))
            ctx.metric_catalog = _dict_literal_keys(metrics, "METRICS_CATALOG")
        gates = root / "tpu_dra" / "infra" / "featuregates.py"
        if gates.exists():
            ctx.gate_names = _string_constants(gates)
        return ctx


def _dict_literal_keys(path: Path, name: str) -> Dict[str, int]:
    """String keys (and their line numbers) of the module-level dict
    literal assigned to `name`."""
    tree = ast.parse(path.read_text(encoding="utf-8"))
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for t in targets:
            if (isinstance(t, ast.Name) and t.id == name
                    and isinstance(node.value, ast.Dict)):
                return {k.value: k.lineno for k in node.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)}
    return {}


def _dict_literal_items(path: Path, name: str) -> Dict[str, str]:
    """String key -> string value of the module-level dict literal
    assigned to `name` (non-string entries are skipped)."""
    tree = ast.parse(path.read_text(encoding="utf-8"))
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        for t in targets:
            if (isinstance(t, ast.Name) and t.id == name
                    and isinstance(node.value, ast.Dict)):
                return {k.value: v.value
                        for k, v in zip(node.value.keys,
                                        node.value.values)
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                        and isinstance(v, ast.Constant)
                        and isinstance(v.value, str)}
    return {}


def _string_constants(path: Path) -> Set[str]:
    """Module-level ``Name = "Name"`` assignments — the feature-gate
    constant idiom (featuregates.py)."""
    tree = ast.parse(path.read_text(encoding="utf-8"))
    out: Set[str] = set()
    for node in tree.body:
        if (isinstance(node, ast.Assign) and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
                and node.targets[0].id == node.value.value):
            out.add(node.value.value)
    return out


# ---------------------------------------------------------------------------
# Rule registry
# ---------------------------------------------------------------------------

class Rule:
    """One lint rule. ``scan`` runs per module; ``finalize`` once after
    all modules (cross-file orphan checks). Rules are instantiated per
    run — they may keep collection state between scan and finalize.

    Rules with cross-file state additionally speak the FACTS protocol
    so the per-file result cache can skip re-scanning unchanged files:
    ``module_facts()`` (called right after ``scan(module)``) returns the
    JSON-able contribution that module made to the rule's aggregate
    state, and ``absorb_facts`` replays a cached contribution for a
    file the runner did not re-parse. Per-file findings are cached
    separately by the runner; finalize always recomputes."""

    rule_id: str = ""
    title: str = ""
    # Rule ids this rule emits. Most rules emit exactly their own id;
    # a combined pass (raceanalysis R9-R11) declares the full set so
    # --rules filtering keeps working (core also post-filters findings
    # by id, so asking for R10 from a combined rule yields only R10).
    provides: frozenset = frozenset()
    # Cache key this rule's FACTS live under. Defaults to rule_id; a
    # rule that CONSUMES another rule's extraction (drflow R13-R15
    # rides draracer's per-module blob) names that rule's id here so
    # the blob is stored once and replayed to both — absorb_facts gets
    # the shared blob, module_facts should return None (the producing
    # rule already contributed it).
    facts_key: str = ""

    @classmethod
    def provided_ids(cls) -> frozenset:
        return cls.provides or frozenset({cls.rule_id})

    @classmethod
    def facts_id(cls) -> str:
        return cls.facts_key or cls.rule_id

    def scan(self, module: Module, ctx: ProjectContext) -> Iterator[Finding]:
        return iter(())

    def finalize(self, ctx: ProjectContext) -> Iterator[Finding]:
        return iter(())

    def module_facts(self) -> Optional[Dict]:
        return None

    def absorb_facts(self, relpath: str, facts: Dict,
                     ctx: ProjectContext) -> None:
        pass


_RULE_CLASSES: List[type] = []


def register(cls: type) -> type:
    _RULE_CLASSES.append(cls)
    return cls


def all_rules() -> List[Rule]:
    return [cls() for cls in _RULE_CLASSES]


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------

@dataclass
class Report:
    findings: List[Finding] = field(default_factory=list)
    suppressed: List[Finding] = field(default_factory=list)
    files: int = 0
    cache_hits: int = 0
    # Suppressed findings whose ignore comment has no justification
    # string — the lint.sh --require-justified gate.
    unjustified: List[Finding] = field(default_factory=list)
    # Per-rule-class wall-clock seconds (scan accumulated across files
    # + finalize), keyed by the rule's primary id — the --rule-table
    # timing column. Parallel scans bill the pool's wall time to
    # "<scan-pool>" since per-rule attribution dissolves across
    # processes.
    timings: Dict[str, float] = field(default_factory=dict)
    # The context the run was performed against (registries + scanned
    # set) — lets callers (e.g. --sites-report) reuse the parse.
    ctx: Optional["ProjectContext"] = None

    @property
    def ok(self) -> bool:
        return not self.findings

    @staticmethod
    def _by_rule(findings: List[Finding]) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for f in findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return dict(sorted(out.items()))

    def to_dict(self) -> Dict:
        # Per-rule counts ride along so CI can trend suppressions the
        # same way the human formatter surfaces them (ISSUE 9).
        return {"files": self.files,
                "cache_hits": self.cache_hits,
                "findings": [f.to_dict() for f in self.findings],
                "suppressed": [f.to_dict() for f in self.suppressed],
                "findings_by_rule": self._by_rule(self.findings),
                "suppressed_by_rule": self._by_rule(self.suppressed),
                "timings_s": {k: round(v, 4)
                              for k, v in sorted(self.timings.items())},
                "suppressed_unjustified":
                    [f.to_dict() for f in self.unjustified]}


def iter_python_files(paths: Sequence[Path]) -> Iterator[Path]:
    seen: Set[Path] = set()
    for p in paths:
        if p.is_dir():
            for f in sorted(p.rglob("*.py")):
                if "__pycache__" in f.parts or f in seen:
                    continue
                seen.add(f)
                yield f
        elif p.suffix == ".py" and p not in seen:
            seen.add(p)
            yield p


def find_root(start: Path) -> Path:
    """The repo root: the nearest ancestor holding the infra registries."""
    cur = start.resolve()
    if cur.is_file():
        cur = cur.parent
    while True:
        if (cur / "tpu_dra" / "infra" / "faults.py").exists():
            return cur
        if cur.parent == cur:
            return start.resolve() if start.is_dir() else start.parent
        cur = cur.parent


# ---------------------------------------------------------------------------
# Per-file result cache
# ---------------------------------------------------------------------------
# Whole-tree lint re-parses ~190 files per run even though almost none
# changed between runs; hack/lint.sh runs on every tier. Entries are
# keyed by (path, mtime_ns, size) and the cache as a whole by a
# rules-version hash (the analyzer's own sources) plus a registries
# hash (faults/metrics/featuregates — their content changes the verdict
# for OTHER files, e.g. an unknown-site finding). An entry stores the
# file's scan-phase findings, its suppression map (finalize findings
# must still honor line-level waivers in unparsed files), and the
# cross-file FACTS each rule contributed (Rule.module_facts), which are
# replayed through absorb_facts so finalize sees the whole tree.

CACHE_VERSION = 3
CACHE_FILENAME = ".dralint-cache.json"

_RULES_SOURCES = ("core.py", "rules.py", "raceanalysis.py",
                  "flowanalysis.py")
_REGISTRY_SOURCES = ("infra/faults.py", "infra/metrics.py",
                     "infra/featuregates.py")


def _hash_sources(files: Iterable[Path]) -> str:
    import hashlib
    h = hashlib.sha1()
    for f in files:
        try:
            h.update(f.read_bytes())
        except OSError:
            h.update(b"?")
    return h.hexdigest()


def cache_keys(root: Path) -> Dict[str, str]:
    analysis = Path(__file__).resolve().parent
    return {
        "rules_version": _hash_sources(analysis / n
                                       for n in _RULES_SOURCES),
        "registries": _hash_sources(root / "tpu_dra" / n
                                    for n in _REGISTRY_SOURCES),
    }


def _load_cache(path: Path, keys: Dict[str, str]) -> Dict:
    try:
        doc = json.loads(path.read_text())
    except (OSError, json.JSONDecodeError, ValueError):
        return {"files": {}}
    if (not isinstance(doc, dict) or doc.get("version") != CACHE_VERSION
            or doc.get("rules_version") != keys["rules_version"]
            or doc.get("registries") != keys["registries"]
            or not isinstance(doc.get("files"), dict)):
        return {"files": {}}
    return doc


class _CachedSuppressions:
    """Module.suppressed() semantics over a cached suppression map —
    finalize findings anchored in an unparsed file still honor its
    waiver comments (and their justification strings)."""

    def __init__(self, doc: Dict):
        lines = doc.get("lines", doc) or {}
        self._lines: Dict[int, Optional[Set[str]]] = {}
        for line, rules in lines.items():
            self._lines[int(line)] = (None if rules is None
                                      else set(rules))
        self._just: Dict[int, bool] = {
            int(line): bool(v)
            for line, v in (doc.get("just") or {}).items()}

    def suppressed(self, rule: str, line: int) -> bool:
        return _lookup_suppressed(self._lines, rule, line)

    def suppression_justified(self, rule: str, line: int) -> bool:
        return _lookup_justified(self._lines, self._just, rule, line)


def _suppressions_doc(mod: Module) -> Dict:
    return {"lines": {str(ln): (None if rules is None else sorted(rules))
                      for ln, rules in mod.suppressions.items()},
            "just": {str(ln): v for ln, v in mod.justified.items()}}


def _rel(path: Path, root: Path) -> str:
    try:
        return str(path.relative_to(root))
    except ValueError:
        return str(path)


# ---------------------------------------------------------------------------
# Per-module scan (shared by the serial loop and the --jobs pool)
# ---------------------------------------------------------------------------

def _scan_module(mod: Module, active: Sequence[Rule], ctx: ProjectContext,
                 timings: Optional[Dict[str, float]] = None,
                 ) -> Tuple[List[Finding], List[Finding], Dict[str, Dict]]:
    """Run every rule's scan phase over one module, returning
    (findings, suppressed, facts-by-key). The one definition of the
    scan-phase protocol — the multiprocessing workers and the in-process
    loop must agree byte for byte or warm/cold/parallel runs diverge."""
    import time
    mod_findings: List[Finding] = []
    mod_suppressed: List[Finding] = []
    facts: Dict[str, Dict] = {}
    for rule in active:
        t0 = time.perf_counter() if timings is not None else 0.0
        for finding in rule.scan(mod, ctx):
            if mod.suppressed(finding.rule, finding.line):
                mod_suppressed.append(finding)
            else:
                mod_findings.append(finding)
        rule_facts = rule.module_facts()
        if rule_facts is not None:
            # setdefault: two rules sharing a facts_key (draracer and
            # drflow both ride the R9 extraction) contribute it once.
            facts.setdefault(rule.facts_id(), rule_facts)
        if timings is not None:
            timings[rule.rule_id] = (timings.get(rule.rule_id, 0.0)
                                     + time.perf_counter() - t0)
    return mod_findings, mod_suppressed, facts


# Pool workers re-create the registries once per process (initializer),
# not once per file — ProjectContext.load parses three infra modules.
_POOL_STATE: Optional[Tuple[Path, "ProjectContext"]] = None


def _pool_init(root_str: str) -> None:
    global _POOL_STATE
    # Rule registration lives in the package __init__ — inherited under
    # fork, but a spawn-based start method needs the explicit import.
    import tpu_dra.analysis  # noqa: F401
    root = Path(root_str)
    _POOL_STATE = (root, ProjectContext.load(root))


def _pool_scan(item: Tuple[str, str]) -> Tuple[str, Optional[Dict]]:
    """One file's scan phase in a worker process: returns a cache-entry
    -shaped payload (findings/suppressed/suppressions/facts) the parent
    absorbs exactly like a cache hit. None = unparseable (compileall
    owns syntax errors, same as the serial path)."""
    rel, source = item
    assert _POOL_STATE is not None
    root, ctx = _POOL_STATE
    mod = parse_module(root / rel, root, source=source)
    if mod is None:
        return rel, None
    mod_findings, mod_suppressed, facts = _scan_module(
        mod, all_rules(), ctx)
    return rel, {
        "findings": [f.to_dict() for f in mod_findings],
        "suppressed": [f.to_dict() for f in mod_suppressed],
        "suppressions": _suppressions_doc(mod),
        "facts": facts,
    }


def resolve_jobs(jobs: object) -> int:
    """'auto'/0 -> min(8, cpu count), else int(jobs) floored at 1."""
    import os
    if jobs in ("auto", 0, "0", None):
        return max(1, min(8, os.cpu_count() or 1))
    return max(1, int(jobs))  # type: ignore[arg-type]


def run(paths: Sequence[Path], root: Optional[Path] = None,
        rules: Optional[Iterable[Rule]] = None,
        rule_ids: Optional[Set[str]] = None,
        use_cache: bool = False, jobs: int = 1) -> Report:
    import hashlib
    import time

    paths = [Path(p) for p in paths]
    root = Path(root) if root else find_root(paths[0] if paths else Path("."))
    ctx = ProjectContext.load(root)
    active = list(rules) if rules is not None else all_rules()
    if rule_ids:
        active = [r for r in active if r.provided_ids() & rule_ids]
    # The cache stores full-rule-set results; a rule-filtered run must
    # not read partial entries as authoritative nor poison future runs.
    # (Callers passing explicit `rules` with use_cache=True — the CLI —
    # are expected to pass the full registry.)
    use_cache = use_cache and rule_ids is None
    cache_path = root / CACHE_FILENAME
    keys = cache_keys(root) if use_cache else {}
    cache = _load_cache(cache_path, keys) if use_cache else {"files": {}}

    report = Report(ctx=ctx)
    pending: List[Tuple[str, str]] = []  # (relpath, source) to scan
    cached: Dict[str, Dict] = {}     # relpath -> valid cache entry
    stats: Dict[str, Dict] = {}      # relpath -> fresh stat for new entry
    refreshed: Dict[str, Dict] = {}  # content-hash hits with new stat keys
    for f in iter_python_files(paths):
        rel = _rel(f, root)
        # A stat/read failure raises: silently skipping an unreadable
        # file would drop its findings AND its contribution to the
        # R9-R11 call graph — "lint tier green" must never mean "lint
        # could not see the tree".
        st = f.stat()
        entry = cache["files"].get(rel) if use_cache else None
        if (entry is not None and entry.get("mtime_ns") == st.st_mtime_ns
                and entry.get("size") == st.st_size):
            cached[rel] = entry
            continue
        data = f.read_bytes()
        sha = hashlib.sha1(data).hexdigest() if use_cache else ""
        if (entry is not None and entry.get("sha1")
                and entry["sha1"] == sha):
            # Content-hash fallback tier: a touch or a content-equal
            # rewrite changed the stat key but not the bytes — reuse
            # the entry and refresh its stat key so the next run hits
            # on the cheap tier again.
            entry = {**entry, "mtime_ns": st.st_mtime_ns,
                     "size": st.st_size}
            cached[rel] = entry
            refreshed[rel] = entry
            continue
        pending.append((rel, data.decode("utf-8")))
        stats[rel] = {"mtime_ns": st.st_mtime_ns,
                      "size": st.st_size, "sha1": sha}

    # Scan phase. Every module is scanned by FRESH per-file rule
    # instances (exactly what a pool worker does) and reduced to a
    # cache-entry-shaped payload; `active` instances are populated
    # purely through absorb_facts below, in sorted relpath order, so
    # warm, cold, serial and --jobs runs feed finalize identically.
    rule_classes = [type(r) for r in active]
    scanned: Dict[str, Dict] = {}         # relpath -> entry payload
    modules_by_rel: Dict[str, Module] = {}
    jobs = min(resolve_jobs(jobs), max(1, len(pending)))
    if set(rule_classes) != set(_RULE_CLASSES):
        # Pool workers instantiate the REGISTERED rule set; a filtered
        # or custom rule list must scan serially or the workers would
        # silently run different rules than the caller asked for.
        jobs = 1
    if jobs > 1:
        import multiprocessing
        t0 = time.perf_counter()
        with multiprocessing.Pool(jobs, initializer=_pool_init,
                                  initargs=(str(root),)) as pool:
            for rel, payload in pool.imap_unordered(
                    _pool_scan, pending, chunksize=4):
                if payload is not None:
                    scanned[rel] = payload
        report.timings["<scan-pool>"] = time.perf_counter() - t0
    else:
        for rel, source in pending:
            mod = parse_module(root / rel, root, source=source)
            if mod is None:
                continue  # compileall (hack/lint.sh) owns syntax errors
            mod_findings, mod_suppressed, facts = _scan_module(
                mod, [cls() for cls in rule_classes], ctx,
                timings=report.timings)
            modules_by_rel[rel] = mod
            scanned[rel] = {
                "findings": [f.to_dict() for f in mod_findings],
                "suppressed": [f.to_dict() for f in mod_suppressed],
                "suppressions": _suppressions_doc(mod),
                "facts": facts,
            }

    report.files = len(scanned) + len(cached)
    report.cache_hits = len(cached)
    ctx.scanned = set(scanned) | set(cached)

    by_rel: Dict[str, object] = {}
    new_entries: Dict[str, Dict] = dict(refreshed)
    entries = {**cached, **scanned}
    for rel in sorted(entries):
        entry = entries[rel]
        replayed = modules_by_rel.get(rel) or _CachedSuppressions(
            entry.get("suppressions") or {})
        by_rel[rel] = replayed
        for rule in active:
            facts = (entry.get("facts") or {}).get(rule.facts_id())
            if facts is not None:
                rule.absorb_facts(rel, facts, ctx)
        report.findings.extend(Finding(**d) for d in entry["findings"])
        for d in entry["suppressed"]:
            f = Finding(**d)
            report.suppressed.append(f)
            if not replayed.suppression_justified(f.rule, f.line):
                report.unjustified.append(f)
        if use_cache and rel in stats:
            new_entries[rel] = {**stats[rel],
                                "findings": entry["findings"],
                                "suppressed": entry["suppressed"],
                                "suppressions": entry["suppressions"],
                                "facts": entry["facts"]}

    for rule in active:
        t0 = time.perf_counter()
        for finding in rule.finalize(ctx):
            mod = by_rel.get(finding.path)
            if mod is not None and mod.suppressed(finding.rule, finding.line):
                report.suppressed.append(finding)
                if not mod.suppression_justified(finding.rule,
                                                 finding.line):
                    report.unjustified.append(finding)
            else:
                report.findings.append(finding)
        report.timings[rule.rule_id] = (
            report.timings.get(rule.rule_id, 0.0)
            + time.perf_counter() - t0)
    if rule_ids:
        report.findings = [f for f in report.findings
                           if f.rule in rule_ids]
        report.suppressed = [f for f in report.suppressed
                             if f.rule in rule_ids]
        report.unjustified = [f for f in report.unjustified
                              if f.rule in rule_ids]
    report.findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    report.suppressed.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    _LINT_FINDINGS.inc(len(report.findings))
    _LINT_CACHE_HITS.inc(report.cache_hits)
    if use_cache:
        # Merge, never replace wholesale: a single-file lint must not
        # evict the rest of the tree's entries. Vanished files linger
        # harmlessly (their stat key can never match again).
        files = dict(cache["files"])
        files.update(new_entries)
        doc = {"version": CACHE_VERSION, **keys, "files": files}
        try:
            cache_path.write_text(json.dumps(doc))
        except OSError:
            pass  # read-only checkout: cache is best-effort
    return report


def lint_sources(sources: Dict[str, str],
                 ctx: Optional[ProjectContext] = None,
                 rule_ids: Optional[Set[str]] = None) -> List[Finding]:
    """Lint a {relpath: source} set as one small tree (the CROSS-MODULE
    test seam the interprocedural rules need): returns UNSUPPRESSED
    findings, using a synthetic context unless one is given. Relpaths
    become module identities — ``pkg/mod_a.py`` is importable from a
    sibling fixture as ``from pkg.mod_a import f``."""
    ctx = ctx or ProjectContext(root=Path("."))
    mods: List[Module] = []
    for relpath, source in sources.items():
        suppressions, justified = _parse_suppressions(source)
        mods.append(Module(path=Path(relpath), relpath=relpath,
                           source=source, tree=ast.parse(source),
                           suppressions=suppressions,
                           justified=justified))
    # The test seam acts as a full-project run: orphan rules see the
    # registries as in-view so fixtures can exercise both directions.
    ctx.scanned = ({m.relpath for m in mods}
                   | {ctx.fault_sites_path, ctx.metric_catalog_path}
                   | ctx.scanned)
    active = all_rules()
    if rule_ids:
        active = [r for r in active if r.provided_ids() & rule_ids]
    by_rel = {m.relpath: m for m in mods}
    out: List[Finding] = []
    for rule in active:
        for mod in mods:
            for finding in rule.scan(mod, ctx):
                if not mod.suppressed(finding.rule, finding.line):
                    out.append(finding)
        for finding in rule.finalize(ctx):
            mod = by_rel.get(finding.path)
            if mod is None or not mod.suppressed(finding.rule,
                                                 finding.line):
                out.append(finding)
    if rule_ids:
        out = [f for f in out if f.rule in rule_ids]
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return out


def lint_source(source: str, relpath: str = "fixture.py",
                ctx: Optional[ProjectContext] = None,
                rule_ids: Optional[Set[str]] = None) -> List[Finding]:
    """Single-module lint seam (the original fixture entry point)."""
    return lint_sources({relpath: source}, ctx=ctx, rule_ids=rule_ids)


def render(report: Report, as_json: bool = False,
           show_suppressed: bool = False) -> str:
    if as_json:
        return json.dumps(report.to_dict(), indent=2)
    lines = [f.format() for f in report.findings]
    if show_suppressed:
        lines += [f"{f.format()} (suppressed)" for f in report.suppressed]
    lines.append(f"dralint: {report.files} files, "
                 f"{len(report.findings)} findings, "
                 f"{len(report.suppressed)} suppressed")
    return "\n".join(lines)
