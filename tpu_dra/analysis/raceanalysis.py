"""draracer: interprocedural lockset & guarded-by inference (R9-R11).

dralint's R1/R2 stop at the lexical horizon: they see one function at a
time and trust the ``*_locked`` naming convention at call sites. PR 8's
review pass caught five raced-state bugs those rules could not see —
every one crossed a function or file boundary. This module is the
whole-program half (SURVEY §16), three rules over one shared per-module
extraction:

- **R9 — interprocedural locked-call discipline.** A whole-tree call
  graph (module-qualified def/method resolution, conservative for
  dynamic dispatch) over which the R1 lock context is propagated
  *interprocedurally*: a call that RESOLVES to a ``*_locked`` function
  — through an import alias, a bound reference, or a helper chain in
  another file — must be reachable only through lock acquisitions. A
  function all of whose static call sites hold a lock inherits the
  context; a chain from an exposed root (no static callers, a thread
  target, an escaping reference) that reaches a ``*_locked`` callee
  without passing an acquisition is a finding, reported at the callee's
  call site with an example chain.

- **R10 — guarded-by inference.** Per class, learn which attributes are
  predominantly accessed under which ``with <recv>.<lock>:`` context
  (lock attributes are discovered from the same creation-site registry
  that keys the runtime lockwitness), then flag reads/writes of a
  guarded attribute outside any acquisition of its guard. An explicit
  ``# GUARDED_BY: <lock>`` comment on the attribute's assignment pins
  intent (``# GUARDED_BY: none`` exempts); ``--locks-report`` prints
  the per-attribute table.

- **R11 — static lock-order graph.** Lock identity is the creation
  site (``relpath:line`` of the ``threading.Lock()`` call) — the SAME
  key the runtime witness uses, so the two graphs are comparable.
  Nested ``with``-acquisitions and lock-acquiring calls made under a
  held lock yield edges; the graph must be acyclic at lint time, and
  ``check_witness`` asserts a runtime-exported edge set (chaos matrix,
  drmc run) is a SUBSET of the static graph — an unexplained runtime
  edge means the call graph under-approximates and fails the gate.

Resolution rules (documented in SURVEY §16.2, exercised per-rule in
tests/test_raceanalysis.py):

1. ``self.m()`` → the enclosing class's method (then base classes).
2. Bare names → nested defs, then module functions, then imports
   (``from x import f [as g]``; ``import x as m; m.f()``).
3. ``obj.m()`` → obj's class when inferable from a parameter
   annotation, a constructor assignment (``obj = Cls(...)``), a typed
   attribute (``self._shards = [Cls(...)]`` + subscript/iteration), or
   a helper's inferred return type; otherwise the DYNAMIC-DISPATCH
   fallback: every class in the tree defining ``m`` (suppressed for
   ubiquitous builtin-ish names, always applied for ``*_locked``).
4. Lock expressions resolve to creation sites through the same engine;
   a with-item that LOOKS like a data lock but cannot be resolved to a
   creation site is itself an R11 finding (an unresolvable acquisition
   would silently punch a hole in the static graph).

Test modules contribute nothing (they call ``*_locked`` helpers in
controlled single-thread contexts and access attributes freely); the
witness gates only run chaos/drmc code, which lives in the tree and IS
analyzed.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from tpu_dra.analysis.core import (
    Finding, Module, ProjectContext, Rule, register,
)
from tpu_dra.analysis.rules import (
    _MUTATORS as _MUTATOR_METHODS, _STATE_MUTATORS, attr_chain,
    is_data_lock_name, module_imports as _module_imports,
)

# Guarded-by inference thresholds (SURVEY §16.3): an attribute is
# inferred lock-guarded when at least MIN_GUARDED accesses vote for one
# guard and those votes are at least GUARD_RATIO of all counted
# accesses. Below either bar the attribute is treated as unshared or
# deliberately torn-read tolerant, and R10 stays silent.
MIN_GUARDED = 4
GUARD_RATIO = 0.75

# Pure builtins whose result directly derives from their arguments —
# attrs read inside them still count as snapshot reads for R14.
_PURE_BUILTINS = {
    "len", "sorted", "list", "tuple", "set", "dict", "frozenset",
    "min", "max", "sum", "any", "all", "bool", "int", "float", "str",
    "reversed", "enumerate", "zip", "abs", "round",
}

# Method names too ubiquitous for the dynamic-dispatch fallback: an
# unresolved receiver calling one of these must not edge into every
# class that happens to define it (dict.get vs SomeClass.get). The
# fallback still ALWAYS applies to *_locked names — they are the
# convention's own namespace and never collide with builtins.
_NO_GLOBAL_FALLBACK = {
    "get", "put", "pop", "add", "set", "run", "start", "stop", "close",
    "acquire", "release", "wait", "notify", "notify_all", "update",
    "append", "extend", "remove", "clear", "copy", "keys", "values",
    "items", "join", "send", "recv", "read", "write", "flush", "open",
    "list", "create", "delete", "patch", "watch", "reset", "load",
    "store", "apply", "check", "name", "format", "to_dict", "value",
}


# ---------------------------------------------------------------------------
# Expression descriptors (JSON-able, resolved in finalize)
# ---------------------------------------------------------------------------

def _lock_ctor_kind(call: ast.Call,
                    lock_names: Dict[str, str]) -> Optional[str]:
    """'lock'/'cond' when `call` creates a threading lock — by dotted
    name (``threading.Lock()``), by import (``from threading import
    Lock``), or through a module-level constructor alias
    (``_real_lock = threading.Lock``); the module's `lock_names` table
    carries the import/alias name → kind mapping."""
    chain = attr_chain(call.func)
    if not chain:
        return None
    tail = chain[-1]
    if chain[:-1] == ["threading"]:
        if tail in ("Lock", "RLock"):
            return "lock"
        if tail == "Condition":
            return "cond"
        return None
    if len(chain) == 1:
        return lock_names.get(tail)
    return None


def _find_lock_creations(node: ast.AST,
                         lock_names: Dict[str, str]) -> List[int]:
    """Line numbers of every lock-creating call anywhere under `node`
    (the creation-site registry: the same ``relpath:line`` keys the
    runtime witness assigns — a dict-comprehension of per-chip locks is
    one class at the comprehension's line). A bare ``Condition()``
    creates its RLock inside threading (unwitnessed) — its site is
    still recorded so the static graph can reason about it; it simply
    never appears in a runtime edge set."""
    out: List[int] = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call):
            kind = _lock_ctor_kind(sub, lock_names)
            if kind == "lock":
                out.append(sub.lineno)
            elif kind == "cond" and not any(
                    isinstance(a, ast.Call)
                    and _lock_ctor_kind(a, lock_names) == "lock"
                    for a in sub.args):
                out.append(sub.lineno)
    return sorted(set(out))


def describe_expr(node: ast.AST,
                  lock_names: Dict[str, str]) -> Dict:
    """A compact JSON descriptor of `node` sufficient for the finalize
    resolver: names, attribute/subscript chains, constructor calls and
    embedded lock creations. Anything else degrades to 'unknown'."""
    if isinstance(node, ast.Call):
        kind = _lock_ctor_kind(node, lock_names)
        if kind == "lock":
            return {"t": "lock", "line": node.lineno}
        if kind == "cond":
            for a in node.args:
                if (isinstance(a, ast.Call)
                        and _lock_ctor_kind(a, lock_names) == "lock"):
                    return {"t": "lock", "line": a.lineno}
            return {"t": "lock", "line": node.lineno, "bare_cond": True}
        desc: Dict = {"t": "call", "func": describe_expr(node.func,
                                                        lock_names),
                      "line": node.lineno}
        # Positional args ride along (capped): drflow's taint walk and
        # the functools.partial chase both need to see through a call
        # expression used as a VALUE (``snap = sorted(lister.list())``,
        # ``partial(self._on_evt, key)``) — call RECORDS carry args
        # only for calls made as statements/receivers.
        if node.args:
            desc["args"] = [describe_expr(a, lock_names)
                            for a in node.args[:5]]
        arg_locks = _find_lock_creations(node, lock_names)
        if arg_locks:
            desc["arg_locks"] = arg_locks
        return desc
    if isinstance(node, ast.Name):
        return {"t": "name", "id": node.id}
    if isinstance(node, ast.Attribute):
        return {"t": "attr", "base": describe_expr(node.value, lock_names),
                "attr": node.attr}
    if isinstance(node, ast.Subscript):
        return {"t": "sub", "base": describe_expr(node.value, lock_names)}
    if isinstance(node, (ast.List, ast.Tuple, ast.Set)):
        locks = _find_lock_creations(node, lock_names)
        ctors = [describe_expr(e, lock_names) for e in node.elts[:4]
                 if isinstance(e, ast.Call)]
        return {"t": "container", "locks": locks, "elems": ctors}
    if isinstance(node, (ast.Dict, ast.DictComp, ast.ListComp,
                         ast.SetComp, ast.GeneratorExp)):
        locks = _find_lock_creations(node, lock_names)
        elems: List[Dict] = []
        if isinstance(node, ast.Dict):
            elems = [describe_expr(v, lock_names) for v in node.values[:4]
                     if isinstance(v, ast.Call)]
        elif isinstance(node, (ast.ListComp, ast.SetComp,
                               ast.GeneratorExp)):
            if isinstance(node.elt, ast.Call):
                elems = [describe_expr(node.elt, lock_names)]
        elif isinstance(node, ast.DictComp):
            if isinstance(node.value, ast.Call):
                elems = [describe_expr(node.value, lock_names)]
        return {"t": "container", "locks": locks, "elems": elems}
    if isinstance(node, ast.Constant):
        return {"t": "const"}
    if isinstance(node, ast.IfExp):
        return describe_expr(node.body, lock_names)
    if isinstance(node, ast.BoolOp) and node.values:
        return describe_expr(node.values[-1], lock_names)
    if isinstance(node, ast.Lambda):
        # Position-keyed: the module records the lambda's body as its
        # own function record, so a registered lambda handler resolves.
        return {"t": "lambda", "line": node.lineno,
                "col": node.col_offset}
    return {"t": "unknown"}


def _held_entry(desc: Dict) -> Dict:
    """A held-stack entry: the descriptor plus its root variable and
    final attribute. Guard identity for R10 is (root var, lock attr),
    which is only meaningful for a SIMPLE ``<name>.<attr>`` chain —
    same variable ⇒ same object ⇒ same class. Crossing a subscript,
    call, or second attribute hop means a DIFFERENT object's
    same-named lock (``self._shards[i]._lock``): it must neither
    satisfy nor vote for the receiver's own guard, so base stays None
    (R11 still uses the full expression)."""
    if desc.get("t") == "attr" and desc["base"].get("t") == "name":
        return {"expr": desc, "base": desc["base"]["id"],
                "attr": desc["attr"]}
    if desc.get("t") == "name":
        return {"expr": desc, "base": desc["id"], "attr": desc["id"]}
    return {"expr": desc, "base": None, "attr": None}


def _lockish_desc(node: ast.AST) -> bool:
    chain = attr_chain(node)
    return bool(chain) and is_data_lock_name(chain[-1])


# ---------------------------------------------------------------------------
# Per-function extraction
# ---------------------------------------------------------------------------

class _FuncRecorder(ast.NodeVisitor):
    """One pass over one function body collecting everything R9/R10/R11
    need, with the lexical held-lock stack tracked the same way R1's
    visitor tracks it (nested defs/lambdas are separate records and do
    NOT inherit; comprehensions execute inline and do)."""

    def __init__(self, rec: Dict, lock_names: Dict[str, str]):
        self.rec = rec
        self.lock_names = lock_names
        self.held: List[Dict] = []       # held-stack entries
        self._explicit: List[Tuple[str, Dict]] = []  # (chainstr, entry)

    # -- scope boundaries ---------------------------------------------------

    def visit_FunctionDef(self, node):  # noqa: N802 — nested def
        self.rec["locals"].setdefault(node.name, []).append(
            {"t": "nested", "qual": f"{self.rec['qual']}.{node.name}"})
        # Body handled by the module walker as its own record.

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node):  # noqa: N802
        pass  # opaque: no lock context, no records

    def visit_ClassDef(self, node):  # noqa: N802
        pass  # nested classes: out of scope for the resolver

    # -- bindings -----------------------------------------------------------

    def _bind(self, target: ast.AST, desc: Dict) -> None:
        if isinstance(target, ast.Name):
            self.rec["locals"].setdefault(target.id, []).append(desc)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, {"t": "unknown"})

    def visit_Assign(self, node):  # noqa: N802
        desc = describe_expr(node.value, self.lock_names)
        for t in node.targets:
            self._bind(t, desc)
            self._record_self_assign(t, node, desc)
            self._record_flow_bind(t, node, desc)
            if isinstance(t, ast.Subscript) and desc.get("t") in (
                    "call", "name", "attr", "sub", "iter"):
                # d[k] = Cls(...): the container binding gains the
                # element — ``inf[name] = Informer(...)`` must let
                # ``inf["pods"].on_add(...)`` resolve its receiver.
                # Non-call elements ride along too: ``self._cache[k] =
                # view`` makes the cache a container OF the view for
                # drflow's taint walk.
                elem = {"t": "container", "locks": [], "elems": [desc]}
                if isinstance(t.value, ast.Name):
                    self.rec["locals"].setdefault(
                        t.value.id, []).append(elem)
                elif (isinstance(t.value, ast.Attribute)
                        and isinstance(t.value.value, ast.Name)
                        and t.value.value.id == "self"):
                    self.rec["self_assigns"].append(
                        {"attr": t.value.attr, "line": node.lineno,
                         "value": elem})
        self.generic_visit(node)

    def visit_AnnAssign(self, node):  # noqa: N802
        if node.value is not None:
            desc = describe_expr(node.value, self.lock_names)
            self._bind(node.target, desc)
            self._record_self_assign(node.target, node, desc)
        self.generic_visit(node)

    def _record_self_assign(self, target, stmt, desc: Dict) -> None:
        if (isinstance(target, ast.Attribute)
                and isinstance(target.value, ast.Name)
                and target.value.id == "self"):
            self.rec["self_assigns"].append(
                {"attr": target.attr, "line": stmt.lineno, "value": desc})

    def _record_flow_bind(self, target, stmt, desc: Dict) -> None:
        """drflow (R13/R14) bindings: call results bound to a name
        (with their line — `locals` descriptors are lineless), and
        snapshot binds — a name bound under a held data lock from
        state rooted at the lock's own receiver, which goes STALE the
        moment the with-block releases (R14's check-then-act seed)."""
        if not isinstance(target, ast.Name):
            return
        if desc.get("t") == "call":
            self.rec.setdefault("call_binds", []).append(
                {"var": target.id, "line": stmt.lineno, "desc": desc})
        lock = next((h for h in reversed(self.held)
                     if h.get("attr") and is_data_lock_name(h["attr"])
                     and h.get("end") is not None), None)
        if lock is None:
            return
        base = lock.get("base")
        attrs: set = set()
        rhs_calls: set = set()

        def collect(n: ast.AST) -> None:
            """Receiver-state attrs the bound value DIRECTLY derives
            from. A non-builtin call's ARGUMENTS are consumed by the
            callee — the bind takes the call's RESULT, whose staleness
            is the locked-getter seed's job — but the call's receiver
            chain is still a direct read (``self._proc.poll()`` reads
            ``_proc``); pure builtins (len/sorted/min ...) pass their
            arguments' reads through (``n = len(self._items)``)."""
            if isinstance(n, ast.Call):
                cchain = attr_chain(n.func)
                if len(cchain) == 2 and cchain[0] == base:
                    # A receiver-method call INSIDE the guarded
                    # expression: if it writes state, the bind is a
                    # test-and-set claim, not a naked check (R14).
                    rhs_calls.add(cchain[1])
                if (isinstance(n.func, ast.Name)
                        and n.func.id in _PURE_BUILTINS):
                    for a in n.args:
                        collect(a)
                else:
                    collect(n.func)
                return
            chain = attr_chain(n) if isinstance(n, ast.Attribute) else None
            if (chain and len(chain) > 1 and chain[0] == base
                    and chain[1] != lock["attr"]):
                attrs.add(chain[1])
            for child in ast.iter_child_nodes(n):
                collect(child)

        collect(stmt.value)
        if not attrs:
            return  # nothing receiver-state-derived: not a snapshot
        self.rec.setdefault("snap_binds", []).append(
            {"var": target.id, "line": stmt.lineno, "base": base,
             "lock_attr": lock["attr"], "release": lock["end"],
             "attrs": sorted(attrs), "rhs_calls": sorted(rhs_calls)})

    def visit_Return(self, node):  # noqa: N802
        # A value returned while holding a data lock is a SNAPSHOT the
        # moment it crosses the return (the getter pattern): callers
        # that guard-then-act on it are R14's interprocedural seed.
        if node.value is not None:
            lock = next((h for h in reversed(self.held)
                         if h.get("attr")
                         and is_data_lock_name(h["attr"])), None)
            if lock is not None and lock.get("base") == "self":
                attrs = set()
                for n in ast.walk(node.value):
                    chain = (attr_chain(n)
                             if isinstance(n, ast.Attribute) else None)
                    if (chain and len(chain) > 1 and chain[0] == "self"
                            and chain[1] != lock["attr"]):
                        attrs.add(chain[1])
                if attrs:
                    cur = self.rec.setdefault(
                        "ret_locked", {"lock_attr": lock["attr"],
                                       "attrs": []})
                    cur["attrs"] = sorted(set(cur["attrs"]) | attrs)
        self.generic_visit(node)

    def _record_test(self, node) -> None:
        names = sorted({n.id for n in ast.walk(node.test)
                        if isinstance(n, ast.Name)})
        if not names or not node.body:
            return
        end = max((getattr(s, "end_lineno", None) or s.lineno
                   for s in node.body), default=node.lineno)
        self.rec.setdefault("tests", []).append(
            {"line": node.lineno, "names": names,
             "span": [node.body[0].lineno, end]})

    def visit_If(self, node):  # noqa: N802
        self._record_test(node)
        self.generic_visit(node)

    visit_While = visit_If  # noqa: N815

    def _visit_comp(self, node):
        # Comprehensions execute inline: generator targets are scope
        # bindings (`l` in ``max(l.when(i) for l in self._limiters)``).
        for gen in node.generators:
            self._bind(gen.target,
                       {"t": "iter",
                        "of": describe_expr(gen.iter, self.lock_names)})
        self.generic_visit(node)

    visit_GeneratorExp = _visit_comp  # noqa: N815
    visit_ListComp = _visit_comp      # noqa: N815
    visit_SetComp = _visit_comp       # noqa: N815
    visit_DictComp = _visit_comp      # noqa: N815

    def visit_For(self, node):  # noqa: N802
        desc = describe_expr(node.iter, self.lock_names)
        if (desc.get("t") == "call"
                and desc["func"].get("t") == "name"
                and desc["func"].get("id") == "enumerate"):
            # for i, x in enumerate(xs): second target iterates xs
            if (isinstance(node.target, ast.Tuple)
                    and len(node.target.elts) == 2
                    and isinstance(node.iter, ast.Call)
                    and node.iter.args):
                inner = describe_expr(node.iter.args[0], self.lock_names)
                self._bind(node.target.elts[1], {"t": "iter", "of": inner})
                self._bind(node.target.elts[0], {"t": "unknown"})
                self.generic_visit(node)
                return
        self._bind(node.target, {"t": "iter", "of": desc})
        self.generic_visit(node)

    # -- acquisitions -------------------------------------------------------

    def visit_With(self, node):  # noqa: N802
        # EVERY with-item is a potential acquisition: naming (`*_lock`)
        # is only the "must resolve" flag (R11's unresolvable-lock
        # finding) — whether the item IS a lock is decided at finalize
        # by resolving it to creation sites or a lock-wrapping class's
        # acquire/__enter__ (SharedFlock). The runtime witness sees a
        # `self._plock` no matter what it is called; so must we. An
        # ``open(...)``/ExitStack item resolves to nothing and
        # contributes nothing.
        pushed: List[Dict] = []
        for item in node.items:
            self.visit(item.context_expr)
            desc = describe_expr(item.context_expr, self.lock_names)
            if desc.get("t") in ("lock", "call", "attr", "name", "sub"):
                self.rec["acquires"].append(
                    {"lock": desc, "line": item.context_expr.lineno,
                     "held": [h["expr"] for h in self.held],
                     "lockish": _lockish_desc(item.context_expr),
                     "via": "with"})
                entry = _held_entry(desc)
                # The with's last line = the release boundary: a value
                # bound inside and used past it crossed the release
                # (R14). Explicit .acquire() entries carry no end —
                # flow-insensitively held to function exit, so nothing
                # lexically "crosses" their release.
                entry["end"] = getattr(node, "end_lineno", None)
                self.held.append(entry)
                pushed.append(entry)
            if item.optional_vars is not None:
                self._bind(item.optional_vars, desc)
        for stmt in node.body:
            self.visit(stmt)
        # Pop the with's OWN entries by identity — an unbalanced
        # explicit .acquire() in the body stays held past the with
        # (flow-insensitive), and a tail slice would pop IT instead of
        # the with-item, corrupting the stack for the rest of the
        # function.
        for entry in pushed:
            for i in range(len(self.held) - 1, -1, -1):
                if self.held[i] is entry:
                    del self.held[i]
                    break

    def visit_Call(self, node):  # noqa: N802
        chain = attr_chain(node.func)
        tail = chain[-1] if chain else ""
        if tail == "enter_context" and isinstance(node.func, ast.Attribute) \
                and len(node.args) == 1:
            # stack.enter_context(self._chip_locks[idx]): an ExitStack
            # acquisition — held until the stack unwinds, which the
            # flow-insensitive model rounds up to "rest of function"
            # (over-approximates edges in the safe direction). A
            # non-lock argument resolves to a class and is chased as a
            # wrapper or contributes nothing.
            arg = node.args[0]
            desc = describe_expr(arg, self.lock_names)
            self.rec["acquires"].append(
                {"lock": desc, "line": node.lineno,
                 "held": [h["expr"] for h in self.held],
                 "lockish": _lockish_desc(arg), "via": "enter_context"})
            self.held.append(_held_entry(desc))
            self.visit(arg)
            return
        if tail == "acquire" and len(chain) >= 2 \
                and isinstance(node.func, ast.Attribute):
            # Explicit X.acquire(): held for the rest of the function
            # (or until a matching .release()) — flow-insensitive, which
            # over-approximates edges in the right direction. Recorded
            # for EVERY receiver; finalize decides whether it is a lock
            # (creation site), a lock-wrapping object (SharedFlock: the
            # class's acquire method is chased), or neither (Semaphore:
            # no edges). `lockish` marks receivers the *_lock naming
            # convention claims are locks — those MUST resolve.
            recv = describe_expr(node.func.value, self.lock_names)
            entry = _held_entry(recv)
            self.rec["acquires"].append(
                {"lock": recv, "line": node.lineno,
                 "held": [h["expr"] for h in self.held],
                 "lockish": is_data_lock_name(chain[-2]),
                 "via": "acquire"})
            self.held.append(entry)
            self._explicit.append((".".join(chain[:-1]), entry))
        elif tail == "release" and len(chain) >= 2 \
                and isinstance(node.func, ast.Attribute):
            key = ".".join(chain[:-1])
            for i in range(len(self._explicit) - 1, -1, -1):
                if self._explicit[i][0] == key:
                    entry = self._explicit.pop(i)[1]
                    if entry in self.held:
                        self.held.remove(entry)
                    break
        elif isinstance(node.func, (ast.Name, ast.Attribute)):
            self.rec["calls"].append(
                {"expr": describe_expr(node.func, self.lock_names),
                 "line": node.lineno,
                 "held": [h["expr"] for h in self.held],
                 "args": [describe_expr(a, self.lock_names)
                          for a in node.args[:8]],
                 "kwargs": {kw.arg: describe_expr(kw.value,
                                                  self.lock_names)
                            for kw in node.keywords if kw.arg}})
            # A *_locked bound reference passed as an argument escapes
            # the lexical context (thread targets, callbacks).
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                self._maybe_ref(arg)
        # Visit children, but skip the call's own name: the method
        # attribute in `self._claims.pop()` is not a data access — the
        # receiver `self._claims` below it is, so descend past it.
        for child in ast.iter_child_nodes(node):
            if child is node.func:
                if isinstance(child, ast.Attribute):
                    self.visit(child.value)
                elif not isinstance(child, ast.Name):
                    self.visit(child)
            else:
                self.visit(child)

    def _maybe_ref(self, node: ast.AST) -> None:
        if isinstance(node, (ast.Name, ast.Attribute)):
            chain = attr_chain(node)
            if chain:
                self.rec["refs"].append(
                    {"expr": describe_expr(node, self.lock_names),
                     "line": node.lineno,
                     "held": [h["expr"] for h in self.held],
                     "locked_name": chain[-1].endswith("_locked")})

    # -- attribute accesses (R10) -------------------------------------------

    def visit_Attribute(self, node):  # noqa: N802
        if isinstance(node.value, ast.Name) and not (
                node.attr.startswith("__") and node.attr.endswith("__")):
            kind = "w" if isinstance(node.ctx, (ast.Store, ast.Del)) else "r"
            self.rec["accesses"].append(
                {"base": node.value.id, "attr": node.attr,
                 "line": node.lineno, "kind": kind,
                 "held": [[h["base"], h["attr"]] for h in self.held
                          if h["base"] is not None]})
        if node.attr.endswith("_locked") and isinstance(node.ctx, ast.Load):
            self._maybe_ref(node)
        self.generic_visit(node)

    def visit_Name(self, node):  # noqa: N802
        if node.id.endswith("_locked") and isinstance(node.ctx, ast.Load):
            self._maybe_ref(node)

    def run(self, fn) -> None:
        for stmt in fn.body:
            self.visit(stmt)
        # Mutator-method calls on first-level attrs count as writes:
        # upgrade the recorded read at the same (base, attr, line).
        writes = set()
        for call in ast.walk(fn):
            if (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr in _STATE_MUTATORS
                    and isinstance(call.func.value, ast.Attribute)
                    and isinstance(call.func.value.value, ast.Name)):
                writes.add((call.func.value.value.id,
                            call.func.value.attr, call.func.value.lineno))
        for acc in self.rec["accesses"]:
            if (acc["base"], acc["attr"], acc["line"]) in writes:
                acc["kind"] = "w"


# ---------------------------------------------------------------------------
# Per-module extraction (shared by R9/R10/R11 via one facts blob)
# ---------------------------------------------------------------------------

_GUARD_RE = re.compile(
    r"#\s*GUARDED_BY:\s*(?P<guard>[A-Za-z_][A-Za-z0-9_.]*|none)")


def _parse_guard_comments(source: str) -> Dict[int, str]:
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                m = _GUARD_RE.search(tok.string)
                if m:
                    out[tok.start[0]] = m.group("guard")
    except (tokenize.TokenError, IndentationError):
        pass
    return out




def _scoped_returns(fn) -> List[ast.AST]:
    """Return-expression nodes of `fn`'s own scope (nested defs and
    lambdas are separate records; their returns must not leak into the
    enclosing function's return-type summary)."""
    out: List[ast.AST] = []
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        if isinstance(node, ast.Return) and node.value is not None:
            out.append(node.value)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _scoped_nested_defs(fn) -> List[ast.AST]:
    """Function defs nested anywhere in `fn`'s own scope (inside
    if/with/try blocks included), excluding defs inside deeper nested
    functions — those belong to the nested record's own recursion."""
    out: List[ast.AST] = []
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            out.append(node)
            continue
        if isinstance(node, ast.Lambda):
            continue
        stack.extend(ast.iter_child_nodes(node))
    return out


def _scoped_lambdas(fn) -> List[ast.Lambda]:
    """Lambdas anywhere in `fn`'s own scope (descending through other
    lambdas: each gets its own record), stopping at nested defs."""
    out: List[ast.Lambda] = []
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(node, ast.Lambda):
            out.append(node)
        stack.extend(ast.iter_child_nodes(node))
    return out


def _scoped_walk(fn) -> Iterator[ast.AST]:
    """Every node in `fn`'s own scope, stopping at nested defs and
    lambdas (their bodies belong to their own records)."""
    stack: List[ast.AST] = list(fn.body)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _collect_mutations(fn) -> List[Dict]:
    """drflow (R13) mutation sinks in `fn`'s own scope: writes through
    an attribute/subscript chain, ``del``, and mutator-method calls —
    each reduced to the chain's ROOT: a local/param name
    (``{"root": name}``) or a self attribute (``{"root": "self",
    "attr": a}``). A plain ``self.a = v`` / ``x = v`` REBINDS rather
    than mutates and is not recorded (stores are tracked separately
    through self_assigns / locals)."""
    out: List[Dict] = []

    def add(chain: List[str], line: int, what: str,
            rebind_ok: bool) -> None:
        if not chain:
            return
        if chain[0] == "self":
            if len(chain) < 2 or (rebind_ok and len(chain) == 2):
                return
            out.append({"root": "self", "attr": chain[1],
                        "line": line, "what": what})
        elif not rebind_ok or len(chain) > 1:
            out.append({"root": chain[0], "line": line, "what": what})

    for node in _scoped_walk(fn):
        targets: Tuple = ()
        what = "assignment to"
        if isinstance(node, ast.Assign):
            targets = tuple(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = (node.target,)
        elif isinstance(node, ast.Delete):
            targets, what = tuple(node.targets), "del of"
        for t in targets:
            if isinstance(t, ast.Subscript):
                add(attr_chain(t), t.lineno, what, rebind_ok=False)
            elif isinstance(t, ast.Attribute):
                # x.y = v mutates x; self.y = v rebinds the attribute.
                add(attr_chain(t), t.lineno, what, rebind_ok=True)
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATOR_METHODS):
            add(attr_chain(node.func.value), node.lineno,
                f".{node.func.attr}() on", rebind_ok=False)
    return out


# drflow annotation grammar (SURVEY §20): ``# drflow: view-ok[reason]``
# sanctions a mutation of a laundered-by-protocol view (R13);
# ``# drflow: swallow-ok[reason]`` sanctions a silent except handler
# (R15; the reason is REQUIRED — an empty one still counts as a
# finding under --require-justified semantics); ``# drflow:
# REVALIDATES:<field>`` on a def declares the function re-checks
# <field> against live state under its lock, which sanctions
# check-then-act flows routed through it (R14). ``<field>`` may be
# ``*`` (revalidates everything it touches).
_DRFLOW_RE = re.compile(
    r"#\s*drflow:\s*(?P<kind>view-ok|swallow-ok|REVALIDATES)"
    r"(?:\[(?P<reason>[^\]]*)\])?"
    r"(?::(?P<field>[A-Za-z_*][A-Za-z0-9_.*]*))?")


def _parse_drflow_comments(source: str) -> Dict[str, Dict[str, str]]:
    out: Dict[str, Dict[str, str]] = {
        "view_ok": {}, "swallow_ok": {}, "revalidates": {}}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type != tokenize.COMMENT:
                continue
            m = _DRFLOW_RE.search(tok.string)
            if not m:
                continue
            line = str(tok.start[0])
            kind = m.group("kind")
            if kind == "REVALIDATES":
                out["revalidates"][line] = m.group("field") or "*"
            else:
                out[kind.replace("-", "_")][line] = (
                    m.group("reason") or "").strip()
    except (tokenize.TokenError, IndentationError):
        pass
    return out


def extract_module(module: Module) -> Dict:
    """The shared extraction: functions (with calls/refs/acquires/
    accesses), classes, imports, module-global locks, GUARDED_BY
    annotations. Memoized on the Module object — R9/R10/R11 all read
    the same blob and it is cached once under R9's facts key."""
    cached = getattr(module, "_race_facts", None)
    if cached is not None:
        return cached
    imports = _module_imports(module.tree)
    lock_names: Dict[str, str] = {}
    for n, t in imports.items():
        if t in ("threading.Lock", "threading.RLock"):
            lock_names[n] = "lock"
        elif t == "threading.Condition":
            lock_names[n] = "cond"
    # Constructor aliases: ``_real_lock = threading.Lock`` (lockwitness
    # keeps raw references so its own internals stay unwitnessed) —
    # calls through the alias are still creations at the CALL site.
    for node in module.tree.body:
        if isinstance(node, ast.Assign) and isinstance(
                node.value, (ast.Attribute, ast.Name)):
            chain = attr_chain(node.value)
            kind = None
            if chain[-2:] in (["threading", "Lock"],
                              ["threading", "RLock"]):
                kind = "lock"
            elif chain[-2:] == ["threading", "Condition"]:
                kind = "cond"
            elif len(chain) == 1 and chain[0] in lock_names:
                kind = lock_names[chain[0]]
            if kind:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        lock_names[t.id] = kind
    guards = _parse_guard_comments(module.source)

    functions: Dict[str, Dict] = {}
    classes: Dict[str, Dict] = {}
    global_locks: Dict[str, List[int]] = {}

    def record_function(node, qual: str, cls: Optional[str]) -> None:
        rec = {
            "qual": qual, "name": node.name, "cls": cls,
            "line": node.lineno,
            "locked_name": node.name.endswith("_locked"),
            "params": [
                {"name": a.arg,
                 "ann": (".".join(attr_chain(a.annotation))
                         if a.annotation is not None
                         and attr_chain(a.annotation) else None)}
                for a in (node.args.posonlyargs + node.args.args
                          + node.args.kwonlyargs)]
            + ([{"name": node.args.vararg.arg, "vararg": True,
                 "ann": (".".join(attr_chain(node.args.vararg.annotation))
                         if node.args.vararg.annotation is not None
                         and attr_chain(node.args.vararg.annotation)
                         else None)}]
               if node.args.vararg is not None else []),
            "locals": {}, "calls": [], "refs": [],
            "acquires": [], "accesses": [], "self_assigns": [],
            "returns": [],
            # Return annotation: the fallback type when no return
            # expression resolves (``def counter(...) -> Counter``
            # returning through a generic register() helper).
            "ret_ann": (".".join(attr_chain(node.returns))
                        if getattr(node, "returns", None) is not None
                        and attr_chain(node.returns) else None),
            "decorators": [".".join(attr_chain(d))
                           for d in node.decorator_list
                           if attr_chain(d)],
            "mutations": _collect_mutations(node),
        }
        v = _FuncRecorder(rec, lock_names)
        v.run(node)
        for ret in _scoped_returns(node):
            rec["returns"].append(describe_expr(ret, lock_names))
        functions[qual] = rec
        # Nested defs become their own records — no inherited lock
        # context (the R1 nested-def reset, now whole-tree).
        for sub in _scoped_nested_defs(node):
            record_function(sub, f"{qual}.{sub.name}", cls)
        # Lambdas too: a ``lambda obj: self._on_claim(None, obj)``
        # registered as a handler is a deferred body with NO inherited
        # lock context; `cls` rides along so `self` resolves.
        for lam in _scoped_lambdas(node):
            lq = f"<lambda@{lam.lineno}:{lam.col_offset}>"
            lrec = {
                "qual": lq, "name": "<lambda>", "cls": cls,
                "line": lam.lineno, "locked_name": False,
                "params": [{"name": a.arg, "ann": None}
                           for a in (lam.args.posonlyargs + lam.args.args
                                     + lam.args.kwonlyargs)],
                "locals": {}, "calls": [], "refs": [],
                "acquires": [], "accesses": [], "self_assigns": [],
                "returns": [describe_expr(lam.body, lock_names)],
                "ret_ann": None,
            }
            lv = _FuncRecorder(lrec, lock_names)
            lv.visit(lam.body)
            functions[lq] = lrec

    global_insts: Dict[str, Dict] = {}

    for node in module.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            record_function(node, node.name, None)
        elif isinstance(node, ast.ClassDef):
            cinfo: Dict = {
                "bases": [".".join(attr_chain(b)) for b in node.bases
                          if attr_chain(b)],
                "line": node.lineno,
                "class_locks": {},
            }
            for sub in node.body:
                if isinstance(sub, ast.Assign):
                    locks = _find_lock_creations(sub.value, lock_names)
                    if locks:
                        for t in sub.targets:
                            if isinstance(t, ast.Name):
                                cinfo["class_locks"][t.id] = locks
                elif isinstance(sub, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    record_function(sub, f"{node.name}.{sub.name}",
                                    node.name)
            classes[node.name] = cinfo
        elif isinstance(node, ast.Assign):
            locks = _find_lock_creations(node.value, lock_names)
            if locks:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        global_locks[t.id] = locks
            elif isinstance(node.value, ast.Call):
                # Module-global singleton (``FAULTS = FaultRegistry()``,
                # ``_PREPS = _METRICS.counter(...)``): the instance's
                # type is resolved lazily in the <module> pseudo-scope.
                desc = describe_expr(node.value, lock_names)
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        global_insts[t.id] = desc

    # The <module> pseudo-record: a resolution scope for module-level
    # value expressions (global singleton types chase imports and
    # helper returns exactly like function-local code).
    functions["<module>"] = {
        "qual": "<module>", "name": "<module>", "cls": None, "line": 0,
        "locked_name": False, "params": [], "locals": {}, "calls": [],
        "refs": [], "acquires": [], "accesses": [], "self_assigns": [],
        "returns": [], "ret_ann": None,
    }

    facts = {
        "imports": imports,
        "functions": functions,
        "classes": classes,
        "global_locks": global_locks,
        "global_insts": global_insts,
        "guards": {str(k): v for k, v in guards.items()},
        "drflow": _parse_drflow_comments(module.source),
    }
    module._race_facts = facts  # type: ignore[attr-defined]
    return facts


# ---------------------------------------------------------------------------
# Whole-tree resolver (finalize-time)
# ---------------------------------------------------------------------------

_MISSING_CHAIN = object()


@dataclass
class _ClassInfo:
    cid: str                      # "relpath::ClassName"
    relpath: str
    name: str
    bases: List[str]              # raw base chains, resolved lazily
    methods: Dict[str, str] = field(default_factory=dict)  # name -> fid
    lock_attrs: Dict[str, List[str]] = field(default_factory=dict)
    attr_types: Dict[str, Dict] = field(default_factory=dict)
    guard_ann: Dict[str, str] = field(default_factory=dict)
    attr_lines: Dict[str, int] = field(default_factory=dict)


class TreeResolver:
    """The whole-tree symbol/type/call resolver R9-R11 share. Built in
    finalize from every module's facts; all resolution rules live here
    so fixtures can target them one at a time."""

    def __init__(self, modules: Dict[str, Dict]):
        self.modules = modules              # relpath -> facts
        self.dotted: Dict[str, str] = {}    # dotted module name -> relpath
        self.classes: Dict[str, _ClassInfo] = {}
        self.funcs: Dict[str, Dict] = {}    # fid -> record
        self.func_mod: Dict[str, str] = {}  # fid -> relpath
        self.methods_by_name: Dict[str, List[str]] = {}
        self.returns: Dict[str, Optional[Dict]] = {}   # fid -> type
        self.subclasses: Dict[str, List[str]] = {}  # cid -> direct subs
        # (fid, id(call record)) -> resolve_call result; call records
        # live as long as the resolver (facts are held by `modules`).
        self._call_memo: Dict[Tuple[str, int],
                              Tuple[List[str], bool]] = {}
        # (fid, id(desc)) -> (desc, result) for top-level resolve_type.
        # The DESC REFERENCE in the value is load-bearing: it pins the
        # descriptor alive so its id can never be reused by a transient
        # dict (the id-keyed-memo poisoning bug class).
        self._type_memo: Dict[Tuple[str, int],
                              Tuple[Dict, Optional[Dict]]] = {}
        # (chain tuple, rel) -> class id; the unique-global-name
        # fallback scans every class otherwise (hot under drflow).
        self._chain_memo: Dict[Tuple[Tuple[str, ...], Optional[str]],
                               Optional[str]] = {}
        # Type results move during _build (return-summary fixpoints,
        # ctor-arg flow update the tables resolve_type reads): the
        # memo only switches on once the tables are final.
        self._memo_enabled = False
        self._build()
        self._memo_enabled = True

    # -- construction -------------------------------------------------------

    @staticmethod
    def _dotted_name(relpath: str) -> str:
        p = relpath[:-3] if relpath.endswith(".py") else relpath
        if p.endswith("/__init__"):
            p = p[: -len("/__init__")]
        return p.replace("/", ".")

    def _build(self) -> None:
        # Sorted: the modules dict's insertion order varies between
        # warm/cold/parallel runs; resolution (methods_by_name order,
        # unique-name class fallbacks) must not.
        for rel, facts in sorted(self.modules.items()):
            self.dotted[self._dotted_name(rel)] = rel
            for cname, cinfo in facts["classes"].items():
                cid = f"{rel}::{cname}"
                info = _ClassInfo(cid=cid, relpath=rel, name=cname,
                                  bases=list(cinfo.get("bases", ())))
                for attr, lines in cinfo.get("class_locks", {}).items():
                    info.lock_attrs[attr] = [f"{rel}:{ln}" for ln in lines]
                self.classes[cid] = info
            for qual, rec in facts["functions"].items():
                fid = f"{rel}::{qual}"
                self.funcs[fid] = rec
                self.func_mod[fid] = rel
        # Attach methods + attr tables.
        for rel, facts in sorted(self.modules.items()):
            guards = {int(k): v for k, v in facts.get("guards", {}).items()}
            for qual, rec in facts["functions"].items():
                fid = f"{rel}::{qual}"
                cls = rec.get("cls")
                if cls and qual == f"{cls}.{rec['name']}":
                    cid = f"{rel}::{cls}"
                    info = self.classes.get(cid)
                    if info is not None:
                        info.methods[rec["name"]] = fid
                        self.methods_by_name.setdefault(
                            rec["name"], []).append(fid)
                for sa in rec.get("self_assigns", ()):
                    cid = f"{rel}::{cls}" if cls else None
                    info = self.classes.get(cid) if cid else None
                    if info is None:
                        continue
                    attr, val = sa["attr"], sa["value"]
                    info.attr_lines.setdefault(attr, sa["line"])
                    locks = self._desc_lock_lines(val)
                    if locks:
                        info.lock_attrs.setdefault(attr, [])
                        for ln in locks:
                            site = f"{rel}:{ln}"
                            if site not in info.lock_attrs[attr]:
                                info.lock_attrs[attr].append(site)
                    ctor = self._desc_ctor(val)
                    if ctor is not None:
                        info.attr_types.setdefault(attr, ctor)
                    ann = guards.get(sa["line"]) or guards.get(
                        sa["line"] - 1)
                    if ann and attr not in info.guard_ann:
                        info.guard_ann[attr] = ann
        # Subclass index (class-hierarchy analysis): a call resolved to
        # a BASE-typed receiver must also consider every override a
        # subclass supplies — the annotation says TpuInfoBackend, the
        # runtime object is a FakeBackend whose chips() takes its own
        # lock. Built after every class is registered so forward
        # references resolve.
        for cid, info in self.classes.items():
            for b in info.bases:
                bid = self._resolve_class_chain(b.split("."),
                                                rel=info.relpath)
                if bid and bid in self.classes:
                    self.subclasses.setdefault(bid, []).append(cid)
        # Return-type summaries: a couple of fixpoint rounds is plenty
        # for the helper patterns the tree uses (_shard_for, _lock_for).
        self.returns = {fid: None for fid in self.funcs}
        for _ in range(3):
            changed = False
            for fid, rec in self.funcs.items():
                if self.returns[fid] is not None:
                    continue
                for rdesc in rec.get("returns", ()):
                    t = self.resolve_type(rdesc, fid)
                    if t is not None:
                        self.returns[fid] = t
                        changed = True
                        break
            if not changed:
                break
        # Return-annotation fallback: a factory whose return expression
        # funnels through a generic helper (``return self.register(
        # Counter(...))``) still declares what it hands back.
        for fid, rec in self.funcs.items():
            ann = rec.get("ret_ann")
            if self.returns.get(fid) is None and ann:
                cid = self._resolve_class_chain(
                    ann.split("."), rel=self.func_mod[fid])
                if cid:
                    self.returns[fid] = {"cls": cid}
        # Post-returns pass: attribute types that only resolve through
        # helper returns or parameter annotations (``self._rl =
        # default_controller_rate_limiter()``, ``self._limiters =
        # <vararg param>``) — needs the return summaries above.
        for fid, rec in self.funcs.items():
            info = self.class_of(fid)
            if info is None:
                continue
            for sa in rec.get("self_assigns", ()):
                attr = sa["attr"]
                if attr in info.attr_types or attr in info.lock_attrs:
                    continue
                t = self.resolve_type(sa["value"], fid)
                if t is None:
                    continue
                if "cls" in t:
                    info.attr_types[attr] = {"cls": t["cls"]}
                elif "container_of" in t:
                    info.attr_types[attr] = {"elem": t["container_of"]}
                elif "lock" in t:
                    info.lock_attrs[attr] = list(t["lock"])
        self._ctor_arg_flow()
        self._callback_flow()

    def _ctor_arg_flow(self) -> None:
        """Constructor-argument flow: ``self.x = <param>`` in a class's
        ``__init__`` takes the lock/class of the argument passed at
        each resolved construction site — the informer hands its RLock
        to ``_Lister``, the driver hands a ``Flock`` to ``SharedFlock``;
        the receiving attribute inherits the creation sites."""
        for _ in range(2):
            changed = False
            for fid, rec in self.funcs.items():
                for call in rec.get("calls", ()):
                    fn = self.resolve_type(call["expr"], fid)
                    if not fn or "clsref" not in fn:
                        continue
                    info = self.classes.get(fn["clsref"])
                    init = (self.class_method(info, "__init__")
                            if info else None)
                    if init is None:
                        continue
                    irec = self.funcs[init]
                    params = [p["name"] for p in irec["params"]][1:]
                    p2a: Dict[str, List[str]] = {}
                    for sa in irec.get("self_assigns", ()):
                        v = sa["value"]
                        if v.get("t") == "name" and v["id"] in params:
                            p2a.setdefault(v["id"], []).append(sa["attr"])
                    if not p2a:
                        continue
                    bound: Dict[str, Dict] = dict(
                        zip(params, call.get("args", ())))
                    bound.update(call.get("kwargs", {}))
                    for pname, attrs in p2a.items():
                        adesc = bound.get(pname)
                        if adesc is None:
                            continue
                        at = self.resolve_type(adesc, fid)
                        if at is None:
                            continue
                        for attr in attrs:
                            if "lock" in at:
                                cur = info.lock_attrs.setdefault(attr, [])
                                for s in at["lock"]:
                                    if s not in cur:
                                        cur.append(s)
                                        changed = True
                            elif "cls" in at \
                                    and attr not in info.attr_types:
                                info.attr_types[attr] = {"cls": at["cls"]}
                                changed = True
            if not changed:
                break

    def _callback_flow(self) -> None:
        """Callback-registry points-to: a bound method handed to
        ``informer.on_add(self._pod_added)`` is appended into the
        informer's handler list and invoked later as ``h(*args)`` under
        the informer's lock — an acquisition path no direct call graph
        sees. Tracks (a) callables appended/assigned into ``self._X``
        (directly or through the receiving method's parameter) and
        (b) per-parameter callable sets flowing from resolved call
        sites, to a fixpoint; ``_callables_of`` then resolves an
        indirect call expression to its candidate targets."""
        self.attr_callables: Dict[Tuple[str, str], Set[str]] = {}
        self.param_callables: Dict[Tuple[str, str], Set[str]] = {}
        param_sinks: Dict[Tuple[str, str], List[Tuple[str, str]]] = {}
        for fid, rec in self.funcs.items():
            info = self.class_of(fid)
            if info is None:
                continue
            pnames = {p["name"] for p in rec["params"]}
            for sa in rec.get("self_assigns", ()):
                v = sa["value"]
                sink = (info.cid, sa["attr"])
                if v.get("t") == "name" and v["id"] in pnames:
                    param_sinks.setdefault((fid, v["id"]), []).append(sink)
            for call in rec["calls"]:
                e = call["expr"]
                if not (e.get("t") == "attr" and e["attr"] == "append"
                        and e["base"].get("t") == "attr"
                        and e["base"]["base"].get("t") == "name"
                        and e["base"]["base"]["id"] == "self"
                        and call.get("args")):
                    continue
                a0 = call["args"][0]
                sink = (info.cid, e["base"]["attr"])
                if a0.get("t") == "name" and a0["id"] in pnames:
                    param_sinks.setdefault((fid, a0["id"]), []).append(sink)
                else:
                    t = self.resolve_type(a0, fid)
                    if t and "func" in t:
                        self.attr_callables.setdefault(
                            sink, set()).add(t["func"])
        for _ in range(6):  # bounded fixpoint (chains are shallow)
            changed = False
            for fid, rec in self.funcs.items():
                for call in rec["calls"]:
                    args = call.get("args")
                    kwargs = call.get("kwargs")
                    if not args and not kwargs:
                        continue
                    for m in self.resolve_call(call, fid,
                                               chase_callbacks=False)[0]:
                        mrec = self.funcs.get(m)
                        if mrec is None:
                            continue
                        params = [p["name"] for p in mrec["params"]]
                        if mrec.get("cls") and params \
                                and params[0] in ("self", "cls"):
                            params = params[1:]
                        bound = dict(zip(params, args or ()))
                        for k, v in (kwargs or {}).items():
                            if k in params:
                                bound[k] = v
                        for pname, adesc in bound.items():
                            fset = self._callables_of(adesc, fid)
                            if not fset:
                                continue
                            cur = self.param_callables.setdefault(
                                (m, pname), set())
                            if fset - cur:
                                cur |= fset
                                changed = True
                            for sink in param_sinks.get((m, pname), ()):
                                scur = self.attr_callables.setdefault(
                                    sink, set())
                                if fset - scur:
                                    scur |= fset
                                    changed = True
            if not changed:
                break

    def _callables_of(self, desc: Dict, fid: str,
                      depth: int = 0) -> Set[str]:
        """Candidate targets of an indirect-call expression: a bound
        reference, a parameter fed callables at resolved call sites, a
        handler-list attribute, or an element of one."""
        if depth > 6 or desc is None:
            return set()
        t_res = self.resolve_type(desc, fid)
        if t_res is not None and "func" in t_res:
            return {t_res["func"]}
        t = desc.get("t")
        if t == "attr":
            base = self.resolve_type(desc["base"], fid)
            cid = base.get("cls") if base else None
            if cid in self.classes:
                out: Set[str] = set()
                for c in self._mro(self.classes[cid]):
                    out |= self.attr_callables.get(
                        (c.cid, desc["attr"]), set())
                return out
            return set()
        if t == "name":
            nm = desc["id"]
            rec = self.funcs.get(fid)
            if rec is None:
                return set()
            if any(p["name"] == nm for p in rec["params"]):
                return set(self.param_callables.get((fid, nm), ()))
            for b in rec["locals"].get(nm, ()):
                out = self._callables_of(b, fid, depth + 1)
                if out:
                    return out
            return set()
        if t == "iter":
            return self._callables_of(desc["of"], fid, depth + 1)
        if t == "sub":
            return self._callables_of(desc["base"], fid, depth + 1)
        if t == "lambda":
            rel = self.func_mod.get(fid)
            lfid = f"{rel}::<lambda@{desc['line']}:{desc['col']}>"
            if lfid in self.funcs:
                return {lfid}
        if t == "call":
            # functools.partial(self._on_evt, key): the partial IS the
            # callable — its target is the first argument.
            chain = self._desc_chain(desc.get("func", {})) or []
            if chain and chain[-1] == "partial" and desc.get("args"):
                return self._callables_of(desc["args"][0], fid,
                                          depth + 1)
        return set()

    @staticmethod
    def _desc_lock_lines(desc: Dict) -> List[int]:
        t = desc.get("t")
        if t == "lock":
            return [desc["line"]]
        if t == "container":
            return list(desc.get("locks", ()))
        if t == "call":
            return list(desc.get("arg_locks", ()))
        return []

    def _desc_ctor(self, desc: Dict) -> Optional[Dict]:
        """{'cls': cid} or {'elem': cid} when the descriptor constructs
        (a container of) a tree-known class."""
        t = desc.get("t")
        if t == "call":
            chain = self._desc_chain(desc["func"])
            return None if chain is None else self._ctor_of(chain)
        if t == "container":
            for e in desc.get("elems", ()):
                inner = self._desc_ctor(e)
                if inner and "cls" in inner:
                    return {"elem": inner["cls"]}
        return None

    def _ctor_of(self, chain: List[str]) -> Optional[Dict]:
        cid = self._resolve_class_chain(chain)
        return {"cls": cid} if cid else None

    @staticmethod
    def _desc_chain(desc: Dict) -> Optional[List[str]]:
        out: List[str] = []
        d = desc
        while True:
            t = d.get("t")
            if t == "attr":
                out.append(d["attr"])
                d = d["base"]
            elif t == "name":
                out.append(d["id"])
                return list(reversed(out))
            else:
                return None

    # -- symbol resolution --------------------------------------------------

    def _module_symbol(self, rel: str, name: str, depth: int = 0):
        """('class', cid) | ('func', fid) | ('lock', [sites]) |
        ('module', relpath) | None — following import aliases across
        the tree (bounded depth: import cycles must not hang lint)."""
        if depth > 4 or rel not in self.modules:
            return None
        facts = self.modules[rel]
        if name in facts["classes"]:
            return ("class", f"{rel}::{name}")
        if name in facts["functions"] and "." not in name:
            return ("func", f"{rel}::{name}")
        if name in facts["global_locks"]:
            return ("lock", [f"{rel}:{ln}"
                             for ln in facts["global_locks"][name]])
        if name in facts.get("global_insts", {}):
            return ("inst", (rel, facts["global_insts"][name]))
        target = facts["imports"].get(name)
        if target is None:
            return None
        if target in self.dotted:
            return ("module", self.dotted[target])
        if "." in target:
            mod, _, leaf = target.rpartition(".")
            if mod in self.dotted:
                return self._module_symbol(self.dotted[mod], leaf,
                                           depth + 1)
        return None

    def _resolve_class_chain(self, chain: List[str],
                             rel: Optional[str] = None) -> Optional[str]:
        """ClassName / mod.ClassName chains to a class id; with no
        module context, fall back to a unique global name match."""
        key = (tuple(chain), rel)
        hit = self._chain_memo.get(key, _MISSING_CHAIN)
        if hit is not _MISSING_CHAIN:
            return hit
        out = self._resolve_class_chain_uncached(chain, rel)
        self._chain_memo[key] = out
        return out

    def _resolve_class_chain_uncached(self, chain: List[str],
                                      rel: Optional[str] = None
                                      ) -> Optional[str]:
        if rel is not None:
            sym = self._module_symbol(rel, chain[0])
            if sym is not None:
                kind, val = sym
                if kind == "class" and len(chain) == 1:
                    return val
                if kind == "module" and len(chain) == 2:
                    sub = self._module_symbol(val, chain[1])
                    if sub and sub[0] == "class":
                        return sub[1]
            if len(chain) == 1:
                local = f"{rel}::{chain[0]}"
                if local in self.classes:
                    return local
        name = chain[-1]
        cands = [cid for cid in self.classes
                 if cid.endswith(f"::{name}")]
        return cands[0] if len(cands) == 1 else None

    def class_of(self, fid: str) -> Optional[_ClassInfo]:
        rec = self.funcs.get(fid)
        if rec is None or not rec.get("cls"):
            return None
        return self.classes.get(f"{self.func_mod[fid]}::{rec['cls']}")

    def _mro(self, info: _ClassInfo, seen=None) -> List[_ClassInfo]:
        seen = seen if seen is not None else set()
        if info.cid in seen:
            return []
        seen.add(info.cid)
        out = [info]
        for b in info.bases:
            bid = self._resolve_class_chain(b.split("."),
                                            rel=info.relpath)
            if bid and bid in self.classes:
                out.extend(self._mro(self.classes[bid], seen))
        return out

    def class_lock_attrs(self, info: _ClassInfo) -> Dict[str, List[str]]:
        out: Dict[str, List[str]] = {}
        for c in reversed(self._mro(info)):
            out.update(c.lock_attrs)
        return out

    def class_method(self, info: _ClassInfo, name: str) -> Optional[str]:
        for c in self._mro(info):
            fid = c.methods.get(name)
            if fid is not None:
                return fid
        return None

    def _descendants(self, cid: str) -> Set[str]:
        out: Set[str] = set()
        stack = list(self.subclasses.get(cid, ()))
        while stack:
            c = stack.pop()
            if c in out:
                continue
            out.add(c)
            stack.extend(self.subclasses.get(c, ()))
        return out

    def class_method_cha(self, info: _ClassInfo, name: str) -> List[str]:
        """Dispatch candidates for `info`-typed receiver calling `name`:
        the MRO resolution PLUS every override (or first definition) a
        transitive subclass supplies — the receiver's static type is an
        upper bound, not the runtime class."""
        out: List[str] = []
        m = self.class_method(info, name)
        if m is not None:
            out.append(m)
        for sub in sorted(self._descendants(info.cid)):
            sm = self.classes[sub].methods.get(name)
            if sm is not None and sm not in out:
                out.append(sm)
        return out

    def class_attr_type(self, info: _ClassInfo, attr: str) -> Optional[Dict]:
        for c in self._mro(info):
            t = c.attr_types.get(attr)
            if t is not None:
                return t
        return None

    def class_guard_ann(self, info: _ClassInfo, attr: str) -> Optional[str]:
        for c in self._mro(info):
            g = c.guard_ann.get(attr)
            if g is not None:
                return g
        return None

    # -- type resolution ----------------------------------------------------

    def resolve_type(self, desc: Dict, fid: str,
                     depth: int = 0) -> Optional[Dict]:
        """{'cls': cid} | {'lock': [sites]} for an expression descriptor
        evaluated in `fid`'s scope, else None (unknown). Top-level
        resolutions are memoized (descriptors are immutable once
        extracted; the memo holds the desc so id-reuse cannot alias)."""
        if depth > 6 or desc is None:
            return None
        if depth == 0 and self._memo_enabled:
            key = (fid, id(desc))
            hit = self._type_memo.get(key)
            if hit is not None and hit[0] is desc:
                return hit[1]
            out = self._resolve_type_inner(desc, fid, 0)
            self._type_memo[key] = (desc, out)
            return out
        return self._resolve_type_inner(desc, fid, depth)

    def _resolve_type_inner(self, desc: Dict, fid: str,
                            depth: int) -> Optional[Dict]:
        rel = self.func_mod.get(fid)
        rec = self.funcs.get(fid)
        if rec is None or rel is None:
            return None
        t = desc.get("t")
        if t == "lock":
            return {"lock": [f"{rel}:{desc['line']}"]}
        if t == "name":
            nm = desc["id"]
            if nm == "self":
                info = self.class_of(fid)
                return {"cls": info.cid} if info else None
            # Own locals, then enclosing-function scopes (closures: a
            # worker nested in a harness captures the harness's lock).
            scope_fid: Optional[str] = fid
            while scope_fid is not None:
                srec = self.funcs.get(scope_fid)
                if srec is None:
                    break
                for b in srec["locals"].get(nm, ()):
                    if b.get("t") == "nested":
                        return {"func": f"{rel}::{b['qual']}"}
                    r = self.resolve_type(b, scope_fid, depth + 1)
                    if r is not None:
                        return r
                for p in srec.get("params", ()):
                    if p["name"] == nm and p.get("ann"):
                        cid = self._resolve_class_chain(
                            p["ann"].split("."), rel=rel)
                        if cid:
                            # ``*limiters: RateLimiter`` annotates the
                            # ELEMENT type; the name binds a tuple.
                            return ({"container_of": cid}
                                    if p.get("vararg") else {"cls": cid})
                qual = srec["qual"]
                scope_fid = (f"{rel}::{qual.rsplit('.', 1)[0]}"
                             if "." in qual else None)
            sym = self._module_symbol(rel, nm)
            if sym is not None:
                kind, val = sym
                if kind == "lock":
                    return {"lock": val}
                if kind == "class":
                    return {"clsref": val}
                if kind == "func":
                    return {"func": val}
                if kind == "module":
                    return {"mod": val}
                if kind == "inst":
                    irel, idesc = val
                    return self.resolve_type(idesc, f"{irel}::<module>",
                                             depth + 1)
            return None
        if t == "attr":
            base = self.resolve_type(desc["base"], fid, depth + 1)
            if base is None:
                return None
            if "cls" in base:
                info = self.classes.get(base["cls"])
                if info is None:
                    return None
                locks = self.class_lock_attrs(info).get(desc["attr"])
                if locks:
                    return {"lock": locks}
                at = self.class_attr_type(info, desc["attr"])
                if at is not None:
                    if "cls" in at:
                        return {"cls": at["cls"]}
                    if "elem" in at:
                        return {"container_of": at["elem"]}
                m = self.class_method(info, desc["attr"])
                if m is not None:
                    decs = self.funcs.get(m, {}).get("decorators") or ()
                    if any(d.split(".")[-1] in ("property",
                                                "cached_property")
                           for d in decs):
                        # Property access: the VALUE is the getter's
                        # return, not a bound method.
                        return self.returns.get(m)
                    return {"func": m, "method": True,
                            "of_cls": info.cid, "mname": desc["attr"]}
                return None
            if "mod" in base:
                sym = self._module_symbol(base["mod"], desc["attr"])
                if sym is not None:
                    kind, val = sym
                    if kind == "lock":
                        return {"lock": val}
                    if kind == "class":
                        return {"clsref": val}
                    if kind == "func":
                        return {"func": val}
                    if kind == "inst":
                        irel, idesc = val
                        return self.resolve_type(
                            idesc, f"{irel}::<module>", depth + 1)
                return None
            return None
        if t == "sub":
            base = self.resolve_type(desc["base"], fid, depth + 1)
            if base and "container_of" in base:
                return {"cls": base["container_of"]}
            if base and "lock" in base:
                # Subscript into a lock container (self._chip_locks[i]):
                # every element shares the container's creation site(s).
                return {"lock": base["lock"]}
            return None
        if t == "iter":
            base = self.resolve_type(desc["of"], fid, depth + 1)
            if base and "container_of" in base:
                return {"cls": base["container_of"]}
            if base and "lock" in base:
                return {"lock": base["lock"]}
            return None
        if t == "call":
            if desc.get("arg_locks"):
                # e.g. self._locks.setdefault(k, threading.Lock()) — the
                # expression yields a lock created at the embedded site.
                return {"lock": [f"{rel}:{ln}"
                                 for ln in desc["arg_locks"]]}
            fn = self.resolve_type(desc["func"], fid, depth + 1)
            if fn is None:
                return None
            if "clsref" in fn:
                return {"cls": fn["clsref"]}
            if "func" in fn:
                return self.returns.get(fn["func"])
            return None
        if t == "container":
            ctor = self._desc_ctor(desc)
            if ctor and "elem" in ctor:
                return {"container_of": ctor["elem"]}
            if desc.get("locks"):
                return None
        return None

    # -- call resolution ----------------------------------------------------

    def resolve_call(self, call: Dict, fid: str,
                     chase_callbacks: bool = True
                     ) -> Tuple[List[str], bool]:
        """(candidate fids, via_fallback): the callee set for a call
        descriptor. Dynamic-dispatch conservatism: an unresolvable
        receiver falls back to every tree class defining the method —
        always for *_locked names, never for builtin-ish names.
        `chase_callbacks=False` is the registry-construction mode (the
        callback fixpoint itself must not consume its own output).
        Memoized per call record: R9 and R11 (callees + edge pass)
        each re-resolve every call, and the resolution chase dominates
        finalize time."""
        if chase_callbacks:
            key = (fid, id(call))
            hit = self._call_memo.get(key)
            if hit is None:
                hit = self._resolve_call_uncached(call, fid, True)
                self._call_memo[key] = hit
            return hit
        return self._resolve_call_uncached(call, fid, False)

    def _resolve_call_uncached(self, call: Dict, fid: str,
                               chase_callbacks: bool
                               ) -> Tuple[List[str], bool]:
        desc = call["expr"]
        fn = self.resolve_type(desc, fid)
        if fn is not None and "func" in fn:
            if fn.get("method") and fn.get("of_cls") in self.classes:
                return self.class_method_cha(
                    self.classes[fn["of_cls"]], fn["mname"]), False
            return [fn["func"]], False
        if fn is None and desc.get("t") == "attr":
            # Receiver resolved to a class that does not itself define
            # the method (abstract protocol): subclasses that do are
            # still dispatch candidates.
            base = self.resolve_type(desc["base"], fid)
            cid = base.get("cls") if base else None
            if cid in self.classes:
                cands = self.class_method_cha(self.classes[cid],
                                              desc["attr"])
                if cands:
                    return cands, False
        if chase_callbacks:
            cbs = self._callables_of(desc, fid)
            if cbs:
                return sorted(cbs), False
        chain = self._desc_chain(desc)
        if chain and len(chain) >= 2:
            name = chain[-1]
            if name.endswith("_locked") or name not in _NO_GLOBAL_FALLBACK:
                cands = self.methods_by_name.get(name, [])
                if name.endswith("_locked") or len(cands) <= 4:
                    return list(cands), True
        return [], False

    def resolve_lock_sites(self, desc: Dict, fid: str) -> List[str]:
        r = self.resolve_type(desc, fid)
        if r is not None and "lock" in r:
            return r["lock"]
        return []


# Draracer and drflow finalize against the SAME tree facts (shared
# through one cache key): building the resolver twice per run would
# double the dominant finalize cost. Keyed by the facts objects'
# identities — a new run's fresh extraction misses, a second rule's
# identical absorption hits.
_RESOLVER_CACHE: Optional[Tuple[frozenset, TreeResolver]] = None


def shared_resolver(tree_facts: Dict[str, Dict]) -> TreeResolver:
    global _RESOLVER_CACHE
    key = frozenset((rel, id(f)) for rel, f in tree_facts.items())
    if _RESOLVER_CACHE is not None and _RESOLVER_CACHE[0] == key:
        return _RESOLVER_CACHE[1]
    res = TreeResolver(tree_facts)
    _RESOLVER_CACHE = (key, res)
    return res


# ---------------------------------------------------------------------------
# R9/R10/R11: one combined rule over the shared extraction
# ---------------------------------------------------------------------------

@register
class RaceAnalysis(Rule):
    """draracer (R9-R11): see the module docstring. One Rule so the
    three passes share a single extraction blob through the facts
    protocol; core filters findings per requested rule id."""

    rule_id = "R9"
    provides = frozenset({"R9", "R10", "R11"})
    title = "interprocedural lockset / guarded-by / lock-order"

    def __init__(self):
        self.tree_facts: Dict[str, Dict] = {}
        self._last_facts: Optional[Dict] = None
        # Populated by finalize, read by the CLI (--locks-report,
        # --check-witness) the same way FaultSiteRegistry feeds
        # --sites-report.
        self.resolver: Optional[TreeResolver] = None
        self.static_edges: Dict[Tuple[str, str], List[str]] = {}
        self.guard_table: List[Dict] = []

    def scan(self, module: Module, ctx: ProjectContext) -> Iterator[Finding]:
        if module.is_test:
            return iter(())
        facts = extract_module(module)
        self._last_facts = facts
        self.tree_facts[module.relpath] = facts
        return iter(())

    def module_facts(self) -> Optional[Dict]:
        facts, self._last_facts = self._last_facts, None
        return facts

    def absorb_facts(self, relpath: str, facts: Dict,
                     ctx: ProjectContext) -> None:
        self.tree_facts[relpath] = facts

    # -- finalize -----------------------------------------------------------

    def finalize(self, ctx: ProjectContext) -> Iterator[Finding]:
        if not self.tree_facts:
            return
        res = self.resolver = shared_resolver(self.tree_facts)
        yield from self._r9(res)
        yield from self._r10(res)
        yield from self._r11(res)

    # -- R9 -----------------------------------------------------------------

    @staticmethod
    def _desc_lockish(desc: Dict) -> bool:
        """Naming-convention heldness: the descriptor's chain tail is a
        data-lock name (the R1-era lexical signal, kept so a lockish
        item that fails to RESOLVE still counts as held for R9 — R11
        separately flags it as unresolvable)."""
        d = desc
        while d.get("t") == "sub":
            d = d["base"]
        if d.get("t") == "attr":
            return is_data_lock_name(d["attr"])
        if d.get("t") == "name":
            return is_data_lock_name(d["id"])
        return d.get("t") == "lock"

    def _holds_lock(self, res: TreeResolver, call: Dict,
                     fid: str) -> bool:
        """Whether a call site holds an actual LOCK. Every with-item is
        on the recorder's held stack (R11 needs that), but an open()/
        ExitStack context manager must not count as a lock for R9 —
        heldness requires a lock creation site, a lock-wrapping class
        (SharedFlock), or at least the lock naming convention."""
        for h in call["held"]:
            if (self._desc_lockish(h)
                    or res.resolve_lock_sites(h, fid)
                    or self._wrapper_methods(res, h, fid)):
                return True
        return False

    def _r9(self, res: TreeResolver) -> Iterator[Finding]:
        # Call edges + per-function entries.
        entries: Dict[str, List[Tuple[str, bool]]] = {f: []
                                                      for f in res.funcs}
        exposed: Set[str] = set()
        locked_calls: List[Tuple[str, Dict, List[str]]] = []
        for fid, rec in res.funcs.items():
            for call in rec["calls"]:
                cands, _ = res.resolve_call(call, fid)
                held = self._holds_lock(res, call, fid)
                for c in cands:
                    if c in entries:
                        entries[c].append((fid, held))
                locked_cands = [c for c in cands
                                if res.funcs[c]["locked_name"]]
                chain = res._desc_chain(call["expr"]) or []
                if not locked_cands and chain \
                        and chain[-1].endswith("_locked"):
                    # Literal *_locked call that did not resolve (R1's
                    # territory) — still participates in propagation.
                    locked_cands = ["<unresolved>"]
                if locked_cands:
                    locked_calls.append((fid, call, locked_cands))
            seen_refs: Set[Tuple[int, str]] = set()
            for ref in rec["refs"]:
                t = res.resolve_type(ref["expr"], fid)
                target = t.get("func") if t else None
                if target is not None:
                    exposed.add(target)
                is_locked_target = (
                    res.funcs.get(target, {}).get("locked_name")
                    if target is not None else ref.get("locked_name"))
                if is_locked_target:
                    chain = res._desc_chain(ref["expr"]) or ["<ref>"]
                    key = (ref["line"], chain[-1])
                    if key in seen_refs:
                        continue
                    seen_refs.add(key)
                    yield Finding(
                        rule="R9", path=res.func_mod[fid],
                        line=ref["line"], col=0,
                        message=f"reference to *_locked function "
                                f"{chain[-1]} escapes its lock context "
                                "(a stored/passed bound reference runs "
                                "later, without the lock — call it "
                                "inside the 'with', or pass a "
                                "non-locked wrapper)")
        for fid in res.funcs:
            if not entries[fid]:
                exposed.add(fid)
        # protected(f) greatest fixpoint: f is protected when it
        # declares the lock (*_locked), or every static entry holds a
        # lock or comes from a protected caller — and f is not exposed.
        protected = {fid: True for fid in res.funcs}
        changed = True
        while changed:
            changed = False
            for fid, rec in res.funcs.items():
                if not protected[fid] or rec["locked_name"]:
                    continue
                ok = fid not in exposed and all(
                    held or protected.get(g, False)
                    for g, held in entries[fid])
                if not ok:
                    protected[fid] = False
                    changed = True
        for fid, call, cands in locked_calls:
            rec = res.funcs[fid]
            if rec["locked_name"] or protected[fid] \
                    or self._holds_lock(res, call, fid):
                continue
            chain = res._desc_chain(call["expr"]) or ["<call>"]
            callee = next((c for c in cands if c != "<unresolved>"), None)
            via = (f" (resolves to {callee})"
                   if callee and not chain[-1].endswith("_locked") else "")
            root = self._unprotected_root(fid, entries, protected,
                                          exposed, res)
            yield Finding(
                rule="R9", path=res.func_mod[fid], line=call["line"],
                col=0,
                message=f"{'.'.join(chain)}(){via} needs its caller's "
                        "lock, but the surrounding function "
                        f"{rec['qual']}() is reachable without one "
                        f"({root}) — acquire the lock, rename the "
                        "chain *_locked, or break the path")

    @staticmethod
    def _wrapper_methods(res: TreeResolver, desc: Dict,
                         fid: str) -> List[str]:
        """acquire/__enter__ methods of the class a non-lock
        acquisition expression resolves to (lock wrappers: SharedFlock,
        Flock) — chased through TACQ so their inner creation sites
        count as held."""
        t = res.resolve_type(desc, fid)
        cid = t.get("cls") if t else None
        info = res.classes.get(cid) if cid else None
        if info is None:
            return []
        out: List[str] = []
        for m in ("acquire", "__enter__"):
            for mf in res.class_method_cha(info, m):
                if mf not in out:
                    out.append(mf)
        return out

    @staticmethod
    def _unprotected_root(fid: str, entries, protected, exposed,
                          res: TreeResolver, limit: int = 6) -> str:
        chain = [fid]
        cur = fid
        for _ in range(limit):
            nxt = next((g for g, held in entries.get(cur, ())
                        if not held and not protected.get(g, True)), None)
            if nxt is None or nxt in chain:
                break
            chain.append(nxt)
            cur = nxt
        chain.reverse()
        names = [f"{res.funcs[f]['qual']}()" for f in chain]
        tag = ("exposed entry point" if cur in exposed
               else "unlocked call path")
        return f"{tag}: " + " -> ".join(names)

    # -- R10 ----------------------------------------------------------------

    def _r10(self, res: TreeResolver) -> Iterator[Finding]:
        # (cid, attr) -> {"guards": {lockattr: n}, "unguarded": [...],
        #                 "declared": n}
        stats: Dict[Tuple[str, str], Dict] = {}
        for fid, rec in res.funcs.items():
            if rec["name"] == "__init__":
                continue
            info = res.class_of(fid)
            for acc in rec["accesses"]:
                base = acc["base"]
                if base == "self":
                    cinfo = info
                else:
                    t = res.resolve_type({"t": "name", "id": base}, fid)
                    cinfo = (res.classes.get(t["cls"])
                             if t and "cls" in t else None)
                if cinfo is None:
                    continue
                lock_attrs = res.class_lock_attrs(cinfo)
                attr = acc["attr"]
                if attr in lock_attrs:
                    continue  # the lock itself is not guarded data
                if res.class_method(cinfo, attr) is not None:
                    continue  # bound-method access, not data
                key = (cinfo.cid, attr)
                st = stats.setdefault(
                    key, {"guards": {}, "unguarded": [], "declared": 0})
                guards_here = [lattr for b, lattr in acc["held"]
                               if b == base and lattr in lock_attrs]
                if guards_here:
                    for g in guards_here:
                        st["guards"][g] = st["guards"].get(g, 0) + 1
                elif rec["locked_name"] and base == "self":
                    st["declared"] += 1
                else:
                    st["unguarded"].append(
                        (res.func_mod[fid], acc["line"], acc["kind"],
                         rec["qual"]))
        self.guard_table = []
        for (cid, attr), st in sorted(stats.items()):
            cinfo = res.classes[cid]
            ann = res.class_guard_ann(cinfo, attr)
            lock_attrs = res.class_lock_attrs(cinfo)
            if not lock_attrs and ann is None:
                continue  # lock-free class: nothing to guard with
            total = (sum(st["guards"].values()) + st["declared"]
                     + len(st["unguarded"]))
            guard: Optional[str] = None
            how = ""
            if ann == "none":
                how = "annotated unguarded"
            elif ann:
                guard = ann.split(".")[-1]
                how = "annotated"
                if guard not in lock_attrs:
                    yield Finding(
                        rule="R10", path=cinfo.relpath,
                        line=cinfo.attr_lines.get(attr, 1), col=0,
                        message=f"GUARDED_BY: {ann} on "
                                f"{cinfo.name}.{attr} names no known "
                                f"lock attribute of {cinfo.name} "
                                f"(known: {sorted(lock_attrs) or '-'})")
                    continue
            elif st["guards"]:
                best = max(st["guards"], key=lambda g: st["guards"][g])
                votes = st["guards"][best] + st["declared"] * (
                    1 if len(lock_attrs) == 1 else 0)
                if votes >= MIN_GUARDED and votes / max(total, 1) \
                        >= GUARD_RATIO:
                    guard, how = best, "inferred"
            self.guard_table.append({
                "class": f"{cinfo.relpath}::{cinfo.name}", "attr": attr,
                "guard": guard, "how": how or "-",
                "guarded": sum(st["guards"].values()) + st["declared"],
                "unguarded": len(st["unguarded"]),
            })
            if guard is None:
                continue
            for path, line, kind, qual in st["unguarded"]:
                word = "write to" if kind == "w" else "read of"
                yield Finding(
                    rule="R10", path=path, line=line, col=0,
                    message=f"{word} {cinfo.name}.{attr} outside its "
                            f"guard self.{guard} ({how}; "
                            f"{self.guard_table[-1]['guarded']} guarded "
                            f"vs {self.guard_table[-1]['unguarded']} "
                            f"unguarded accesses) in {qual}() — acquire "
                            "the lock, or annotate '# GUARDED_BY: none' "
                            "if torn reads are tolerated")

    # -- R11 ----------------------------------------------------------------

    def _r11(self, res: TreeResolver) -> Iterator[Finding]:
        # TACQ: sites each function may acquire, directly or through
        # any call — worklist fixpoint over the call graph.
        direct: Dict[str, Set[str]] = {}
        callees: Dict[str, Set[str]] = {}
        for fid, rec in res.funcs.items():
            d: Set[str] = set()
            cs: Set[str] = set()
            for acq in rec["acquires"]:
                sites = res.resolve_lock_sites(acq["lock"], fid)
                if sites:
                    d.update(sites)
                    continue
                # A lock-WRAPPING object (SharedFlock, Flock): the
                # acquisition delegates to the class's acquire/enter
                # methods — chase them through TACQ like any call.
                wrappers = self._wrapper_methods(res, acq["lock"], fid)
                if wrappers:
                    cs.update(wrappers)
                elif acq["lockish"]:
                    yield Finding(
                        rule="R11", path=res.func_mod[fid],
                        line=acq["line"], col=0,
                        message="acquisition of a data-lock-named "
                                "expression that resolves to no "
                                "creation site — the static lock-order "
                                "graph cannot model it (name the lock "
                                "via an attribute the analyzer can "
                                "trace, or rename it off the *_lock "
                                "convention if it is not a threading "
                                "lock)")
            direct[fid] = d
            for call in rec["calls"]:
                for c in res.resolve_call(call, fid)[0]:
                    cs.add(c)
            callees[fid] = cs
        tacq: Dict[str, Set[str]] = {f: set(direct[f]) for f in res.funcs}
        changed = True
        while changed:
            changed = False
            for fid in res.funcs:
                before = len(tacq[fid])
                for c in callees[fid]:
                    tacq[fid] |= tacq.get(c, set())
                if len(tacq[fid]) != before:
                    changed = True

        def sites_of(desc: Dict, fid: str) -> List[str]:
            """Creation sites a held/acquired expression stands for —
            directly, or through a lock-wrapping class's acquire path."""
            s = res.resolve_lock_sites(desc, fid)
            if s:
                return s
            out: Set[str] = set()
            for m in self._wrapper_methods(res, desc, fid):
                out |= tacq.get(m, set())
            return sorted(out)
        # Edges: nested with-acquisitions + lock-acquiring calls under
        # a held lock. Same-site nesting is the witness's self-nest
        # carve-out (sorted same-class acquisition), not an edge.
        edges: Dict[Tuple[str, str], List[str]] = {}

        def add_edge(src: str, dst: str, where: str) -> None:
            if src != dst:
                edges.setdefault((src, dst), []).append(where)

        for fid, rec in res.funcs.items():
            rel = res.func_mod[fid]
            for acq in rec["acquires"]:
                dsts = sites_of(acq["lock"], fid)
                held_sites = [s for h in acq["held"]
                              for s in sites_of(h, fid)]
                for a in held_sites:
                    for b in dsts:
                        add_edge(a, b, f"{rel}:{acq['line']}")
            for call in rec["calls"]:
                if not call["held"]:
                    continue
                held_sites = [s for h in call["held"]
                              for s in sites_of(h, fid)]
                if not held_sites:
                    continue
                for c in res.resolve_call(call, fid)[0]:
                    for b in tacq.get(c, ()):
                        for a in held_sites:
                            add_edge(a, b, f"{rel}:{call['line']}")
        self.static_edges = edges
        cycle = _find_cycle(set(edges))
        if cycle:
            path = " -> ".join(cycle + [cycle[0]])
            src = cycle[0]
            dst = cycle[1] if len(cycle) > 1 else cycle[0]
            where = edges.get((src, dst), ["?:1"])[0]
            rel, _, line = where.rpartition(":")
            yield Finding(
                rule="R11", path=rel or where, line=int(line or 1), col=0,
                message=f"static lock-order cycle (potential deadlock): "
                        f"{path} — break the inversion or restructure "
                        "the acquisition order")


def _find_cycle(edge_set: Set[Tuple[str, str]]) -> Optional[List[str]]:
    adj: Dict[str, List[str]] = {}
    for s, d in edge_set:
        adj.setdefault(s, []).append(d)
    WHITE, GRAY, BLACK = 0, 1, 2
    color: Dict[str, int] = {}
    stack: List[str] = []

    def dfs(n: str) -> Optional[List[str]]:
        color[n] = GRAY
        stack.append(n)
        for m in adj.get(n, ()):
            c = color.get(m, WHITE)
            if c == GRAY:
                return stack[stack.index(m):]
            if c == WHITE:
                out = dfs(m)
                if out is not None:
                    return out
        stack.pop()
        color[n] = BLACK
        return None

    for n in list(adj):
        if color.get(n, WHITE) == WHITE:
            out = dfs(n)
            if out is not None:
                return out
    return None


# ---------------------------------------------------------------------------
# Witness cross-validation (the lint.sh / race.sh observed⊆static gate)
# ---------------------------------------------------------------------------

def check_witness(rule: RaceAnalysis,
                  observed: Sequence[Tuple[str, str]]) -> List[str]:
    """Every runtime-observed lock-order edge must be explained by the
    static graph (observed ⊆ static, site-keyed). An unexplained edge
    means the call graph under-approximates — the gate FAILS so the
    model is fixed rather than quietly trusted. Returns violation
    lines (empty = validated)."""
    static = set(rule.static_edges)
    nodes = {n for e in static for n in e}
    # Sites the analyzer discovered at all (a lock class can exist with
    # no outgoing/incoming static edges yet).
    if rule.resolver is not None:
        for rel, facts in rule.resolver.modules.items():
            for rec in facts["functions"].values():
                for sa in rec.get("self_assigns", ()):
                    for ln in TreeResolver._desc_lock_lines(sa["value"]):
                        nodes.add(f"{rel}:{ln}")
                # Function-local creations too (a drmc scenario's
                # truth_lock): misdiagnosing their edges as "unknown
                # site" would send the maintainer hunting outside the
                # tree instead of at the call graph.
                for descs in rec.get("locals", {}).values():
                    for d in descs:
                        for ln in TreeResolver._desc_lock_lines(d):
                            nodes.add(f"{rel}:{ln}")
            for lines in facts.get("global_locks", {}).values():
                nodes.update(f"{rel}:{ln}" for ln in lines)
            for cinfo in facts.get("classes", {}).values():
                for lines in cinfo.get("class_locks", {}).values():
                    nodes.update(f"{rel}:{ln}" for ln in lines)
    out: List[str] = []
    for src, dst in observed:
        if (src, dst) in static:
            continue
        missing = [n for n in (src, dst) if n not in nodes]
        if missing:
            out.append(
                f"runtime edge {src} -> {dst}: site(s) "
                f"{', '.join(missing)} unknown to the static analyzer "
                "(lock created outside the scanned tree, or the "
                "creation expression is not traced)")
        else:
            out.append(
                f"runtime edge {src} -> {dst} is not in the static "
                "lock-order graph — the call graph under-approximates "
                "this acquisition path")
    return out


def locks_report(rule: RaceAnalysis) -> List[Dict]:
    """The --locks-report table (mirrors --sites-report): one row per
    (class, attribute) the guarded-by pass considered."""
    return list(rule.guard_table)
