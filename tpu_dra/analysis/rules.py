"""dralint rules: the project invariants, machine-checked.

The rule set encodes the ownership and concurrency discipline PRs 1-4
rely on (SURVEY §§8-12). Naming conventions the rules key on:

- **data locks** are attributes/names matching ``*_lock`` / ``*_locks``
  (or exactly ``lock``), plus condition variables ``*_cond``. They are
  hold-time-bounded: no blocking work inside their ``with`` bodies.
- **operation gates** — ``Flock`` file locks (``_flock``), the flock's
  in-process serializer (``_tlock``), spawn slots — are long-held BY
  DESIGN and deliberately do not match the data-lock pattern; the
  runtime lock witness (infra/lockwitness.py) still watches them.
- ``*_locked``-suffixed functions assert "my caller holds the lock".

All rules are lexical: they see one function at a time and do not chase
data flow across call boundaries. That is the point — the conventions
are designed so that the invariant is CHECKABLE at the call site, and
the rules fail loudly where the convention is skipped, not silently
where an alias laundered a view through a helper.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Set, Tuple

from tpu_dra.analysis.core import (
    Finding, Module, ProjectContext, Rule, register,
)

# ---------------------------------------------------------------------------
# Shared AST helpers
# ---------------------------------------------------------------------------


def attr_chain(node: ast.AST) -> List[str]:
    """Dotted-name components of an Attribute/Name chain, looking through
    subscripts and calls: ``self._informers["x"].lister.list`` ->
    ``["self", "_informers", "lister", "list"]``."""
    out: List[str] = []
    while True:
        if isinstance(node, ast.Attribute):
            out.append(node.attr)
            node = node.value
        elif isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Name):
            out.append(node.id)
            break
        else:
            break
    return list(reversed(out))


def _norm(name: str) -> str:
    return name.lstrip("_").lower()


def is_data_lock_name(name: str) -> bool:
    n = _norm(name)
    return (n in ("lock", "locks", "cond")
            or n.endswith(("_lock", "_locks", "_cond")))


def is_cond_name(name: str) -> bool:
    n = _norm(name)
    return n == "cond" or n.endswith("_cond")


def lockish_context(item: ast.withitem) -> Optional[str]:
    """The lock's display name when a with-item acquires a data lock."""
    chain = attr_chain(item.context_expr)
    if chain and is_data_lock_name(chain[-1]):
        return ".".join(chain)
    return None


def base_name(node: ast.AST) -> Optional[str]:
    """The root Name of an Attribute/Subscript chain (``pod`` for
    ``pod["spec"]["nodeName"]``), or None."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def module_imports(tree: ast.AST) -> Dict[str, str]:
    """name -> dotted target for module-level imports (shared by the
    draracer extraction and the laundering predicate below)."""
    out: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                out[alias.asname or alias.name.split(".")[0]] = alias.name
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for alias in node.names:
                out[alias.asname or alias.name] = \
                    f"{node.module}.{alias.name}"
    return out


def is_laundering_chain(chain: List[str],
                        imports: Optional[Dict[str, str]] = None) -> bool:
    """THE sanctioned view-laundering predicate (SURVEY §10/§20),
    shared by R3 and drflow R13: ``copy.deepcopy`` and the JSON-shaped
    fast path ``json_deepcopy`` (k8s.client) turn a zero-copy informer
    view into a private object. Both spellings are recognized directly
    and through module import aliases (``from copy import deepcopy as
    dc``; ``import copy as c; c.deepcopy``) when the module's import
    map is supplied."""
    if not chain:
        return False
    dotted = ".".join(chain)
    if imports and chain[0] in imports:
        dotted = ".".join([imports[chain[0]], *chain[1:]])
    parts = dotted.split(".")
    return (parts[-1] == "json_deepcopy"
            or parts[-2:] == ["copy", "deepcopy"]
            or parts == ["deepcopy"])


# ---------------------------------------------------------------------------
# R1/R2 shared visitor: lexical lock context
# ---------------------------------------------------------------------------

class _LockContextVisitor(ast.NodeVisitor):
    """Tracks, per lexical position, which data locks the surrounding
    code provably holds: enclosing ``with *_lock`` bodies plus an
    enclosing ``*_locked`` function. A nested non-``_locked`` function
    body runs LATER, not under the lock, so entering one clears the
    stack (callbacks defined under a lock are not 'under the lock')."""

    def __init__(self, module: Module, ctx: ProjectContext):
        self.module = module
        self.ctx = ctx
        self.lock_stack: List[str] = []
        self.func_stack: List[str] = []
        # Lexically inside an ``async def`` body: blocking work here
        # stalls the event loop, not just a lock's waiters (the R2
        # coroutine check, SURVEY §21). A nested sync def resets it the
        # same way it resets the lock stack — its body runs when
        # called, which for the narrow lexical check is "elsewhere"
        # (typically on an executor or as a callback).
        self.coro_depth = 0
        self.findings: List[Finding] = []

    # -- scope handling -----------------------------------------------------

    def _visit_function(self, node) -> None:
        saved = self.lock_stack
        saved_coro = self.coro_depth
        self.lock_stack = ([f"{node.name}()"]
                           if node.name.endswith("_locked") else [])
        self.coro_depth = (self.coro_depth + 1
                           if isinstance(node, ast.AsyncFunctionDef) else 0)
        self.func_stack.append(node.name)
        self.generic_visit(node)
        self.func_stack.pop()
        self.lock_stack = saved
        self.coro_depth = saved_coro

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    def visit_Lambda(self, node: ast.Lambda) -> None:
        saved = self.lock_stack
        saved_coro = self.coro_depth
        self.lock_stack = []
        self.coro_depth = 0
        self.generic_visit(node)
        self.lock_stack = saved
        self.coro_depth = saved_coro

    def in_coroutine(self) -> bool:
        return self.coro_depth > 0

    def visit_With(self, node: ast.With) -> None:
        held = [lockish_context(item) for item in node.items]
        held = [h for h in held if h]
        for item in node.items:
            self.visit(item)
        self.lock_stack.extend(held)
        for stmt in node.body:
            self.visit(stmt)
        if held:
            del self.lock_stack[-len(held):]

    def holds_lock(self) -> bool:
        return bool(self.lock_stack)

    def emit(self, rule: str, node: ast.AST, message: str) -> None:
        self.findings.append(Finding(
            rule=rule, path=self.module.relpath, line=node.lineno,
            col=node.col_offset, message=message))


@register
class LockedCallDiscipline(Rule):
    """R1: ``*_locked`` functions may only be called with the lock
    provably held — from a ``with *_lock`` body or from another
    ``*_locked`` function."""

    rule_id = "R1"
    title = "locked-call discipline"

    class _V(_LockContextVisitor):
        def visit_Call(self, node: ast.Call) -> None:
            chain = attr_chain(node.func)
            if chain and chain[-1].endswith("_locked"):
                if not self.holds_lock():
                    self.emit("R1", node,
                              f"{chain[-1]}() called without holding a "
                              "lock (call it from a 'with *_lock' body "
                              "or from another *_locked method)")
            self.generic_visit(node)

    def scan(self, module: Module, ctx: ProjectContext) -> Iterator[Finding]:
        v = self._V(module, ctx)
        v.visit(module.tree)
        return iter(v.findings)


# -- R2: blocking work under a data lock ------------------------------------

_CLIENT_VERBS = {"get", "list", "create", "update", "delete", "patch",
                 "watch", "update_status", "list_with_rv", "request"}
_MUTEX_WAITERS = {"wait", "communicate"}


def blocking_reason(node: ast.Call) -> Optional[str]:
    """Why this call blocks, or None. Deliberately conservative: plain
    file I/O is allowed (checkpoint stores under the state lock are the
    crash-consistency design), condition-variable waits release the
    lock they guard, and thread-safe in-memory work is fine."""
    chain = attr_chain(node.func)
    if not chain:
        return None
    last = chain[-1]
    recv = chain[:-1]
    if chain[-2:] == ["time", "sleep"] or chain == ["sleep"]:
        return "time.sleep"
    if chain[0] == "subprocess":
        return f"subprocess.{last} (fork/exec)"
    if last == "Popen":
        return "Popen (fork/exec)"
    if chain[0] == "socket" and last in ("socket", "create_connection"):
        return f"socket.{last}"
    if chain[0] == "fcntl" and last in ("flock", "lockf"):
        return f"fcntl.{last} (file-lock syscall)"
    if chain[-2:] == ["vfs", "flock"]:
        return "vfs.flock (file-lock syscall behind the durable-op seam)"
    if chain[0] == "os" and last in ("system", "popen", "waitpid"):
        return f"os.{last}"
    if last in _MUTEX_WAITERS and recv:
        if is_cond_name(recv[-1]):
            return None  # Condition.wait releases the lock it guards
        return f".{last}() (blocks the holder)"
    if last == "join" and recv and not node.args:
        # str.join always takes a positional iterable; a thread/process
        # join takes none (timeout is keyword-only in this codebase).
        return ".join() (blocks on another thread)"
    if last in _CLIENT_VERBS and any("client" in _norm(c) for c in recv):
        return f"API-client .{last}() (network round-trip w/ retries)"
    return None


def coroutine_blocking_reason(node: ast.Call) -> Optional[str]:
    """Why this call would stall the event loop from a coroutine, or
    None (the R2 coroutine check, SURVEY §21). Everything that blocks
    under a lock blocks the loop too, plus the loop-specific set the
    front-end swap made load-bearing: fdatasync/fsync (the journal's
    group commit), flock (already in the shared set), Future.result()
    and Event/lock acquire waits — all of which belong on an executor
    (``run_in_executor``), never in an ``async def`` body."""
    reason = blocking_reason(node)
    if reason:
        return reason
    chain = attr_chain(node.func)
    if not chain:
        return None
    last = chain[-1]
    recv = chain[:-1]
    if chain[0] in ("os", "vfs") and last in ("fdatasync", "fsync"):
        return f"{chain[0]}.{last} (durable-sync syscall)"
    if last == "result" and recv:
        return ".result() (blocks the loop on a Future)"
    if last == "acquire" and recv and is_data_lock_name(recv[-1]):
        return ".acquire() on a data lock (blocks the loop)"
    if last in _MUTEX_WAITERS and recv and is_cond_name(recv[-1]):
        # Condition.wait releases ITS lock but still parks the thread —
        # on the loop thread that parks the whole reactor.
        return ".wait() (parks the loop thread)"
    return None


@register
class NoBlockingUnderLock(Rule):
    """R2: no blocking operations inside a ``with *_lock`` body or a
    ``*_locked`` function — sleeps, subprocess spawns, socket/API-client
    verbs and flock syscalls stall every other thread queued on the
    lock (and the watchdog/readiness paths behind them).

    Coroutine family member (SURVEY §21): the same discipline lexically
    inside ``async def`` bodies, where the victim is the event loop —
    flock, fdatasync, ``Future.result()``, lock acquires and the shared
    blocking set must be offloaded to an executor, never awaited-around
    on the loop thread."""

    rule_id = "R2"
    title = "no blocking work under a data lock"

    class _V(_LockContextVisitor):
        def visit_Call(self, node: ast.Call) -> None:
            if self.holds_lock():
                reason = blocking_reason(node)
                if reason:
                    self.emit("R2", node,
                              f"blocking call {reason} while holding "
                              f"{self.lock_stack[-1]}")
            if self.in_coroutine():
                reason = coroutine_blocking_reason(node)
                if reason:
                    self.emit("R2", node,
                              f"blocking call {reason} inside a "
                              "coroutine — it stalls the event loop; "
                              "offload it to an executor "
                              "(run_in_executor)")
            self.generic_visit(node)

    def scan(self, module: Module, ctx: ProjectContext) -> Iterator[Finding]:
        v = self._V(module, ctx)
        v.visit(module.tree)
        return iter(v.findings)


# ---------------------------------------------------------------------------
# R3: zero-copy informer reads are read-only
# ---------------------------------------------------------------------------

_VIEW_TAILS = (("lister", "list"), ("lister", "get"))
_MUTATORS = {"update", "append", "extend", "insert", "setdefault", "pop",
             "popitem", "clear", "remove", "sort", "add", "discard"}
_READERS = {"get", "keys", "values", "items", "copy", "index", "count"}
_PROPAGATORS = {"sorted", "list", "reversed", "iter", "next", "tuple",
                "filter", "enumerate"}


def _is_view_call(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    chain = attr_chain(node.func)
    return (tuple(chain[-2:]) in _VIEW_TAILS
            or (chain and chain[-1] == "get_by_index"))


class _TaintWalker:
    """Statement-order taint tracking within one function: names bound
    to informer-cache views (lister reads, index lookups, watch-event
    payloads in ``copy_events=False`` modules) must not be mutated.
    ``copy.deepcopy`` — or the JSON-shaped fast path
    ``k8s.client.json_deepcopy`` — launders a view into a private
    object."""

    def __init__(self, module: Module, zero_copy_events: bool,
                 imports: Optional[Dict[str, str]] = None):
        self.module = module
        self.zero_copy_events = zero_copy_events
        self.imports = imports
        self.findings: List[Finding] = []

    # -- expression classification -----------------------------------------

    def _tainted_expr(self, node: ast.AST, tainted: Set[str]) -> bool:
        if _is_view_call(node):
            return True
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, (ast.Attribute, ast.Subscript)):
            base = base_name(node)
            return base in tainted if base else False
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if is_laundering_chain(chain, self.imports):
                return False  # the sanctioned escape hatches
            if chain and chain[-1] in _PROPAGATORS and len(chain) == 1:
                return any(self._tainted_expr(a, tainted)
                           for a in node.args)
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _READERS):
                return self._tainted_expr(node.func.value, tainted)
            return False
        if isinstance(node, ast.BoolOp):
            return any(self._tainted_expr(v, tainted) for v in node.values)
        if isinstance(node, ast.IfExp):
            return (self._tainted_expr(node.body, tainted)
                    or self._tainted_expr(node.orelse, tainted))
        return False

    # -- statement walk ------------------------------------------------------

    def run(self, fn) -> None:
        tainted: Set[str] = set()
        if self.zero_copy_events and fn.name.startswith("_on_"):
            args = fn.args
            for a in (args.posonlyargs + args.args + args.kwonlyargs):
                if a.arg not in ("self", "cls"):
                    tainted.add(a.arg)
        self._walk(fn.body, tainted)

    def _taint_target(self, target: ast.AST, is_view: bool,
                      tainted: Set[str]) -> None:
        if isinstance(target, ast.Name):
            (tainted.add if is_view else tainted.discard)(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._taint_target(elt, is_view, tainted)

    def _check_write_target(self, target: ast.AST, tainted: Set[str],
                            what: str) -> None:
        if isinstance(target, (ast.Attribute, ast.Subscript)):
            base = base_name(target)
            if base and base in tainted:
                self.findings.append(Finding(
                    rule="R3", path=self.module.relpath,
                    line=target.lineno, col=target.col_offset,
                    message=f"{what} on '{base}', a zero-copy informer "
                            "view (copy.deepcopy it before writing — "
                            "SURVEY §10 ownership rule)"))

    def _check_mutator_calls(self, node: ast.AST, tainted: Set[str]) -> None:
        for call in ast.walk(node):
            if (isinstance(call, ast.Call)
                    and isinstance(call.func, ast.Attribute)
                    and call.func.attr in _MUTATORS):
                base = base_name(call.func.value)
                if base and base in tainted:
                    self.findings.append(Finding(
                        rule="R3", path=self.module.relpath,
                        line=call.lineno, col=call.col_offset,
                        message=f".{call.func.attr}() on '{base}', a "
                                "zero-copy informer view (copy.deepcopy "
                                "it before mutating)"))

    def _walk(self, stmts, tainted: Set[str]) -> None:
        for stmt in stmts:
            if isinstance(stmt, ast.Assign):
                is_view = self._tainted_expr(stmt.value, tainted)
                for t in stmt.targets:
                    self._check_write_target(t, tainted, "assignment")
                self._check_mutator_calls(stmt.value, tainted)
                for t in stmt.targets:
                    self._taint_target(t, is_view, tainted)
            elif isinstance(stmt, ast.AugAssign):
                self._check_write_target(stmt.target, tainted,
                                         "augmented assignment")
                self._check_mutator_calls(stmt.value, tainted)
            elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                self._check_write_target(stmt.target, tainted, "assignment")
                self._taint_target(stmt.target,
                                   self._tainted_expr(stmt.value, tainted),
                                   tainted)
            elif isinstance(stmt, ast.Delete):
                for t in stmt.targets:
                    self._check_write_target(t, tainted, "del")
            elif isinstance(stmt, ast.For):
                self._check_mutator_calls(stmt.iter, tainted)
                self._taint_target(stmt.target,
                                   self._tainted_expr(stmt.iter, tainted),
                                   tainted)
                self._walk(stmt.body, tainted)
                self._walk(stmt.orelse, tainted)
            elif isinstance(stmt, ast.While):
                self._check_mutator_calls(stmt.test, tainted)
                self._walk(stmt.body, tainted)
                self._walk(stmt.orelse, tainted)
            elif isinstance(stmt, ast.If):
                self._check_mutator_calls(stmt.test, tainted)
                self._walk(stmt.body, tainted)
                self._walk(stmt.orelse, tainted)
            elif isinstance(stmt, ast.With):
                for item in stmt.items:
                    self._check_mutator_calls(item.context_expr, tainted)
                    if item.optional_vars is not None:
                        self._taint_target(
                            item.optional_vars,
                            self._tainted_expr(item.context_expr, tainted),
                            tainted)
                self._walk(stmt.body, tainted)
            elif isinstance(stmt, ast.Try):
                self._walk(stmt.body, tainted)
                for h in stmt.handlers:
                    self._walk(h.body, tainted)
                self._walk(stmt.orelse, tainted)
                self._walk(stmt.finalbody, tainted)
            elif isinstance(stmt, (ast.Expr, ast.Return)):
                if stmt.value is not None:
                    self._check_mutator_calls(stmt.value, tainted)
            # nested defs: a fresh scope, fresh taint — handled by the
            # rule driving one _TaintWalker per FunctionDef.


@register
class ZeroCopyViewsReadOnly(Rule):
    """R3: objects read zero-copy from an informer cache (lister.list /
    lister.get / get_by_index results; handler payloads in modules that
    build ``copy_events=False`` informers) are views of live cache
    state — mutating one corrupts every other reader and the watch-
    event diffing built on the cache."""

    rule_id = "R3"
    title = "zero-copy informer reads are read-only"

    @staticmethod
    def _module_has_zero_copy_events(module: Module) -> bool:
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Call):
                for kw in node.keywords:
                    if (kw.arg == "copy_events"
                            and isinstance(kw.value, ast.Constant)
                            and kw.value.value is False):
                        return True
        return False

    def scan(self, module: Module, ctx: ProjectContext) -> Iterator[Finding]:
        zero_copy = self._module_has_zero_copy_events(module)
        imports = module_imports(module.tree)
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                walker = _TaintWalker(module, zero_copy, imports)
                walker.run(node)
                findings.extend(walker.findings)
        return iter(findings)


# ---------------------------------------------------------------------------
# R4: fault-site registry coverage (both directions)
# ---------------------------------------------------------------------------

@dataclass
class _SiteUse:
    site: str
    path: str
    line: int
    kind: str  # "guard" | "arm"


@register
class FaultSiteRegistry(Rule):
    """R4: every fault-site literal consulted (``FAULTS.check/fires/
    pull``) or armed (``arm/disarm/armed``) must be declared in the
    central ``SITES`` registry (a typo'd site chaos-tests nothing), and
    every registered site must be exercised by at least one chaos walk
    or test AND consulted by at least one production guard — orphans in
    either direction rot the failure model."""

    rule_id = "R4"
    title = "fault-site registry coverage"

    _GUARDS = {"check", "fires", "pull"}
    _ARMS = {"arm", "disarm", "armed"}

    def __init__(self):
        self.uses: List[_SiteUse] = []
        self.local_registered: Dict[str, Set[str]] = {}  # relpath -> sites
        self.exercised: Set[str] = set()
        self.guarded: Set[str] = set()
        self._last_facts: Optional[Dict] = None

    def scan(self, module: Module, ctx: ProjectContext) -> Iterator[Finding]:
        local: Set[str] = set()
        uses: List[_SiteUse] = []
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if not chain or len(chain) < 2:
                continue
            recv_is_faults = any(_norm(c) == "faults" for c in chain[:-1])
            if not recv_is_faults:
                continue
            kind = None
            if chain[-1] in self._GUARDS:
                kind = "guard"
            elif chain[-1] in self._ARMS:
                kind = "arm"
            elif chain[-1] == "register_site":
                if (node.args and isinstance(node.args[0], ast.Constant)
                        and isinstance(node.args[0].value, str)):
                    local.add(node.args[0].value)
                continue
            if kind is None:
                continue
            if not (node.args and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue  # dynamic site expression (chaos rearm loops)
            site = node.args[0].value
            uses.append(_SiteUse(site=site, path=module.relpath,
                                 line=node.lineno, kind=kind))
        # Any registered-site literal appearing in a test or chaos module
        # counts as exercised (CHAOS_SITES tuples, parametrized tests) —
        # recorded as a use too so the --sites-report table shows the
        # same evidence the gate accepts (a dynamically armed site must
        # not read as 'arms 0').
        if module.is_test or module.is_chaos:
            arm_lines = {(u.site, u.line) for u in uses}
            for node in ast.walk(module.tree):
                if (isinstance(node, ast.Constant)
                        and isinstance(node.value, str)
                        and node.value in ctx.fault_sites
                        and (node.value, node.lineno) not in arm_lines):
                    uses.append(_SiteUse(
                        site=node.value, path=module.relpath,
                        line=node.lineno, kind="literal"))
        # The module's contribution, both merged into the aggregate and
        # exported as cacheable facts (absorb_facts replays them for
        # files the runner skipped).
        facts = {"uses": [[u.site, u.line, u.kind] for u in uses],
                 "registered": sorted(local),
                 "is_exercising": bool(module.is_test or module.is_chaos)}
        self._last_facts = facts
        self.absorb_facts(module.relpath, facts, ctx)
        return iter(())

    def module_facts(self) -> Optional[Dict]:
        facts, self._last_facts = self._last_facts, None
        return facts

    def absorb_facts(self, relpath: str, facts: Dict,
                     ctx: ProjectContext) -> None:
        exercising = facts.get("is_exercising", False)
        for site, line, kind in facts.get("uses", ()):
            self.uses.append(_SiteUse(site=site, path=relpath,
                                      line=line, kind=kind))
            if kind == "guard" and not exercising:
                self.guarded.add(site)
            if kind in ("arm", "literal") and exercising:
                self.exercised.add(site)
        self.local_registered[relpath] = set(facts.get("registered", ()))

    def finalize(self, ctx: ProjectContext) -> Iterator[Finding]:
        dynamic: Set[str] = set()
        for sites in self.local_registered.values():
            dynamic |= sites
        known = set(ctx.fault_sites) | dynamic
        for use in self.uses:
            if use.site not in known:
                yield Finding(
                    rule="R4", path=use.path, line=use.line, col=0,
                    message=f"unknown fault site {use.site!r}: not in "
                            "infra/faults.py SITES (a typo here "
                            "chaos-tests nothing)")
        if ctx.fault_sites_path not in ctx.scanned:
            return  # partial run: no orphan evidence
        for site, line in sorted(ctx.fault_sites.items()):
            if site not in self.exercised:
                yield Finding(
                    rule="R4", path=ctx.fault_sites_path, line=line, col=0,
                    message=f"registered fault site {site!r} is never "
                            "armed by any chaos walk or test (orphan: "
                            "its failure mode is unexercised)")
            if site not in self.guarded:
                yield Finding(
                    rule="R4", path=ctx.fault_sites_path, line=line, col=0,
                    message=f"registered fault site {site!r} has no "
                            "production guard (FAULTS.check/fires/pull) "
                            "— arming it does nothing)")


# ---------------------------------------------------------------------------
# R5: metric names centrally cataloged, tpu_dra_-prefixed
# ---------------------------------------------------------------------------

_METRIC_NAME_RE = re.compile(r"^tpu_dra_[a-z0-9_]+$")
_REGISTER_VERBS = {"counter", "gauge", "histogram"}


@register
class MetricCatalog(Rule):
    """R5: every metric registered in production code must carry the
    ``tpu_dra_`` prefix and be declared in ``METRICS_CATALOG``
    (infra/metrics.py) — the one place dashboards, the bench gates and
    SURVEY point at; and every cataloged name must actually be
    registered somewhere (orphan detection both directions)."""

    rule_id = "R5"
    title = "metric catalog coverage"

    def __init__(self):
        self.registered: Set[str] = set()
        self._last_facts: Optional[Dict] = None

    def module_facts(self) -> Optional[Dict]:
        facts, self._last_facts = self._last_facts, None
        return facts

    def absorb_facts(self, relpath: str, facts: Dict,
                     ctx: ProjectContext) -> None:
        self.registered.update(facts.get("registered", ()))

    def scan(self, module: Module, ctx: ProjectContext) -> Iterator[Finding]:
        if module.is_test:
            return iter(())
        mod_registered: Set[str] = set()
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _REGISTER_VERBS
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            name = node.args[0].value
            mod_registered.add(name)
            self.registered.add(name)
            if not _METRIC_NAME_RE.match(name):
                findings.append(Finding(
                    rule="R5", path=module.relpath, line=node.lineno, col=0,
                    message=f"metric {name!r} does not match the "
                            "tpu_dra_[a-z0-9_]+ naming contract"))
            elif ctx.metric_catalog and name not in ctx.metric_catalog:
                findings.append(Finding(
                    rule="R5", path=module.relpath, line=node.lineno, col=0,
                    message=f"metric {name!r} is not declared in "
                            "infra/metrics.py METRICS_CATALOG"))
        self._last_facts = {"registered": sorted(mod_registered)}
        return iter(findings)

    def finalize(self, ctx: ProjectContext) -> Iterator[Finding]:
        if not self.registered or ctx.metric_catalog_path not in ctx.scanned:
            return  # partial run (e.g. tests only): no orphan evidence
        for name, line in sorted(ctx.metric_catalog.items()):
            if name not in self.registered:
                yield Finding(
                    rule="R5", path=ctx.metric_catalog_path, line=line,
                    col=0,
                    message=f"cataloged metric {name!r} is never "
                            "registered (orphan catalog entry)")


# ---------------------------------------------------------------------------
# R6: feature-gate names must exist
# ---------------------------------------------------------------------------

@register
class FeatureGateNames(Rule):
    """R6: gate names referenced as strings — ``enabled("...")`` and
    ``set_from_string("A=true,B=false")`` — must exist in
    infra/featuregates.py. The runtime raises on unknown gates, but
    only on the code path that consults them; the linter catches the
    typo before a gate silently never flips."""

    rule_id = "R6"
    title = "feature-gate names exist"

    def scan(self, module: Module, ctx: ProjectContext) -> Iterator[Finding]:
        if not ctx.gate_names:
            return iter(())
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if not (isinstance(node, ast.Call) and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                continue
            chain = attr_chain(node.func)
            if not chain:
                continue
            if (chain[-1] == "enabled"
                    and any(c in ("featuregates", "Features")
                            for c in chain[:-1])):
                name = node.args[0].value
                if name not in ctx.gate_names:
                    findings.append(Finding(
                        rule="R6", path=module.relpath, line=node.lineno,
                        col=0,
                        message=f"unknown feature gate {name!r}"))
            elif chain[-1] == "set_from_string":
                for part in node.args[0].value.split(","):
                    name = part.split("=", 1)[0].strip()
                    if name and name not in ctx.gate_names:
                        findings.append(Finding(
                            rule="R6", path=module.relpath,
                            line=node.lineno, col=0,
                            message=f"unknown feature gate {name!r} in "
                                    "gate string"))
        return iter(findings)


# ---------------------------------------------------------------------------
# R7: mutation-under-try needs a paired unwind in the handler
# ---------------------------------------------------------------------------

_STATE_MUTATORS = {"pop", "popitem", "update", "append", "extend",
                   "insert", "clear", "remove", "add", "discard",
                   "setdefault", "sort"}
_UNWIND_NAME_RE = re.compile(r"unwind|rollback|abort|reinsert|restore",
                             re.IGNORECASE)


def _self_state_mutations(fn) -> List[int]:
    """Line numbers of lexical mutations of ``self``-rooted state in
    `fn`: attribute/subscript assignment, ``del``, augmented
    assignment, or a mutator-method call on a ``self.*`` receiver."""
    out: List[int] = []

    def rooted_at_self(node: ast.AST) -> bool:
        chain = attr_chain(node)
        return bool(chain) and chain[0] == "self" and len(chain) > 1

    for node in ast.walk(fn):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.Delete):
            targets = node.targets
        for t in targets:
            if isinstance(t, (ast.Attribute, ast.Subscript)) \
                    and rooted_at_self(t):
                out.append(t.lineno)
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _STATE_MUTATORS
                and rooted_at_self(node.func.value)):
            out.append(node.lineno)
    return out


def _handler_has_unwind(handler: ast.ExceptHandler) -> bool:
    """A handler 'pairs' the mutation when it re-raises, calls an
    unwind/rollback helper, or compensates with its own self-state
    mutation (reinserting what the failed operation removed)."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain and _UNWIND_NAME_RE.search(chain[-1]):
                return True
    return bool(_self_state_mutations(handler))


@register
class PrepareUnwindDiscipline(Rule):
    """R7: in the prepare pipelines (functions whose name contains
    ``prepare``), an ``except`` path that swallows an error AFTER the
    function has mutated driver state must carry a paired unwind — a
    ``*unwind*``/``*rollback*`` call, a compensating self-state
    mutation, or a re-raise. A handler that just logs and moves on
    leaves memory ahead of disk: exactly the bug class chaos seed 5
    found on the unprepare path (SURVEY §9), now checked lexically."""

    rule_id = "R7"
    title = "prepare-pipeline except paths unwind"

    def scan(self, module: Module, ctx: ProjectContext) -> Iterator[Finding]:
        if module.is_test or module.is_chaos:
            return iter(())
        findings: List[Finding] = []
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if "prepare" not in fn.name.lower():
                continue
            mutations = _self_state_mutations(fn)
            if not mutations:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Try):
                    continue
                for handler in node.handlers:
                    # Mutations lexically before this handler (earlier
                    # statements or the try body it guards) are at
                    # stake; later ones never ran when it fires.
                    if not any(ln < handler.lineno for ln in mutations):
                        continue
                    if _handler_has_unwind(handler):
                        continue
                    findings.append(Finding(
                        rule="R7", path=module.relpath,
                        line=handler.lineno, col=handler.col_offset,
                        message=f"except path in {fn.name}() swallows "
                                "an error after mutating driver state "
                                "with no paired unwind/rollback "
                                "(compensate, call *_unwind_*, or "
                                "re-raise — SURVEY §9)"))
        return iter(findings)


# ---------------------------------------------------------------------------
# R8: no success externalization before the terminal durable store
# ---------------------------------------------------------------------------

def _is_terminal_store(node: ast.Call) -> bool:
    chain = attr_chain(node.func)
    if not chain:
        return False
    if chain[-1] in ("fdatasync", "fsync"):
        return True
    # journal_commit appends the terminal record and journal_barrier is
    # its durability point (the cross-RPC group fdatasync) — both count,
    # so the rule keeps its teeth on the journaled hot path.
    return (chain[-1] in ("store", "store_batch", "journal_commit",
                          "journal_barrier")
            and any("ckpt" in _norm(c) or "checkpoint" in _norm(c)
                    for c in chain[:-1]))


def _is_checkpoint_mutation(node: ast.AST) -> Optional[int]:
    """Line of a mutation of checkpoint state (component named
    *checkpoint* or ``claims``), else None."""
    def matches(target: ast.AST) -> bool:
        if not isinstance(target, (ast.Attribute, ast.Subscript)):
            return False
        chain = attr_chain(target)
        return any("checkpoint" in _norm(c) or _norm(c) == "claims"
                   for c in chain)

    targets = []
    if isinstance(node, ast.Assign):
        targets = node.targets
    elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
        targets = [node.target]
    elif isinstance(node, ast.Delete):
        targets = node.targets
    elif (isinstance(node, ast.Call)
          and isinstance(node.func, ast.Attribute)
          and node.func.attr in _STATE_MUTATORS
          and matches(node.func.value)):
        return node.lineno
    for t in targets:
        if matches(t):
            return t.lineno
    return None


def _success_externalizations(fn) -> List[Tuple[int, str]]:
    """(line, what) of success externalization points: a success
    PrepareResult filled into a result map (no ``error`` kwarg), or a
    success-metric ``.inc()``."""
    out: List[Tuple[int, str]] = []
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            val = node.value
            if (isinstance(val, ast.Call)
                    and attr_chain(val.func)[-1:] == ["PrepareResult"]
                    and not any(kw.arg == "error" for kw in val.keywords)
                    and any(isinstance(t, ast.Subscript)
                            for t in node.targets)):
                out.append((node.lineno, "success PrepareResult fill"))
        elif (isinstance(node, ast.Call)
              and isinstance(node.func, ast.Attribute)
              and node.func.attr == "inc"
              and any("success" in _norm(c)
                      for c in attr_chain(node.func.value))):
            out.append((node.lineno, "success-counter inc"))
    return out


@register
class NoSuccessBeforeTerminalStore(Rule):
    """R8: no success externalization — a success RPC-result fill, a
    success-metric increment — lexically between a checkpoint mutation
    and the terminal ``store``/``fdatasync`` that persists it. Anyone
    observing the success (kubelet starting a container, a dashboard)
    would be ahead of disk: a crash in that window un-happens what was
    already announced. The durable-ordering rule drmc checks
    dynamically (crash enumeration), stated lexically."""

    rule_id = "R8"
    title = "no success externalization before the terminal store"

    def scan(self, module: Module, ctx: ProjectContext) -> Iterator[Finding]:
        if module.is_test or module.is_chaos:
            return iter(())
        findings: List[Finding] = []
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            stores = [n.lineno for n in ast.walk(fn)
                      if isinstance(n, ast.Call) and _is_terminal_store(n)]
            if not stores:
                continue
            mutations = [ln for n in ast.walk(fn)
                         for ln in [_is_checkpoint_mutation(n)]
                         if ln is not None]
            if not mutations:
                continue
            for line, what in _success_externalizations(fn):
                if (any(m < line for m in mutations)
                        and any(s > line for s in stores)):
                    findings.append(Finding(
                        rule="R8", path=module.relpath, line=line, col=0,
                        message=f"{what} in {fn.name}() after a "
                                "checkpoint mutation but before the "
                                "terminal store — success must not be "
                                "externalized until the state backing "
                                "it is durable (SURVEY §13)"))
        return iter(findings)


# ---------------------------------------------------------------------------
# R12: span begin/end discipline (the claim tracer, SURVEY §19)
# ---------------------------------------------------------------------------

_SPAN_CLOSERS = {"end", "abandon"}


def _is_tracer_recv(chain: List[str]) -> bool:
    """Receiver names the tracer by convention (``TRACER``, ``tracer``,
    ``self._tracer`` …) — the same naming-keys-the-rule design as the
    ``*_lock`` family."""
    return any("tracer" in _norm(c) for c in chain[:-1])


def _span_begin_call(node: ast.AST) -> bool:
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "begin"
            and _is_tracer_recv(attr_chain(node.func)))


def _walk_scope(fn) -> Iterator[ast.AST]:
    """Walk `fn`'s body WITHOUT descending into nested functions /
    lambdas — each nested scope gets its own R12 visit, so a begin
    there is neither double-reported nor credited with an outer close."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _close_target(node: ast.Call) -> Optional[str]:
    """The span variable a close call closes: ``x.end()`` /
    ``x.abandon()`` / ``TRACER.end(x)`` / ``TRACER.abandon(x)``."""
    if not isinstance(node.func, ast.Attribute) \
            or node.func.attr not in _SPAN_CLOSERS:
        return None
    recv = node.func.value
    if isinstance(recv, ast.Name):
        chain = attr_chain(node.func)
        if _is_tracer_recv(chain):
            if node.args and isinstance(node.args[0], ast.Name):
                return node.args[0].id
            return None
        return recv.id
    return None


@register
class SpanBeginEndDiscipline(Rule):
    """R12: every ``tracer.begin(...)`` outside the ``with``-form must
    have an ``end()``/``abandon()`` on all paths — a span that leaks
    open poisons the trace-completeness invariants chaos and drmc gate
    on (zero open spans at quiesce / every terminal state), and its
    trace silently stops attributing.

    Lexical approximation (same altitude as R7): a begun span held in a
    local variable must be (a) discarded — a finding outright, the span
    can never be closed; (b) closed somewhere — no close at all is a
    finding; and (c) closed in a ``finally`` block whenever anything
    between the begin and the close can raise (a call, a raise, an
    early return) — a straight-line begin/close pair needs no finally.
    A span that ESCAPES the function (returned, stored into an
    attribute/subscript, aliased, or passed to a non-close call) is the
    caller's to close — the dynamic zero-open-span gates backstop those
    paths. The ``with TRACER.span(...)`` form closes itself and is
    always clean."""

    rule_id = "R12"
    title = "span begin/end discipline"

    def scan(self, module: Module, ctx: ProjectContext) -> Iterator[Finding]:
        if module.is_test or module.is_chaos:
            return iter(())
        findings: List[Finding] = []
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            findings.extend(self._scan_function(module, fn))
        return iter(findings)

    def _scan_function(self, module: Module, fn) -> List[Finding]:
        begins: Dict[str, ast.Call] = {}       # var -> begin call node
        discarded: List[ast.Call] = []
        closes: Dict[str, List[ast.Call]] = {}
        escaped: Set[str] = set()
        finally_calls: Set[int] = set()        # id() of calls in finalbody
        for node in _walk_scope(fn):
            if isinstance(node, ast.Try):
                for stmt in node.finalbody:
                    for sub in ast.walk(stmt):
                        if isinstance(sub, ast.Call):
                            finally_calls.add(id(sub))
            elif isinstance(node, ast.Expr) \
                    and _span_begin_call(node.value):
                discarded.append(node.value)
            elif isinstance(node, ast.Assign):
                if _span_begin_call(node.value):
                    if len(node.targets) == 1 \
                            and isinstance(node.targets[0], ast.Name):
                        begins[node.targets[0].id] = node.value
                    # attribute/subscript/tuple target: escapes — the
                    # holder's owner closes it (device_state's b.span).
                elif isinstance(node.value, ast.Name):
                    escaped.add(node.value.id)  # aliased/stored
            elif isinstance(node, (ast.Return, ast.Yield)) \
                    and node.value is not None:
                for sub in ast.walk(node.value):
                    if isinstance(sub, ast.Name):
                        escaped.add(sub.id)
            elif isinstance(node, ast.Call):
                target = _close_target(node)
                if target is not None:
                    closes.setdefault(target, []).append(node)
                else:
                    for arg in list(node.args) \
                            + [kw.value for kw in node.keywords]:
                        if isinstance(arg, ast.Name):
                            escaped.add(arg.id)
        out: List[Finding] = []
        for call in discarded:
            out.append(Finding(
                rule="R12", path=module.relpath, line=call.lineno,
                col=call.col_offset,
                message=f"tracer.begin() result discarded in {fn.name}()"
                        " — the span can never be ended (bind it and "
                        "end()/abandon() it, or use the with-form)"))
        for var, begin in sorted(begins.items()):
            if var in escaped:
                continue  # ownership transferred; dynamic gates cover it
            var_closes = closes.get(var, [])
            if not var_closes:
                out.append(Finding(
                    rule="R12", path=module.relpath, line=begin.lineno,
                    col=begin.col_offset,
                    message=f"span '{var}' begun in {fn.name}() is never"
                            " end()ed/abandon()ed — it leaks open and "
                            "fails the quiesce zero-open-span invariant"))
                continue
            if any(id(c) in finally_calls for c in var_closes):
                continue  # closed on all paths by construction
            last_close = max(c.lineno for c in var_closes)
            # Exclude the begin/close calls AND their sub-expressions
            # (a multi-line begin's attribute dict is not risky work).
            own = {id(n) for n in ast.walk(begin)}
            for c in var_closes:
                own |= {id(n) for n in ast.walk(c)}
            risky = False
            for node in _walk_scope(fn):
                if id(node) in own:
                    continue
                if begin.lineno < getattr(node, "lineno", -1) < last_close:
                    if isinstance(node, (ast.Raise, ast.Return)):
                        risky = True
                        break
                    if isinstance(node, ast.Call):
                        risky = True
                        break
            if risky:
                out.append(Finding(
                    rule="R12", path=module.relpath, line=begin.lineno,
                    col=begin.col_offset,
                    message=f"span '{var}' begun in {fn.name}() is "
                            "closed, but code between begin and close "
                            "can raise/return past it — move the "
                            "end()/abandon() into a finally (or use "
                            "the with-form)"))
        return out


# ---------------------------------------------------------------------------
# Site-coverage report (informational; hack/lint.sh --sites-report)
# ---------------------------------------------------------------------------

def site_coverage(report_rule: FaultSiteRegistry,
                  ctx: ProjectContext) -> List[Tuple[str, List[str], List[str]]]:
    """(site, guard locations, arm/exercise locations) per registered
    site — the arm column includes literal evidence in test/chaos
    modules (dynamic arms via site tuples), matching what R4 accepts."""
    out = []
    for site in sorted(ctx.fault_sites):
        # Sorted: the collection order differs between fresh scans and
        # cache-replayed facts; the table must not.
        guards = sorted(f"{u.path}:{u.line}" for u in report_rule.uses
                        if u.site == site and u.kind == "guard")
        arms = sorted({f"{u.path}:{u.line}" for u in report_rule.uses
                       if u.site == site and u.kind in ("arm", "literal")})
        out.append((site, guards, arms))
    return out
