"""drflow: interprocedural escape, atomicity and error-flow analysis.

Three defect classes repeatedly bit this codebase and stayed outside
the existing rule families' reach (SURVEY §20): zero-copy informer
views escaping into helpers that mutate them (R3's statement-order
taint stops at the function boundary), check-then-act atomicity
violations across lock releases (draracer sees the LOCKS, not the
staleness of the data read under them — the exact bug family drmc's
racy-index fixture replays), and silently-swallowed exceptions. drflow
is the combined dataflow rule family covering them, built on
draracer's whole-tree ``TreeResolver`` (module-qualified resolution,
CHA, callback points-to) and the SAME per-module extraction blob
(``facts_key = "R9"``: the cache stores it once, both rules absorb it).

- **R13 — whole-tree view escape analysis.** A lister/get_by_index
  result is a VIEW of live informer-cache state (SURVEY §10). R13
  lifts R3's taint to the tree: a view flowing through call arguments,
  returns, container stores (``self._cache[k] = view``,
  ``acc.append(view)``) and closure captures must reach only read-only
  sinks. ``copy.deepcopy`` / ``json_deepcopy`` (one shared predicate
  with R3, alias-aware — ``rules.is_laundering_chain``) launder a view
  into a private object; ``# drflow: view-ok[reason]`` marks a
  sanctioned hatch. Findings carry the seed site (where the view was
  read) so runtime view-shadow drift (k8s.informer.viewshadow) can be
  cross-validated observed⊆static: every drift site must be a
  statically implicated seed (``check_view_shadow``).

- **R14 — stale-snapshot check-then-act.** A value read under a data
  lock goes STALE the moment the lock releases — by leaving the
  ``with`` body, or by being RETURNED out of a locked getter (the
  interprocedural seed). Guarding on that stale value and then writing
  the same state it was derived from, without re-validation, is the
  lost-update/TOCTOU shape. Re-validation is recognized structurally:
  a live re-read of the same attribute under the lock between check
  and act, an act callee that re-reads it under its own lock, or a
  callee annotated ``# drflow: REVALIDATES:<field>`` (the scheduler's
  snapshot→try_commit protocol, documented rather than suppressed).

- **R15 — swallowed-exception audit.** Every BROAD handler (bare
  ``except``, ``except Exception``/``BaseException``) must do
  SOMETHING with the error: re-raise, use the bound exception value,
  increment a metric, log, or call a degrade-path helper.
  ``# drflow: swallow-ok[reason]`` sanctions a deliberate swallow —
  the reason is mandatory. Handlers whose try body guards a registered
  fault site with a declared degradation (``DEGRADATIONS`` in
  infra/faults.py) must additionally route to that degradation or
  re-raise: an injected fault that only gets logged is a failure mode
  chaos thinks is covered but production quietly eats.

Annotation grammar (SURVEY §20): ``# drflow: view-ok[reason]``,
``# drflow: swallow-ok[reason]``, ``# drflow: REVALIDATES:<field>``
(``*`` = everything it touches), parsed by the shared extraction and
matched on the finding's line or the line above (view/swallow) or the
``def`` line (REVALIDATES).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from tpu_dra.analysis.core import (
    Finding, Module, ProjectContext, Rule, register,
)
from tpu_dra.analysis.raceanalysis import (
    TreeResolver, extract_module, shared_resolver,
)
from tpu_dra.analysis.rules import attr_chain, is_laundering_chain

# R3's propagation vocabulary, shared verbatim so intra- and
# inter-procedural taint agree on what carries a view along.
from tpu_dra.analysis.rules import (  # noqa: E402
    _PROPAGATORS, _READERS, _VIEW_TAILS,
)

# Container-store method names: calling one with a tainted argument
# makes the receiver a container OF views (elements are views; the
# container itself may be restructured freely).
_CONTAINER_STORES = {"append", "add", "insert", "setdefault", "extend",
                     "update"}

# Taint fixpoint bound: chains in this tree are shallow (a view rarely
# crosses more than 3-4 hops); the bound only guards pathological
# fixtures from hanging lint.
_MAX_ROUNDS = 12

# -- R15 discipline vocabulary ----------------------------------------------

_BROAD_EXC = {"Exception", "BaseException"}
_LOG_TAILS = {"print", "print_exc", "exception", "warning", "warn",
              "error", "critical", "info", "debug", "log"}
_METRIC_TAILS = {"inc", "observe"}
_DEGRADE_RE = re.compile(
    r"degrade|quarantin|requeue|backoff|abort|wedge|unwind|rollback|"
    r"restore|reinsert|evict|unhealthy|supersede|kill", re.IGNORECASE)


# ---------------------------------------------------------------------------
# Descriptor helpers
# ---------------------------------------------------------------------------

def _desc_chain_loose(desc: Optional[Dict]) -> List[str]:
    """Dotted chain of a descriptor, looking through subscripts and
    calls (the descriptor analog of rules.attr_chain)."""
    out: List[str] = []
    d = desc
    while isinstance(d, dict):
        t = d.get("t")
        if t == "attr":
            out.append(d["attr"])
            d = d.get("base")
        elif t == "sub":
            d = d.get("base")
        elif t == "call":
            d = d.get("func")
        elif t == "name":
            out.append(d["id"])
            break
        else:
            break
    return list(reversed(out))


def _is_view_chain(chain: Sequence[str]) -> bool:
    return (tuple(chain[-2:]) in _VIEW_TAILS
            or (bool(chain) and chain[-1] == "get_by_index"))


# ---------------------------------------------------------------------------
# R13: whole-tree escape analysis
# ---------------------------------------------------------------------------

# Taint entities: ("l", fid, name) a local/param, ("r", fid) a return
# value, ("a", cid, attr) a class attribute. Each carries a provenance
# (seed_site, kind): kind "view" = the value IS a view (mutating it is
# a finding), "container" = it HOLDS views (indexing/iterating yields
# views; restructuring the container itself is fine).
_Prov = Tuple[str, str]


class _CalleeCache:
    """resolve_call over a bare FUNC descriptor (no call record).
    The fabricated ``{"expr": desc}`` wrappers are kept alive for the
    resolver's lifetime: resolve_call memoizes by ``id(call)``, and a
    garbage-collected wrapper's id being reused by the next one would
    poison that memo with another call's resolution."""

    def __init__(self, res: TreeResolver):
        self.res = res
        self._memo: Dict[Tuple[str, int], List[str]] = {}
        self._keep: List[Dict] = []

    def callees(self, func_desc: Dict, fid: str) -> List[str]:
        key = (fid, id(func_desc))
        hit = self._memo.get(key)
        if hit is None:
            wrapper = {"expr": func_desc}
            self._keep.append(wrapper)
            hit = self.res.resolve_call(wrapper, fid)[0]
            self._memo[key] = hit
        return hit


class _TaintEngine:
    def __init__(self, res: TreeResolver, calls: _CalleeCache):
        self.res = res
        self.t: Dict[Tuple, _Prov] = {}
        self.changed = False
        self._calls = calls
        self._imports: Dict[str, Dict[str, str]] = {
            rel: facts.get("imports", {})
            for rel, facts in res.modules.items()}

    def mark(self, ent: Tuple, prov: _Prov) -> None:
        if ent not in self.t:
            self.t[ent] = prov
            self.changed = True

    def lookup_local(self, fid: str, name: str) -> Optional[_Prov]:
        """A local's taint, searching enclosing function scopes too
        (closure captures: a nested handler mutating a captured view)."""
        res = self.res
        scope: Optional[str] = fid
        while scope is not None:
            prov = self.t.get(("l", scope, name))
            if prov is not None:
                return prov
            rec = res.funcs.get(scope)
            if rec is None:
                break
            qual = rec["qual"]
            rel = res.func_mod[scope]
            scope = (f"{rel}::{qual.rsplit('.', 1)[0]}"
                     if "." in qual else None)
        return None

    def callees(self, func_desc: Dict, fid: str) -> List[str]:
        return self._calls.callees(func_desc, fid)

    def attr_taint(self, cid: Optional[str],
                   attr: str) -> Optional[_Prov]:
        info = self.res.classes.get(cid) if cid else None
        if info is None:
            return None
        for c in self.res._mro(info):
            prov = self.t.get(("a", c.cid, attr))
            if prov is not None:
                return prov
        return None

    def taints(self, desc: Optional[Dict], fid: str,
               depth: int = 0) -> Optional[_Prov]:
        """The provenance a value expression carries in `fid`'s scope,
        or None (clean)."""
        if desc is None or depth > 8:
            return None
        t = desc.get("t")
        if t == "name":
            return self.lookup_local(fid, desc["id"])
        if t in ("sub", "iter"):
            inner = self.taints(desc.get("base") or desc.get("of"),
                                fid, depth + 1)
            # Indexing / iterating either kind yields an element view.
            return (inner[0], "view") if inner else None
        if t == "attr":
            base_prov = self.taints(desc.get("base"), fid, depth + 1)
            if base_prov:
                return (base_prov[0], "view")
            base_t = self.res.resolve_type(desc.get("base"), fid)
            cid = base_t.get("cls") if base_t else None
            prov = self.attr_taint(cid, desc["attr"])
            if prov is not None:
                return prov
            # A property access carries its GETTER's return taint
            # (``pods = self.pods`` where the getter hands out views).
            info = self.res.classes.get(cid) if cid else None
            m = (self.res.class_method(info, desc["attr"])
                 if info else None)
            if m is not None:
                decs = self.res.funcs.get(m, {}).get("decorators") or ()
                if any(d.split(".")[-1] in ("property", "cached_property")
                       for d in decs):
                    return self.t.get(("r", m))
            return None
        if t == "container":
            for e in desc.get("elems", ()):
                inner = self.taints(e, fid, depth + 1)
                if inner:
                    return (inner[0], "container")
            return None
        if t == "call":
            chain = _desc_chain_loose(desc.get("func"))
            rel = self.res.func_mod.get(fid, "")
            if is_laundering_chain(chain, self._imports.get(rel)):
                return None  # the sanctioned hatch: a private copy
            if _is_view_chain(chain):
                line = desc.get("line", 0)
                return (f"{rel}:{line}", "view")
            func = desc.get("func") or {}
            if (len(chain) == 1 and chain[0] in _PROPAGATORS):
                for a in desc.get("args", ()):
                    inner = self.taints(a, fid, depth + 1)
                    if inner:
                        return inner
                return None
            if func.get("t") == "attr" and func["attr"] in _READERS:
                # d.get/.values/.items/.copy on a tainted receiver:
                # still (a shallow view of) the same objects.
                return self.taints(func.get("base"), fid, depth + 1)
            for c in self.callees(func, fid):
                prov = self.t.get(("r", c))
                if prov is not None:
                    return prov
            return None
        return None


class _R13Pass:
    def __init__(self, res: TreeResolver, calls: _CalleeCache):
        self.res = res
        self.eng = _TaintEngine(res, calls)
        # relpath:line of every view-producing call site the analyzer
        # recognized, and the subset implicated in a finding — the
        # runtime shadow's observed⊆static gate keys on these.
        self.recognized: Set[str] = set()
        self.implicated: Set[str] = set()

    def run(self) -> List[Finding]:
        res, eng = self.res, self.eng
        for fid, rec in res.funcs.items():
            rel = res.func_mod[fid]
            for call in rec.get("calls", ()):
                if _is_view_chain(_desc_chain_loose(call["expr"])):
                    self.recognized.add(f"{rel}:{call['line']}")
        for _ in range(_MAX_ROUNDS):
            eng.changed = False
            for fid, rec in res.funcs.items():
                self._propagate(fid, rec)
            if not eng.changed:
                break
        return self._findings()

    def _propagate(self, fid: str, rec: Dict) -> None:
        res, eng = self.res, self.eng
        info = res.class_of(fid)
        for name, descs in rec.get("locals", {}).items():
            for d in descs:
                prov = eng.taints(d, fid)
                if prov:
                    eng.mark(("l", fid, name), prov)
        # A laundering function's own return is BY DEFINITION clean —
        # json_deepcopy's scalar fast path (`return obj`) must not
        # taint every laundered copy in the tree.
        if not is_laundering_chain([rec["name"]]):
            for rdesc in rec.get("returns", ()):
                prov = eng.taints(rdesc, fid)
                if prov:
                    eng.mark(("r", fid), prov)
        for sa in rec.get("self_assigns", ()):
            if info is None:
                continue
            prov = eng.taints(sa["value"], fid)
            if prov:
                eng.mark(("a", info.cid, sa["attr"]), prov)
        for call in rec.get("calls", ()):
            args = call.get("args") or []
            kwargs = call.get("kwargs") or {}
            expr = call["expr"]
            # Container stores: receiver becomes a container of views.
            if (expr.get("t") == "attr"
                    and expr["attr"] in _CONTAINER_STORES and args):
                stored = eng.taints(args[-1], fid)
                if stored:
                    base = expr.get("base") or {}
                    prov = (stored[0], "container")
                    if (base.get("t") == "attr"
                            and base.get("base", {}).get("t") == "name"
                            and base["base"]["id"] == "self"
                            and info is not None):
                        eng.mark(("a", info.cid, base["attr"]), prov)
                    elif base.get("t") == "name":
                        eng.mark(("l", fid, base["id"]), prov)
            if not args and not kwargs:
                continue
            taints = {i: eng.taints(a, fid) for i, a in enumerate(args)}
            kw_taints = {k: eng.taints(v, fid)
                         for k, v in kwargs.items()}
            if not any(taints.values()) and not any(kw_taints.values()):
                continue
            for c in eng.callees(expr, fid):
                crec = res.funcs.get(c)
                if crec is None:
                    continue
                params = [p["name"] for p in crec["params"]]
                if crec.get("cls") and params \
                        and params[0] in ("self", "cls"):
                    params = params[1:]
                for i, prov in taints.items():
                    if prov and i < len(params):
                        eng.mark(("l", c, params[i]), prov)
                for k, prov in kw_taints.items():
                    if prov and k in params:
                        eng.mark(("l", c, k), prov)

    def _findings(self) -> List[Finding]:
        res, eng = self.res, self.eng
        out: List[Finding] = []
        seen: Set[Tuple[str, int, str]] = set()
        for fid, rec in res.funcs.items():
            rel = res.func_mod[fid]
            ann = (res.modules[rel].get("drflow") or {}).get("view_ok", {})
            info = res.class_of(fid)
            for m in rec.get("mutations", ()):
                if m["root"] == "self":
                    prov = (eng.t.get(("a", info.cid, m["attr"]))
                            if info is not None else None)
                    shown = f"self.{m['attr']}"
                else:
                    prov = eng.lookup_local(fid, m["root"])
                    shown = m["root"]
                if prov is None or prov[1] != "view":
                    continue
                hatch = next((ann[str(ln)] for ln in (m["line"],
                                                      m["line"] - 1)
                              if str(ln) in ann), None)
                if hatch is not None:
                    # Sanctioned hatch: STILL a statically-known flow —
                    # a runtime drift seeded here must read as
                    # explained, not as under-approximation.
                    self.implicated.add(prov[0])
                    if not hatch:
                        out.append(Finding(
                            rule="R13", path=rel, line=m["line"], col=0,
                            message="'# drflow: view-ok' without a "
                                    "reason — the annotation grammar "
                                    "is view-ok[reason] (SURVEY §20)"))
                    continue
                key = (rel, m["line"], shown)
                if key in seen:
                    continue
                seen.add(key)
                self.implicated.add(prov[0])
                out.append(Finding(
                    rule="R13", path=rel, line=m["line"], col=0,
                    message=f"{m['what']} '{shown}' in {rec['qual']}()"
                            f", a zero-copy informer view that escaped "
                            f"interprocedurally (view read at {prov[0]})"
                            " — deepcopy/json_deepcopy the object "
                            "before writing, or annotate '# drflow: "
                            "view-ok[reason]' (SURVEY §20)"))
        return out


# ---------------------------------------------------------------------------
# R14: stale-snapshot check-then-act
# ---------------------------------------------------------------------------

class _R14Pass:
    def __init__(self, res: TreeResolver, calls: _CalleeCache):
        self.res = res
        self._calls = calls
        # fid -> attrs this function re-reads (kind r) under a held
        # data lock — the "re-checks live state" signal for act
        # callees, computed lazily.
        self._live_reads: Dict[str, Set[str]] = {}
        self._reval: Dict[str, str] = {}  # fid -> REVALIDATES field
        for rel, facts in res.modules.items():
            rv = (facts.get("drflow") or {}).get("revalidates", {})
            if not rv:
                continue
            lines = {int(k): v for k, v in rv.items()}
            for qual, frec in facts["functions"].items():
                field = (lines.get(frec.get("line", -1))
                         or lines.get(frec.get("line", -1) - 1))
                if field:
                    self._reval[f"{rel}::{qual}"] = field
        # cid -> attrs written OUTSIDE __init__ anywhere in the tree:
        # only mutable state can go stale. A snapshot derived purely
        # from construction-time handles (self._ckpt_mgr) is a value,
        # not a racing read.
        self._written: Dict[str, Set[str]] = {}
        for fid, rec in res.funcs.items():
            if rec["name"] == "__init__":
                continue
            info = res.class_of(fid)
            if info is None:
                continue
            w = self._written.setdefault(info.cid, set())
            w.update(a["attr"] for a in rec.get("accesses", ())
                     if a["kind"] == "w" and a["base"] == "self")
            w.update(m["attr"] for m in rec.get("mutations", ())
                     if m["root"] == "self")
            w.update(sa["attr"] for sa in rec.get("self_assigns", ()))

    def _mutable_attrs(self, cid: Optional[str]) -> Set[str]:
        info = self.res.classes.get(cid) if cid else None
        if info is None:
            return set()
        out: Set[str] = set()
        for c in self.res._mro(info):
            out |= self._written.get(c.cid, set())
        return out

    def _receiver_cid(self, fid: str, base: str) -> Optional[str]:
        res = self.res
        if base == "self":
            info = res.class_of(fid)
            return info.cid if info else None
        t = res.resolve_type({"t": "name", "id": base}, fid)
        return t.get("cls") if t else None

    def _writes_state(self, fid: str) -> bool:
        rec = self.res.funcs.get(fid) or {}
        return (any(a["kind"] == "w" and a["base"] == "self"
                    for a in rec.get("accesses", ()))
                or any(m["root"] == "self"
                       for m in rec.get("mutations", ())))

    def _is_reservation(self, fid: str, rec: Dict, seed: Dict) -> bool:
        """The snapshot block COMMITTED something while it held the
        lock — a test-and-set, not a naked check, so the actor is
        serialized even though the data it read is stale:

        - the guarded expression itself called a receiver method that
          writes state (``spawn = ... and self._claim_spawn_slot()``);
        - or some attribute is both guard-read and written under the
          lock before the release (``if self._sync_in_flight: wait
          ... self._sync_in_flight = True`` — the group-sync leader
          claim in journal_barrier)."""
        res = self.res
        cid = self._receiver_cid(fid, seed["base"])
        info = res.classes.get(cid) if cid else None
        for mname in seed.get("rhs_calls", ()):
            cands = (res.class_method_cha(info, mname) if info
                     else res.methods_by_name.get(mname, []))
            if any(self._writes_state(c) for c in cands):
                return True
        held_rw: Dict[str, List[str]] = {}
        for acc in rec.get("accesses", ()):
            if (acc["base"] == seed["base"]
                    and acc["line"] <= seed["release"]
                    and any(h[0] == seed["base"]
                            and h[1] == seed["lock_attr"]
                            for h in acc["held"])):
                held_rw.setdefault(acc["attr"], []).append(acc["kind"])
        return any("r" in kinds and "w" in kinds
                   for kinds in held_rw.values())

    def live_reads(self, fid: str) -> Set[str]:
        hit = self._live_reads.get(fid)
        if hit is None:
            hit = set()
            rec = self.res.funcs.get(fid) or {}
            for acc in rec.get("accesses", ()):
                if acc["kind"] == "r" and acc["base"] == "self" \
                        and acc["held"]:
                    hit.add(acc["attr"])
            self._live_reads[fid] = hit
        return hit

    def _revalidated_by(self, fid: str, attrs: Sequence[str]) -> bool:
        field = self._reval.get(fid)
        if field == "*":
            return True
        if field and field in attrs:
            return True
        return bool(self.live_reads(fid) & set(attrs))

    def _seeds(self, fid: str, rec: Dict) -> List[Dict]:
        res = self.res
        seeds = [dict(s, kind="with") for s in rec.get("snap_binds", ())]
        for cb in rec.get("call_binds", ()):
            func = cb["desc"].get("func") or {}
            chain = _desc_chain_loose(func)
            if not chain or len(chain) < 2:
                continue  # a bare function call is not a receiver read
            for c in self._calls.callees(func, fid):
                ret = res.funcs.get(c, {}).get("ret_locked")
                if not ret:
                    continue
                if self._reval.get(c):
                    continue  # the getter itself IS the validation
                seeds.append({
                    "var": cb["var"], "line": cb["line"],
                    "base": chain[0], "lock_attr": ret["lock_attr"],
                    "release": cb["line"], "attrs": ret["attrs"],
                    "kind": "getter", "callee": c})
                break
        return seeds

    def run(self) -> List[Finding]:
        res = self.res
        out: List[Finding] = []
        seen: Set[Tuple[str, int]] = set()
        for fid, rec in res.funcs.items():
            rel = res.func_mod[fid]
            for seed in self._seeds(fid, rec):
                mutable = self._mutable_attrs(
                    self._receiver_cid(fid, seed["base"]))
                seed["attrs"] = [a for a in seed["attrs"] if a in mutable]
                if not seed["attrs"]:
                    continue  # construction-time handles cannot go stale
                if seed["kind"] == "with" \
                        and self._is_reservation(fid, rec, seed):
                    continue
                for test in rec.get("tests", ()):
                    if test["line"] <= seed["release"] \
                            or seed["var"] not in test["names"]:
                        continue
                    f = self._check_act(fid, rec, rel, seed, test)
                    if f is not None and (f.path, f.line) not in seen:
                        seen.add((f.path, f.line))
                        out.append(f)
        return out

    def _check_act(self, fid: str, rec: Dict, rel: str, seed: Dict,
                   test: Dict) -> Optional[Finding]:
        res = self.res
        attrs = set(seed["attrs"])
        lo, hi = test["span"]
        base = seed["base"]

        def revalidated_before(line: int) -> bool:
            """A live re-read of the snapshotted state under the lock
            anywhere between the RELEASE and the act — the acted-on
            decision was refreshed (rebinding the variable under a new
            acquisition included)."""
            for acc in rec.get("accesses", ()):
                if (acc["base"] == base and acc["attr"] in attrs
                        and acc["kind"] == "r"
                        and seed["release"] < acc["line"] <= line
                        and any(h[0] == base
                                and h[1] == seed["lock_attr"]
                                for h in acc["held"])):
                    return True
            for call in rec.get("calls", ()):
                if not (seed["release"] < call["line"] <= line):
                    continue
                for c in res.resolve_call(call, fid)[0]:
                    if self._revalidated_by(c, seed["attrs"]):
                        return True
            return False

        # Act form 1: a direct write of the snapshotted state.
        for acc in rec.get("accesses", ()):
            if (acc["kind"] == "w" and acc["base"] == base
                    and acc["attr"] in attrs
                    and lo <= acc["line"] <= hi):
                if revalidated_before(acc["line"]):
                    return None
                return self._finding(rel, acc["line"], rec, seed, test,
                                     f"{base}.{acc['attr']}")
        # Act form 2: a call on the same receiver that writes the
        # snapshotted state (the getter/act method pair).
        for call in rec.get("calls", ()):
            if not (lo <= call["line"] <= hi):
                continue
            chain = _desc_chain_loose(call["expr"])
            if not chain or chain[0] != base:
                continue
            for c in res.resolve_call(call, fid)[0]:
                crec = res.funcs.get(c)
                if crec is None or self._revalidated_by(c, seed["attrs"]):
                    continue
                writes = {a["attr"] for a in crec.get("accesses", ())
                          if a["kind"] == "w" and a["base"] == "self"}
                writes |= {m["attr"] for m in crec.get("mutations", ())
                           if m["root"] == "self"}
                hit = writes & attrs
                if hit:
                    if revalidated_before(call["line"]):
                        return None
                    return self._finding(
                        rel, call["line"], rec, seed, test,
                        f"{base}.{sorted(hit)[0]} (via "
                        f"{crec['qual']}())")
        return None

    def _finding(self, rel: str, line: int, rec: Dict, seed: Dict,
                 test: Dict, target: str) -> Finding:
        how = ("read under the lock" if seed["kind"] == "with"
               else "returned by a locked getter")
        return Finding(
            rule="R14", path=rel, line=line, col=0,
            message=f"check-then-act on a stale snapshot in "
                    f"{rec['qual']}(): '{seed['var']}' ({how} at line "
                    f"{seed['line']}, lock {seed['base']}."
                    f"{seed['lock_attr']} released) guards the branch "
                    f"at line {test['line']} and then {target} is "
                    "written without re-validating against live state "
                    "— re-read under the lock, route through a "
                    "'# drflow: REVALIDATES:<field>' commit, or "
                    "restructure (SURVEY §20)")


# ---------------------------------------------------------------------------
# R15: swallowed-exception audit (lexical, per module)
# ---------------------------------------------------------------------------

def _broad_handler(h: ast.ExceptHandler) -> bool:
    t = h.type
    if t is None:
        return True
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    for e in elts:
        chain = attr_chain(e)
        if chain and chain[-1] in _BROAD_EXC:
            return True
    return False


def _handler_discipline(h: ast.ExceptHandler) -> Optional[str]:
    """What the handler DOES with the error, or None (silent swallow):
    're-raise', 'uses the exception value', 'metric', 'log',
    'degrade-path call'."""
    for node in ast.walk(h):
        if isinstance(node, ast.Raise):
            return "re-raise"
    if h.name:
        for node in ast.walk(h):
            if isinstance(node, ast.Name) and node.id == h.name \
                    and isinstance(node.ctx, ast.Load):
                return "uses the exception value"
    for node in ast.walk(h):
        if not isinstance(node, ast.Call):
            continue
        chain = attr_chain(node.func)
        if not chain:
            continue
        tail = chain[-1]
        if tail in _METRIC_TAILS:
            return "metric"
        if tail in _LOG_TAILS:
            return "log"
        if _DEGRADE_RE.search(tail):
            return "degrade-path call"
    return None


def _handler_degrades(h: ast.ExceptHandler, want: str) -> bool:
    """Whether the handler routes to the site's DECLARED degradation:
    re-raises, or calls something whose name carries `want` (or any
    generic degrade verb — a stronger action than the declared one is
    not a finding)."""
    for node in ast.walk(h):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain and (want in chain[-1]
                          or _DEGRADE_RE.search(chain[-1])):
                return True
    return False


def _guarded_sites(try_node: ast.Try, ctx: ProjectContext) -> List[str]:
    """Registered fault sites whose guards sit in this try's BODY: the
    handler below is the code that runs when the injected fault fires."""
    out: List[str] = []
    for stmt in try_node.body:
        for node in ast.walk(stmt):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func)
            if (len(chain) >= 2 and chain[-1] in ("check", "fires", "pull")
                    and any(c.lstrip("_").lower() == "faults"
                            for c in chain[:-1])
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                    and node.args[0].value in ctx.fault_sites):
                out.append(node.args[0].value)
    return out


def _site_degradation_findings(module: Module, h: ast.ExceptHandler,
                               sites: Sequence[str],
                               ctx: ProjectContext) -> Iterator[Finding]:
    for site in sites:
        want = ctx.fault_degradations.get(site)
        if want and not _handler_degrades(h, want):
            yield Finding(
                rule="R15", path=module.relpath, line=h.lineno,
                col=h.col_offset,
                message=f"handler guards fault site {site!r} but does "
                        f"not route to its declared degradation "
                        f"({want}, infra/faults.py DEGRADATIONS) — an "
                        "injected fault that is only logged leaves the "
                        "degrade path untested (SURVEY §20)")
            return


def r15_scan(module: Module, ctx: ProjectContext) -> Iterator[Finding]:
    facts = extract_module(module)
    swallow_ok: Dict[str, str] = (facts.get("drflow") or {}).get(
        "swallow_ok", {})

    def sanctioned(line: int) -> Optional[Tuple[int, str]]:
        for ln in (line, line - 1):
            if str(ln) in swallow_ok:
                return ln, swallow_ok[str(ln)]
        return None

    for node in ast.walk(module.tree):
        if not isinstance(node, ast.Try):
            continue
        sites = None  # computed lazily: most handlers need no registry
        for h in node.handlers:
            if not _broad_handler(h):
                # Narrow handlers never swallow-audit, but a try body
                # guarding a declared-degradation fault site holds its
                # handler — broad or not — to the declared route
                # (FaultInjected is usually caught narrowly).
                if sites is None:
                    sites = _guarded_sites(node, ctx)
                yield from _site_degradation_findings(
                    module, h, sites, ctx)
                continue
            ann = sanctioned(h.lineno)
            disc = _handler_discipline(h)
            if disc is None:
                if ann is not None and ann[1]:
                    continue  # justified deliberate swallow
                if ann is not None:
                    yield Finding(
                        rule="R15", path=module.relpath, line=h.lineno,
                        col=h.col_offset,
                        message="'# drflow: swallow-ok' without a "
                                "reason — the annotation grammar is "
                                "swallow-ok[reason] (SURVEY §20)")
                    continue
                yield Finding(
                    rule="R15", path=module.relpath, line=h.lineno,
                    col=h.col_offset,
                    message="broad except handler swallows the error "
                            "silently: no re-raise, no metric inc, no "
                            "log, no degrade-path call, bound "
                            "exception unused — count/log/degrade, or "
                            "annotate '# drflow: swallow-ok[reason]' "
                            "(SURVEY §20)")
                continue
            if ann is not None:
                continue  # annotated AND disciplined: fine either way
            if sites is None:
                sites = _guarded_sites(node, ctx)
            yield from _site_degradation_findings(module, h, sites, ctx)


# ---------------------------------------------------------------------------
# The combined rule
# ---------------------------------------------------------------------------

@register
class FlowAnalysis(Rule):
    """drflow (R13-R15): see the module docstring. One Rule riding
    draracer's extraction through the shared facts key; R15 is lexical
    (scan-phase, per-file cacheable), R13/R14 resolve whole-tree in
    finalize."""

    rule_id = "R13"
    provides = frozenset({"R13", "R14", "R15"})
    facts_key = "R9"  # the draracer extraction blob, stored once
    title = "escape / stale-snapshot / swallowed-error flow analysis"

    def __init__(self):
        self.tree_facts: Dict[str, Dict] = {}
        self._last_facts: Optional[Dict] = None
        # Populated by finalize for the CLI (--check-view-shadow):
        # every recognized view-read site and the statically implicated
        # subset, relpath:line-keyed like the lock witness.
        self.view_sites_recognized: Set[str] = set()
        self.view_sites_implicated: Set[str] = set()

    def scan(self, module: Module, ctx: ProjectContext) -> Iterator[Finding]:
        if module.is_test:
            return iter(())
        facts = extract_module(module)
        self.tree_facts[module.relpath] = facts
        self._last_facts = facts
        return r15_scan(module, ctx)

    def module_facts(self) -> Optional[Dict]:
        # Normally draracer (same facts_key, registered first) already
        # contributed the shared blob and the runner's setdefault keeps
        # that copy — but under a --rules filter that excludes R9-R11,
        # drflow is the only contributor; returning None there would
        # leave finalize with an EMPTY tree (no R13/R14 at all).
        facts, self._last_facts = self._last_facts, None
        return facts

    def absorb_facts(self, relpath: str, facts: Dict,
                     ctx: ProjectContext) -> None:
        self.tree_facts[relpath] = facts

    def finalize(self, ctx: ProjectContext) -> Iterator[Finding]:
        if not self.tree_facts:
            return
        res = shared_resolver(self.tree_facts)
        calls = _CalleeCache(res)
        r13 = _R13Pass(res, calls)
        yield from r13.run()
        self.view_sites_recognized = r13.recognized
        self.view_sites_implicated = r13.implicated
        yield from _R14Pass(res, calls).run()


# ---------------------------------------------------------------------------
# View-shadow cross-validation (the lint.sh observed⊆static gate)
# ---------------------------------------------------------------------------

def check_view_shadow(rule: FlowAnalysis,
                      drifts: Sequence[Dict]) -> List[str]:
    """Every runtime view-shadow drift (a zero-copy informer view whose
    content hash changed between hand-out and quiesce —
    k8s.informer.viewshadow) must be explained by the static escape
    analysis: its hand-out site must be an R13-implicated view seed.
    An unexplained drift means R13 under-approximates (or never saw
    the site at all) — the gate FAILS so the model gets fixed rather
    than quietly trusted. Returns violation lines (empty = validated);
    the standing green state is zero drifts AND zero findings."""
    out: List[str] = []
    for d in drifts:
        site = d.get("site", "?")
        what = d.get("key", d.get("kind", "object"))
        if site in rule.view_sites_implicated:
            continue
        if site not in rule.view_sites_recognized:
            out.append(
                f"view drift at {site} ({what}): site unknown to the "
                "static analyzer (not a recognized lister/"
                "get_by_index read — the extraction is blind to this "
                "hand-out path)")
        else:
            out.append(
                f"view drift at {site} ({what}): a runtime mutation "
                "of this view maps to NO static R13 finding — the "
                "escape analysis under-approximates this flow")
    return out
