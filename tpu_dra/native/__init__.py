"""L0 native-layer bindings (reference: cgo go-nvml usage in nvlib.go)."""

from tpu_dra.native.tpuinfo import (  # noqa: F401
    Chip, HealthEvent, TpuInfoBackend, NativeBackend, FakeBackend,
    get_backend, make_fake_sysfs, GEN_SPECS,
)
