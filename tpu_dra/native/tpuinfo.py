"""Python binding for libtpuinfo + in-process fake backend.

Reference mapping: this module is the seam the reference reaches through cgo
go-nvml (cmd/gpu-kubelet-plugin/nvlib.go:46-183 `deviceLib`), re-designed so
every upper layer can run against a hardware-free backend:

- ``NativeBackend`` — ctypes binding to the C++ ``libtpuinfo.so`` (which
  itself accepts an injectable filesystem root, so even the native path is
  testable against a synthetic sysfs tree).
- ``FakeBackend`` — pure-Python, in-process, programmable chips + health
  event injection; selected with ``TPU_DRA_TPUINFO_BACKEND=fake``.

Both implement ``TpuInfoBackend``. ``get_backend()`` picks by env.
"""

from __future__ import annotations

import ctypes
import logging
import os
import queue
import sys
import threading
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

# Generation table mirrored from native/src/tpuinfo.cc kGenTable.
GEN_SPECS: Dict[str, Tuple[int, int]] = {
    # name -> (tensorcore_count, hbm_bytes)
    "v4": (2, 32 << 30),
    "v5e": (1, 16 << 30),
    "v5p": (2, 95 << 30),
    "v6e": (1, 32 << 30),
}

# Public per-chip peak dense bf16 TFLOP/s per generation (cloud.google.com
# TPU system architecture pages); denominator for MFU reporting.
PEAK_BF16_TFLOPS: Dict[str, float] = {
    "v4": 275.0,
    "v5e": 197.0,
    "v5p": 459.0,
    "v6e": 918.0,
}


def generation_from_device_kind(device_kind: str) -> Optional[str]:
    """Map a JAX `device_kind` string (e.g. 'TPU v5 lite') to a generation
    key in GEN_SPECS/PEAK_BF16_TFLOPS."""
    k = device_kind.lower()
    if "v6" in k or "trillium" in k:
        return "v6e"
    if "v5 lite" in k or "v5e" in k or "v5lite" in k:
        return "v5e"
    if "v5p" in k or "v5" in k:
        return "v5p"
    if "v4" in k:
        return "v4"
    return None


@dataclass(frozen=True)
class Chip:
    """One TPU chip (GpuInfo analog, nvlib.go:261-385)."""
    index: int
    uuid: str
    generation: str
    tensorcore_count: int
    hbm_bytes: int
    pci_address: str = ""
    driver_version: str = "unknown"
    slice_id: str = ""
    worker_index: int = 0
    coords: Tuple[int, int, int] = (0, 0, 0)
    # Declared dims of the slice this chip belongs to ("4x4x4"); empty
    # when the backend does not know (topology then falls back to the
    # discovered coordinate bounding box).
    slice_topology: str = ""
    healthy: bool = True

    @property
    def dev_path(self) -> str:
        return f"/dev/accel{self.index}"


@dataclass(frozen=True)
class HealthEvent:
    """Accel-driver health event (NVML Xid/ECC event analog,
    device_health.go:36-117). chip_index == -1 addresses all chips."""
    chip_index: int
    code: int
    kind: str
    description: str = ""


class TpuInfoBackend:
    kind = "unknown"  # which implementation served the inventory

    def chips(self) -> List[Chip]:
        raise NotImplementedError

    def get_chip(self, index: int) -> Chip:
        for c in self.chips():
            if c.index == index:
                return c
        raise KeyError(f"no chip with index {index}")

    def set_timeslice(self, index: int, interval_us: int) -> None:
        raise NotImplementedError

    def get_timeslice(self, index: int) -> Optional[int]:
        raise NotImplementedError

    def set_exclusive_mode(self, index: int, exclusive: bool) -> None:
        raise NotImplementedError

    def wait_health_event(self, timeout: float) -> Optional[HealthEvent]:
        """Block up to `timeout` seconds; None on timeout."""
        raise NotImplementedError

    def driver_version(self) -> str:
        chips = self.chips()
        return chips[0].driver_version if chips else "unknown"

    def close(self) -> None:
        pass


# ---------------------------------------------------------------------------
# Native backend (ctypes -> libtpuinfo.so)
# ---------------------------------------------------------------------------

_MAX_STR = 96


class _CChip(ctypes.Structure):
    _fields_ = [
        ("index", ctypes.c_int32),
        ("uuid", ctypes.c_char * _MAX_STR),
        ("generation", ctypes.c_int32),
        ("generation_name", ctypes.c_char * 16),
        ("tensorcore_count", ctypes.c_int32),
        ("hbm_bytes", ctypes.c_int64),
        ("pci_address", ctypes.c_char * 32),
        ("driver_version", ctypes.c_char * 32),
        ("slice_id", ctypes.c_char * _MAX_STR),
        ("worker_index", ctypes.c_int32),
        ("coord_x", ctypes.c_int32),
        ("coord_y", ctypes.c_int32),
        ("coord_z", ctypes.c_int32),
        ("healthy", ctypes.c_int32),
    ]


class _CEvent(ctypes.Structure):
    _fields_ = [
        ("chip_index", ctypes.c_int32),
        ("code", ctypes.c_int32),
        ("kind", ctypes.c_char * 32),
        ("description", ctypes.c_char * _MAX_STR),
    ]


def _default_lib_path() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    candidates = [
        os.environ.get("TPU_DRA_LIBTPUINFO", ""),
        os.path.join(here, "..", "..", "native", "build", "libtpuinfo.so"),
        "/usr/local/lib/libtpuinfo.so",
        "/usr/lib/libtpuinfo.so",
    ]
    for c in candidates:
        if c and os.path.exists(c):
            return os.path.abspath(c)
    raise FileNotFoundError(
        "libtpuinfo.so not found; build with `make -C native` or set "
        "TPU_DRA_LIBTPUINFO")


class NativeBackend(TpuInfoBackend):
    """Binding to the C++ library. The reference's driver-root resolution
    (root.go:26-110 locating libnvidia-ml.so.1 under a configurable host
    root) maps to the lib-path candidates + TPU_DRA_LIBTPUINFO override."""

    kind = "native"
    _TIMEOUT_STATUS = -4  # TPUINFO_ERR_TIMEOUT
    _NOT_FOUND_STATUS = -1

    def __init__(self, sysfs_root: str = "", lib_path: Optional[str] = None):
        self._lib = ctypes.CDLL(lib_path or _default_lib_path())
        self._lib.tpuinfo_init.argtypes = [ctypes.c_char_p, ctypes.POINTER(ctypes.c_void_p)]
        self._lib.tpuinfo_get_chip.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.POINTER(_CChip)]
        self._lib.tpuinfo_chip_count.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int32)]
        self._lib.tpuinfo_wait_health_event.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.POINTER(_CEvent)]
        self._lib.tpuinfo_set_timeslice.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32]
        self._lib.tpuinfo_get_timeslice.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.POINTER(ctypes.c_int32)]
        self._lib.tpuinfo_set_exclusive_mode.argtypes = [
            ctypes.c_void_p, ctypes.c_int32, ctypes.c_int32]
        self._lib.tpuinfo_status_string.restype = ctypes.c_char_p
        self._lib.tpuinfo_status_string.argtypes = [ctypes.c_int32]

        ctx = ctypes.c_void_p()
        st = self._lib.tpuinfo_init(sysfs_root.encode(), ctypes.byref(ctx))
        if st != 0:
            raise RuntimeError(f"tpuinfo_init({sysfs_root!r}): {self._strerror(st)}")
        self._ctx = ctx

    def _strerror(self, st: int) -> str:
        return self._lib.tpuinfo_status_string(st).decode()

    def _check(self, st: int, what: str) -> None:
        if st != 0:
            raise RuntimeError(f"{what}: {self._strerror(st)}")

    def chips(self) -> List[Chip]:
        n = ctypes.c_int32()
        self._check(self._lib.tpuinfo_chip_count(self._ctx, ctypes.byref(n)),
                    "tpuinfo_chip_count")
        out: List[Chip] = []
        idx = 0
        scanned = 0
        while scanned < n.value and idx < 4096:
            c = _CChip()
            st = self._lib.tpuinfo_get_chip(self._ctx, idx, ctypes.byref(c))
            if st == 0:
                out.append(Chip(
                    index=c.index,
                    uuid=c.uuid.decode(),
                    generation=c.generation_name.decode(),
                    tensorcore_count=c.tensorcore_count,
                    hbm_bytes=c.hbm_bytes,
                    pci_address=c.pci_address.decode(),
                    driver_version=c.driver_version.decode(),
                    slice_id=c.slice_id.decode(),
                    worker_index=c.worker_index,
                    coords=(c.coord_x, c.coord_y, c.coord_z),
                    healthy=bool(c.healthy),
                ))
                scanned += 1
            elif st != self._NOT_FOUND_STATUS:
                self._check(st, f"tpuinfo_get_chip({idx})")
            idx += 1
        return out

    def set_timeslice(self, index: int, interval_us: int) -> None:
        self._check(self._lib.tpuinfo_set_timeslice(self._ctx, index, interval_us),
                    f"tpuinfo_set_timeslice({index})")

    def get_timeslice(self, index: int) -> Optional[int]:
        v = ctypes.c_int32()
        st = self._lib.tpuinfo_get_timeslice(self._ctx, index, ctypes.byref(v))
        if st == self._NOT_FOUND_STATUS:
            return None
        self._check(st, f"tpuinfo_get_timeslice({index})")
        return v.value

    def set_exclusive_mode(self, index: int, exclusive: bool) -> None:
        self._check(self._lib.tpuinfo_set_exclusive_mode(
            self._ctx, index, 1 if exclusive else 0),
            f"tpuinfo_set_exclusive_mode({index})")

    def wait_health_event(self, timeout: float) -> Optional[HealthEvent]:
        ev = _CEvent()
        st = self._lib.tpuinfo_wait_health_event(
            self._ctx, int(timeout * 1000), ctypes.byref(ev))
        if st == self._TIMEOUT_STATUS:
            return None
        self._check(st, "tpuinfo_wait_health_event")
        return HealthEvent(chip_index=ev.chip_index, code=ev.code,
                           kind=ev.kind.decode(), description=ev.description.decode())

    def close(self) -> None:
        if getattr(self, "_ctx", None):
            self._lib.tpuinfo_shutdown(self._ctx)
            self._ctx = None


# ---------------------------------------------------------------------------
# Fake backend
# ---------------------------------------------------------------------------

def default_fake_chips(count: int = 4, generation: str = "v5e",
                       slice_id: str = "", worker_index: int = 0,
                       total_workers: int = 1) -> List[Chip]:
    """`count` fake chips laid out as a real per-generation slice: 3D
    near-cubic torus dims for v4/v5p, 2D (z=1) for v5e/v6e
    (tpu_dra.topology.mesh.topology_dims). Multi-host slices: the slice
    spans `total_workers` hosts of `count` chips each and this host is
    `worker_index` — coords are the host's block of the GLOBAL slice
    coordinate space, so the union across workers is a valid dense mesh
    and each worker's block is disjoint."""
    from tpu_dra.topology.mesh import format_topology, topology_dims

    if not 0 <= worker_index < total_workers:
        raise ValueError(f"worker_index {worker_index} outside "
                         f"total_workers {total_workers}")
    cores, hbm = GEN_SPECS[generation]
    dims = topology_dims(generation, count * total_workers)
    topo = format_topology(dims)
    out: List[Chip] = []
    for i in range(count):
        g = worker_index * count + i  # global position within the slice
        coords = (g % dims[0], (g // dims[0]) % dims[1],
                  g // (dims[0] * dims[1]))
        out.append(Chip(
            index=i, uuid=f"tpu-{generation}-{worker_index}-{i:02d}-fake"
            if total_workers > 1 else f"tpu-{generation}-{i:02d}-fake",
            generation=generation,
            tensorcore_count=cores, hbm_bytes=hbm,
            pci_address=f"0000:0{i}:00.0", driver_version="1.0.0-fake",
            slice_id=slice_id, worker_index=worker_index,
            coords=coords, slice_topology=topo))
    return out


class FakeBackend(TpuInfoBackend):
    """In-process fake: programmable chips, settings recorded, health events
    injectable. This is the unit-test seam the reference lacks (SURVEY §4.1:
    'no unit tests for device_state/nvlib/cdi — the TPU build should do
    better here')."""

    kind = "fake"

    def __init__(self, chips: Optional[List[Chip]] = None):
        if chips is None:
            count = int(os.environ.get("TPU_DRA_FAKE_CHIPS", "4"))
            gen = os.environ.get("TPU_DRA_FAKE_GENERATION", "v5e")
            slice_id = os.environ.get("TPU_DRA_FAKE_SLICE_ID", "")
            worker = int(os.environ.get("TPU_DRA_FAKE_WORKER_INDEX", "0"))
            workers = int(os.environ.get("TPU_DRA_FAKE_TOTAL_WORKERS", "0"))
            chips = default_fake_chips(count, gen, slice_id, worker,
                                       total_workers=max(workers,
                                                         worker + 1, 1))
        self._chips: Dict[int, Chip] = {c.index: c for c in chips}
        self.timeslices: Dict[int, int] = {}
        self.exclusive: Dict[int, bool] = {}
        self._events: "queue.Queue[HealthEvent]" = queue.Queue()
        self._lock = threading.Lock()

    def chips(self) -> List[Chip]:
        with self._lock:
            return [self._chips[i] for i in sorted(self._chips)]

    def set_timeslice(self, index: int, interval_us: int) -> None:
        self.get_chip(index)
        self.timeslices[index] = interval_us

    def get_timeslice(self, index: int) -> Optional[int]:
        return self.timeslices.get(index)

    def set_exclusive_mode(self, index: int, exclusive: bool) -> None:
        self.get_chip(index)
        self.exclusive[index] = exclusive

    def wait_health_event(self, timeout: float) -> Optional[HealthEvent]:
        try:
            return self._events.get(timeout=timeout)
        except queue.Empty:
            return None

    # -- test hooks ---------------------------------------------------------

    def inject_health_event(self, event: HealthEvent) -> None:
        self._events.put(event)
        # Mirror the driver's semantics in the fake's own chip model:
        # faults mark unhealthy, 'recovered' restores, 'info' is neutral.
        if event.kind == "info":
            return
        healthy = event.kind == "recovered"
        with self._lock:
            for idx in ([event.chip_index] if event.chip_index >= 0
                        else list(self._chips)):
                if idx in self._chips:
                    self._chips[idx] = replace(self._chips[idx],
                                               healthy=healthy)

    def set_chip(self, chip: Chip) -> None:
        with self._lock:
            self._chips[chip.index] = chip

    def remove_chip(self, index: int) -> None:
        with self._lock:
            self._chips.pop(index, None)


# ---------------------------------------------------------------------------
# Fake sysfs materialization (drives the *native* lib + tpuctl in tests/CI)
# ---------------------------------------------------------------------------

def make_fake_sysfs(root: str, chips: List[Chip]) -> str:
    """Write the accel driver's filesystem ABI for the given chips under
    `root` (the kind-cluster / CI analog of SURVEY §4.2's simulated accel
    device directory)."""
    os.makedirs(os.path.join(root, "dev"), exist_ok=True)
    class_dir = os.path.join(root, "sys", "class", "accel")
    os.makedirs(class_dir, exist_ok=True)
    for chip in chips:
        # Char device stand-in (a regular file: stat() is what's checked).
        open(os.path.join(root, "dev", f"accel{chip.index}"), "w").close()
        dev = os.path.join(class_dir, f"accel{chip.index}", "device")
        topo = os.path.join(dev, "topology")
        os.makedirs(topo, exist_ok=True)
        writes = {
            os.path.join(dev, "generation"): chip.generation,
            os.path.join(dev, "uuid"): chip.uuid,
            os.path.join(dev, "tensorcore_count"): str(chip.tensorcore_count),
            os.path.join(dev, "hbm_bytes"): str(chip.hbm_bytes),
            os.path.join(dev, "pci_address"): chip.pci_address,
            os.path.join(dev, "driver_version"): chip.driver_version,
            os.path.join(dev, "health"): "ok" if chip.healthy else "failed",
            os.path.join(topo, "slice_id"): chip.slice_id,
            os.path.join(topo, "worker_index"): str(chip.worker_index),
            os.path.join(topo, "coords"): ",".join(map(str, chip.coords)),
        }
        for path, content in writes.items():
            with open(path, "w") as f:
                f.write(content + "\n")
    # Health events file exists (empty) so tailing starts cleanly.
    open(os.path.join(class_dir, "health_events"), "a").close()
    _materialize_pci(root, chips)
    return root


def _materialize_pci(root: str, chips: List[Chip]) -> None:
    """PCI/IOMMU sysfs topology for the passthrough path
    (tpu_dra/tpuplugin/passthrough.py): per-device driver symlink +
    driver_override, per-driver bind/unbind files, IOMMU groups (group id
    = chip index), vfio module dir and /dev/vfio nodes."""
    drivers = os.path.join(root, "sys", "bus", "pci", "drivers")
    for drv in ("tpu-accel", "vfio-pci"):
        os.makedirs(os.path.join(drivers, drv), exist_ok=True)
        for f in ("bind", "unbind"):
            open(os.path.join(drivers, drv, f), "w").close()
    os.makedirs(os.path.join(root, "sys", "module", "vfio_pci"),
                exist_ok=True)
    os.makedirs(os.path.join(root, "dev", "vfio"), exist_ok=True)
    open(os.path.join(root, "dev", "vfio", "vfio"), "w").close()
    devices = os.path.join(root, "sys", "bus", "pci", "devices")
    groups = os.path.join(root, "sys", "kernel", "iommu_groups")
    for chip in chips:
        if not chip.pci_address:
            continue
        ddir = os.path.join(devices, chip.pci_address)
        os.makedirs(ddir, exist_ok=True)
        open(os.path.join(ddir, "driver_override"), "w").close()
        drv_link = os.path.join(ddir, "driver")
        if not os.path.islink(drv_link):
            os.symlink(os.path.join("..", "..", "drivers", "tpu-accel"),
                       drv_link)
        gdir = os.path.join(groups, str(chip.index), "devices")
        os.makedirs(gdir, exist_ok=True)
        dev_link = os.path.join(gdir, chip.pci_address)
        if not os.path.islink(dev_link):
            os.symlink(ddir, dev_link)
        grp_link = os.path.join(ddir, "iommu_group")
        if not os.path.islink(grp_link):
            os.symlink(os.path.join(groups, str(chip.index)), grp_link)
        open(os.path.join(root, "dev", "vfio", str(chip.index)),
             "w").close()


def append_health_event(root: str, event: HealthEvent) -> None:
    """Append an event record to the fake sysfs tree (native-path injection)."""
    path = os.path.join(root, "sys", "class", "accel", "health_events")
    with open(path, "a") as f:
        f.write(f"{event.chip_index} {event.code} {event.kind} {event.description}\n")


def probe_jax_tpu_devices() -> Optional[Tuple[int, str]]:
    """(device_count, device_kind) when this process's JAX has *already*
    initialized a TPU backend; None otherwise. Deliberately never triggers
    backend initialization itself — that is seconds of work (and possibly a
    hard failure) the driver's hot path must not absorb."""
    jax_mod = sys.modules.get("jax")
    if jax_mod is None:
        return None
    try:
        from jax._src import xla_bridge
        initialized = getattr(xla_bridge, "backends_are_initialized",
                              lambda: bool(getattr(xla_bridge, "_backends", None)))
        if not initialized():
            return None
        if jax_mod.default_backend() != "tpu":
            return None
        devs = jax_mod.devices()
        return len(devs), getattr(devs[0], "device_kind", "")
    except Exception:  # noqa: BLE001 # drflow: swallow-ok[advisory probe: no importable TPU backend is the normal outcome on CPU hosts]
        return None


def has_accel_sysfs(root: Optional[str] = None) -> bool:
    """Single source of truth for 'this host has the accel driver's sysfs
    class' — used by get_backend(auto) and the bench's backend picker."""
    if root is None:
        root = os.environ.get("TPUINFO_SYSFS_ROOT", "")
    return os.path.isdir(os.path.join(root or "/", "sys", "class", "accel"))


def get_backend(jax_tpu_devices: Optional[int] = None) -> TpuInfoBackend:
    """Select backend by TPU_DRA_TPUINFO_BACKEND: 'fake', 'native', or
    'auto' (native when an accel sysfs class exists, else fake).

    Auto-selection **refuses** to serve fake chips on a host where JAX has
    a real TPU backend (passed via `jax_tpu_devices`, or probed from an
    already-initialized in-process JAX): fake inventory on real hardware
    means every claim the driver prepares lies about the machine
    (round-1 failure mode — psum ran on 1 real device while the claim
    said 4 fake chips). Set TPU_DRA_TPUINFO_BACKEND=fake to override
    explicitly.
    """
    choice = os.environ.get("TPU_DRA_TPUINFO_BACKEND", "auto")
    if choice == "fake":
        return FakeBackend()
    if choice == "native":
        return NativeBackend(sysfs_root=os.environ.get("TPUINFO_SYSFS_ROOT", ""))
    # auto: native when a real accel class dir exists, else fake
    if has_accel_sysfs():
        return NativeBackend(
            sysfs_root=os.environ.get("TPUINFO_SYSFS_ROOT", ""))
    if jax_tpu_devices is None:
        probed = probe_jax_tpu_devices()
        jax_tpu_devices = probed[0] if probed else 0
    if jax_tpu_devices:
        raise RuntimeError(
            f"get_backend(auto): this host exposes {jax_tpu_devices} real "
            "TPU device(s) through JAX/libtpu but no accel sysfs class dir "
            "for the native backend; refusing to silently serve fake chips "
            "on real hardware. Set TPUINFO_SYSFS_ROOT to the accel tree, or "
            "TPU_DRA_TPUINFO_BACKEND=fake to run with fake inventory "
            "deliberately.")
    logger.warning(
        "get_backend(auto): no accel sysfs and no TPU visible to JAX — "
        "serving the fake chip backend (TPU_DRA_FAKE_CHIPS=%s)",
        os.environ.get("TPU_DRA_FAKE_CHIPS", "4"))
    return FakeBackend()
