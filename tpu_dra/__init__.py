"""tpu_dra — a TPU-native Kubernetes Dynamic Resource Allocation driver.

A ground-up rebuild of the capabilities of NVIDIA's k8s-dra-driver-gpu
(reference: /root/reference) for TPU pods:

- chip discovery via a native C++ ``libtpuinfo`` over ``/dev/accel*`` and
  ``/sys/class/accel`` (replaces NVML/go-nvlib cgo enumeration),
- CDI injection of ``/dev/accelN`` + ``TPU_VISIBLE_CHIPS``/libtpu env
  (replaces nvidia-container-toolkit CDI specs),
- TPU-core subslicing (replaces dynamic MIG partitioning),
- time-sliced / multiprocess chip sharing (replaces time-slicing / MPS),
- ICI-connected slice provisioning via the ComputeDomain controller/daemon
  pair (replaces IMEX-channel Multi-Node-NVLink orchestration).

Layer map (see SURVEY.md §1):

- ``tpu_dra.api``         — L6 config kinds + ComputeDomain CRD
- ``tpu_dra.k8s``         — client/informer machinery (replaces client-go +
  generated clientset/informers/listers of pkg/nvidia.com)
- ``tpu_dra.infra``       — L5 workqueue/flock/featuregates/flags
- ``tpu_dra.native``      — L0 bindings to the C++ libtpuinfo
- ``tpu_dra.cdi``         — L1 container integration
- ``tpu_dra.kubeletplugin`` — L3 DRA gRPC plugin framework
- ``tpu_dra.tpuplugin``   — L2/L3 TPU kubelet plugin (gpu-kubelet-plugin analog)
- ``tpu_dra.cdplugin``    — ComputeDomain kubelet plugin
- ``tpu_dra.cdcontroller`` — L4 cluster controller
- ``tpu_dra.cddaemon``    — L4b per-node slice daemon wrapper
- ``tpu_dra.webhook``     — validating admission webhook
- ``tpu_dra.workloads``   — JAX workloads driven by driver-provisioned slices
"""

from tpu_dra.version import __version__  # noqa: F401
