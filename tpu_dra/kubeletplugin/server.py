"""DRA plugin gRPC server + registration + ResourceSlice publishing.

Reference: the kubeletplugin.Helper from k8s.io/dynamic-resource-allocation
that cmd/*/driver.go:73-82 builds on. It:

- serves the DRAPlugin service (NodePrepareResources/NodeUnprepareResources)
  on a unix socket under the plugin dir,
- serves the Registration service on a socket under the kubelet plugin
  registry so kubelet's plugin watcher discovers the driver,
- publishes ResourceSlices describing this node's devices to the API
  server (PublishResources, driver.go:217-235).

The gRPC services are registered with hand-rolled method handlers (we
generate message gencode with protoc but service stubs by hand — grpc_tools
is not available in this environment).
"""

from __future__ import annotations

import os
import threading
import time
from concurrent import futures
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import grpc

from tpu_dra.kubeletplugin.gen import dra_v1_pb2 as dra
from tpu_dra.kubeletplugin.gen import pluginregistration_pb2 as reg
from tpu_dra.k8s import ApiClient, RESOURCESLICES


@dataclass
class PreparedDevice:
    """One device result returned to kubelet (dra.v1 Device)."""
    pool_name: str
    device_name: str
    cdi_device_ids: List[str] = field(default_factory=list)
    request_names: List[str] = field(default_factory=list)


@dataclass
class PrepareResult:
    devices: List[PreparedDevice] = field(default_factory=list)
    error: str = ""


@dataclass
class Claim:
    uid: str
    name: str
    namespace: str


class DriverCallbacks:
    """Implemented by each driver (gpu/cd kubelet plugin device states).

    The claims list is the RPC's batch — kubelet sends a pod's claims in
    ONE NodePrepareResources call. Implementations must return one entry
    per claim uid with per-claim error isolation (one bad claim must not
    fail its batch siblings); they may treat the batch as a single unit
    of work (one lock acquisition, group-committed durable state)."""

    def prepare_claims(self, claims: List[Claim]) -> Dict[str, PrepareResult]:
        raise NotImplementedError

    def unprepare_claims(self, claims: List[Claim]) -> Dict[str, str]:
        """uid -> error string ('' = success)."""
        raise NotImplementedError

    def record_wire(self, stage_s: Dict[str, float]) -> None:
        """Server-side wire-time attribution hook: per-RPC seconds for
        the request-decode and response-encode stages plus the whole
        handler wall ({'decode','encode','handler'}). Drivers that
        attribute claim-to-ready override this (tpuplugin); the default
        drops it."""


def _dra_service(callbacks: DriverCallbacks) -> grpc.GenericRpcHandler:
    def node_prepare(request: dra.NodePrepareResourcesRequest, context):
        t_in = time.perf_counter()
        claims = [Claim(uid=c.uid, name=c.name, namespace=c.namespace)
                  for c in request.claims]
        t_decoded = time.perf_counter()
        results = dict(callbacks.prepare_claims(claims))
        for claim in claims:
            # A driver bug that dropped a claim from the result map must
            # surface as that claim's error, not a missing response entry
            # kubelet could misread as success-shaped.
            results.setdefault(
                claim.uid,
                PrepareResult(error="driver returned no result for claim"))
        t_done = time.perf_counter()
        resp = dra.NodePrepareResourcesResponse()
        for uid, res in results.items():
            # Built in place: the map entry materializes on first access,
            # avoiding a per-claim message copy on the hot path.
            out = resp.claims[uid]
            if res.error:
                out.error = res.error
            else:
                for d in res.devices:
                    dev = out.devices.add()
                    dev.pool_name = d.pool_name
                    dev.device_name = d.device_name
                    dev.cdi_device_ids.extend(d.cdi_device_ids)
                    dev.request_names.extend(d.request_names)
        t_out = time.perf_counter()
        callbacks.record_wire({"decode": t_decoded - t_in,
                               "encode": t_out - t_done,
                               "handler": t_out - t_in})
        return resp

    def node_unprepare(request: dra.NodeUnprepareResourcesRequest, context):
        claims = [Claim(uid=c.uid, name=c.name, namespace=c.namespace)
                  for c in request.claims]
        errors = dict(callbacks.unprepare_claims(claims))
        for claim in claims:
            errors.setdefault(claim.uid,
                              "driver returned no result for claim")
        resp = dra.NodeUnprepareResourcesResponse()
        for uid, err in errors.items():
            if err:
                resp.claims[uid].error = err
            else:
                # Success: materialize an empty entry for the uid.
                resp.claims[uid].SetInParent()
        return resp

    handlers = {
        "NodePrepareResources": grpc.unary_unary_rpc_method_handler(
            node_prepare,
            request_deserializer=dra.NodePrepareResourcesRequest.FromString,
            response_serializer=dra.NodePrepareResourcesResponse.SerializeToString),
        "NodeUnprepareResources": grpc.unary_unary_rpc_method_handler(
            node_unprepare,
            request_deserializer=dra.NodeUnprepareResourcesRequest.FromString,
            response_serializer=dra.NodeUnprepareResourcesResponse.SerializeToString),
    }
    return grpc.method_handlers_generic_handler(
        "k8s.io.kubelet.pkg.apis.dra.v1.DRAPlugin", handlers)


def _registration_service(driver_name: str, endpoint: str,
                          on_status: Optional[Callable[[bool, str], None]] = None
                          ) -> grpc.GenericRpcHandler:
    def get_info(request: reg.InfoRequest, context):
        return reg.PluginInfo(type="DRAPlugin", name=driver_name,
                              endpoint=endpoint, supported_versions=["v1"])

    def notify(request: reg.RegistrationStatus, context):
        if on_status:
            on_status(request.plugin_registered, request.error)
        return reg.RegistrationStatusResponse()

    handlers = {
        "GetInfo": grpc.unary_unary_rpc_method_handler(
            get_info,
            request_deserializer=reg.InfoRequest.FromString,
            response_serializer=reg.PluginInfo.SerializeToString),
        "NotifyRegistrationStatus": grpc.unary_unary_rpc_method_handler(
            notify,
            request_deserializer=reg.RegistrationStatus.FromString,
            response_serializer=reg.RegistrationStatusResponse.SerializeToString),
    }
    return grpc.method_handlers_generic_handler("pluginregistration.Registration",
                                                handlers)


def self_probe(server: "DRAPluginServer", timeout: float = 3.0) -> bool:
    """Liveness self-probe (gpu plugin health.go:118-144): dial the
    plugin's own sockets as kubelet would — GetInfo on the registration
    socket, NodePrepareResources with an empty request on the DRA socket —
    and report whether both answered."""
    try:
        channel, prepare, _ = kubelet_stubs(server.dra_socket)
        try:
            prepare(dra.NodePrepareResourcesRequest(), timeout=timeout)
        finally:
            channel.close()
        reg_sock = getattr(server, "registration_socket", None)
        if reg_sock:
            reg_channel = grpc.insecure_channel(f"unix://{reg_sock}")
            try:
                get_info = reg_channel.unary_unary(
                    "/pluginregistration.Registration/GetInfo",
                    request_serializer=reg.InfoRequest.SerializeToString,
                    response_deserializer=reg.PluginInfo.FromString)
                info = get_info(reg.InfoRequest(), timeout=timeout)
                if info.name != server.driver_name:
                    return False
            finally:
                reg_channel.close()
        return True
    except grpc.RpcError:
        return False


def kubelet_stubs(dra_socket: str):
    """Client-side stubs acting as kubelet: (channel, prepare, unprepare).

    Single source of truth for the DRA v1 method paths/serializers used by
    the bench harness and the e2e tests; close the returned channel when
    done."""
    channel = grpc.insecure_channel(f"unix://{dra_socket}")
    prepare = channel.unary_unary(
        "/k8s.io.kubelet.pkg.apis.dra.v1.DRAPlugin/NodePrepareResources",
        request_serializer=dra.NodePrepareResourcesRequest.SerializeToString,
        response_deserializer=dra.NodePrepareResourcesResponse.FromString)
    unprepare = channel.unary_unary(
        "/k8s.io.kubelet.pkg.apis.dra.v1.DRAPlugin/NodeUnprepareResources",
        request_serializer=dra.NodeUnprepareResourcesRequest.SerializeToString,
        response_deserializer=dra.NodeUnprepareResourcesResponse.FromString)
    return channel, prepare, unprepare


class DRAPluginServer:
    """Hosts the DRA + Registration services on unix sockets.

    plugin_dir:   /var/lib/kubelet/plugins/<driver>/   (dra.sock lives here)
    registry_dir: /var/lib/kubelet/plugins_registry/   (watcher socket)
    """

    def __init__(self, driver_name: str, node_name: str,
                 callbacks: DriverCallbacks,
                 plugin_dir: str, registry_dir: Optional[str] = None):
        self.driver_name = driver_name
        self.node_name = node_name
        self._callbacks = callbacks
        self._plugin_dir = plugin_dir
        self._registry_dir = registry_dir
        os.makedirs(plugin_dir, exist_ok=True)
        if registry_dir:
            os.makedirs(registry_dir, exist_ok=True)
        self.dra_socket = os.path.join(plugin_dir, "dra.sock")
        self.registration_registered = threading.Event()
        self._server: Optional[grpc.Server] = None
        self._reg_server: Optional[grpc.Server] = None
        self._stopped = False
        # Serializes start_registration() against stop(): they run on
        # different threads (publish retry queue vs driver shutdown).
        self._reg_lock = threading.Lock()

    def start(self, register: bool = True) -> None:
        for sock in [self.dra_socket]:
            if os.path.exists(sock):
                os.unlink(sock)
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=8),
            handlers=[_dra_service(self._callbacks)])
        self._server.add_insecure_port(f"unix://{self.dra_socket}")
        self._server.start()
        if register:
            self.start_registration()

    def start_registration(self) -> None:
        """Expose the plugin-watcher socket. Separate from start() so the
        driver can gate kubelet registration on the first successful
        ResourceSlice publish (the reference Helper's sequencing,
        driver.go:73-116): kubelet should not route claims here before the
        scheduler can see this node's inventory. Idempotent, and refuses
        after stop(): the gated first publish runs on the retry queue,
        whose worker can still be mid-callback when the driver shuts down
        — starting a registration server then would leak it (nothing will
        ever stop it) and advertise a dead plugin to kubelet."""
        with self._reg_lock:
            if self._stopped or self._reg_server is not None \
                    or not self._registry_dir:
                return
            reg_sock = os.path.join(
                self._registry_dir, f"{self.driver_name}-reg.sock")
            if os.path.exists(reg_sock):
                os.unlink(reg_sock)
            self._reg_server = grpc.server(
                futures.ThreadPoolExecutor(max_workers=2),
                handlers=[_registration_service(
                    self.driver_name, self.dra_socket,
                    on_status=lambda ok, err: (
                        self.registration_registered.set() if ok else None))])
            self._reg_server.add_insecure_port(f"unix://{reg_sock}")
            self._reg_server.start()
            self.registration_socket = reg_sock

    def stop(self, grace: float = 2.0) -> None:
        with self._reg_lock:
            self._stopped = True
        if self._server:
            self._server.stop(grace).wait()
        if self._reg_server:
            self._reg_server.stop(grace).wait()


# ---------------------------------------------------------------------------
# ResourceSlice publishing
# ---------------------------------------------------------------------------

def build_resource_slice(driver_name: str, node_name: str,
                         devices: List[Dict], pool_generation: int = 1) -> Dict:
    """Render a resource.k8s.io/v1 ResourceSlice for this node's devices
    (publishResources, driver.go:217-235). `devices` entries are
    {name, attributes, capacity} dicts produced by the device model."""
    return {
        "apiVersion": "resource.k8s.io/v1",
        "kind": "ResourceSlice",
        "metadata": {
            "name": f"{node_name}-{driver_name}",
            "ownerReferences": [],
        },
        "spec": {
            "driver": driver_name,
            "nodeName": node_name,
            "pool": {
                "name": node_name,
                "generation": pool_generation,
                "resourceSliceCount": 1,
            },
            "devices": devices,
        },
    }


def publish_resources(client: ApiClient, driver_name: str, node_name: str,
                      devices: List[Dict], pool_generation: int = 1) -> Dict:
    """Create-or-update this node's ResourceSlice."""
    slice_obj = build_resource_slice(driver_name, node_name, devices,
                                     pool_generation)
    from tpu_dra.k8s.client import NotFoundError
    try:
        current = client.get(RESOURCESLICES, slice_obj["metadata"]["name"])
        slice_obj["metadata"]["resourceVersion"] = \
            current["metadata"].get("resourceVersion")
        return client.update(RESOURCESLICES, slice_obj)
    except NotFoundError:
        return client.create(RESOURCESLICES, slice_obj)
