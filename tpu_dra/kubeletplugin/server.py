"""DRA plugin RPC server + registration + ResourceSlice publishing.

Reference: the kubeletplugin.Helper from k8s.io/dynamic-resource-allocation
that cmd/*/driver.go:73-82 builds on. It:

- serves the DRAPlugin service (NodePrepareResources/NodeUnprepareResources)
  on a unix socket under the plugin dir,
- serves the Registration service on a socket under the kubelet plugin
  registry so kubelet's plugin watcher discovers the driver,
- publishes ResourceSlices describing this node's devices to the API
  server (PublishResources, driver.go:217-235).

The gRPC services are registered with hand-rolled method handlers (we
generate message gencode with protoc but service stubs by hand — grpc_tools
is not available in this environment).

Since SURVEY §21 the front-end is ASYNC: one event loop thread (see
aio_server.py) hosts a grpc.aio server on the kubelet DRA socket (wire
compatibility — kubelet speaks gRPC) plus a framed-RPC listener on
``dra-fast.sock`` (the sub-0.5ms prepare transport). Both feed the SAME
blocking handlers — pipeline admission, SharedFlock, group commit —
through a shared executor; the thread-per-RPC ``grpc.server`` is
retired. The handlers themselves are transport-independent
(``DraHandlers``), which is what the PR 7 seam promised: the server
swapped, ``DeviceState`` and the admission pipeline did not move.
"""

from __future__ import annotations

import asyncio
import os
import socket
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

import grpc

from tpu_dra.infra.faults import FAULTS, FaultInjected
from tpu_dra.infra.metrics import DefaultRegistry
from tpu_dra.kubeletplugin import aio_server
from tpu_dra.kubeletplugin.aio_server import (
    FRAME_HEADER, METHOD_ERROR, METHOD_PING, METHOD_PREPARE,
    METHOD_UNPREPARE, EventLoopThread, FramedRpcServer,
    aio_service_handlers,
)
from tpu_dra.kubeletplugin.gen import dra_v1_pb2 as dra
from tpu_dra.kubeletplugin.gen import pluginregistration_pb2 as reg
from tpu_dra.k8s import ApiClient, RESOURCESLICES

_DRA_SERVICE = "k8s.io.kubelet.pkg.apis.dra.v1.DRAPlugin"
_REG_SERVICE = "pluginregistration.Registration"


@dataclass
class PreparedDevice:
    """One device result returned to kubelet (dra.v1 Device)."""
    pool_name: str
    device_name: str
    cdi_device_ids: List[str] = field(default_factory=list)
    request_names: List[str] = field(default_factory=list)


@dataclass
class PrepareResult:
    devices: List[PreparedDevice] = field(default_factory=list)
    error: str = ""


@dataclass
class Claim:
    uid: str
    name: str
    namespace: str


class DriverCallbacks:
    """Implemented by each driver (gpu/cd kubelet plugin device states).

    The claims list is the RPC's batch — kubelet sends a pod's claims in
    ONE NodePrepareResources call. Implementations must return one entry
    per claim uid with per-claim error isolation (one bad claim must not
    fail its batch siblings); they may treat the batch as a single unit
    of work (one lock acquisition, group-committed durable state)."""

    def prepare_claims(self, claims: List[Claim]) -> Dict[str, PrepareResult]:
        raise NotImplementedError

    def unprepare_claims(self, claims: List[Claim]) -> Dict[str, str]:
        """uid -> error string ('' = success)."""
        raise NotImplementedError

    def record_wire(self, stage_s: Dict[str, float]) -> None:
        """Server-side wire-time attribution hook: per-RPC seconds for
        the request-decode and response-encode stages plus the whole
        handler wall ({'decode','encode','handler'}). Drivers that
        attribute claim-to-ready override this (tpuplugin); the default
        drops it."""


class DraHandlers:
    """Transport-independent DRA method implementations.

    Every method here BLOCKS (pipeline admission, flock, fdatasync) —
    the async front-end must only ever call them through an executor.
    Two surfaces per method: ``*_msg`` for transports handing parsed
    protobuf messages (grpc.aio) and ``*_bytes`` for the framed path
    (wire parse/serialize included in the decode/encode stopwatches, so
    the attribution stays honest about what each transport pays)."""

    def __init__(self, callbacks: DriverCallbacks):
        self._callbacks = callbacks

    # -- NodePrepareResources ----------------------------------------------

    def node_prepare_msg(self, request) -> "dra.NodePrepareResourcesResponse":
        t_in = time.perf_counter()
        claims = [Claim(uid=c.uid, name=c.name, namespace=c.namespace)
                  for c in request.claims]
        t_decoded = time.perf_counter()
        results = dict(self._callbacks.prepare_claims(claims))
        t_done = time.perf_counter()
        resp = self._build_prepare_response(claims, results)
        t_out = time.perf_counter()
        self._callbacks.record_wire({"decode": t_decoded - t_in,
                                     "encode": t_out - t_done,
                                     "handler": t_out - t_in})
        return resp

    def node_prepare_bytes(self, body: bytes) -> bytes:
        t_in = time.perf_counter()
        request = dra.NodePrepareResourcesRequest.FromString(body)
        claims = [Claim(uid=c.uid, name=c.name, namespace=c.namespace)
                  for c in request.claims]
        t_decoded = time.perf_counter()
        results = dict(self._callbacks.prepare_claims(claims))
        t_done = time.perf_counter()
        payload = self._build_prepare_response(
            claims, results).SerializeToString()
        t_out = time.perf_counter()
        self._callbacks.record_wire({"decode": t_decoded - t_in,
                                     "encode": t_out - t_done,
                                     "handler": t_out - t_in})
        return payload

    @staticmethod
    def _build_prepare_response(claims: List[Claim],
                                results: Dict[str, PrepareResult]):
        for claim in claims:
            # A driver bug that dropped a claim from the result map must
            # surface as that claim's error, not a missing response entry
            # kubelet could misread as success-shaped.
            results.setdefault(
                claim.uid,
                PrepareResult(error="driver returned no result for claim"))
        resp = dra.NodePrepareResourcesResponse()
        for uid, res in results.items():
            # Built in place: the map entry materializes on first access,
            # avoiding a per-claim message copy on the hot path.
            out = resp.claims[uid]
            if res.error:
                out.error = res.error
            else:
                for d in res.devices:
                    dev = out.devices.add()
                    dev.pool_name = d.pool_name
                    dev.device_name = d.device_name
                    dev.cdi_device_ids.extend(d.cdi_device_ids)
                    dev.request_names.extend(d.request_names)
        return resp

    # -- NodeUnprepareResources --------------------------------------------

    def node_unprepare_msg(self, request
                           ) -> "dra.NodeUnprepareResourcesResponse":
        claims = [Claim(uid=c.uid, name=c.name, namespace=c.namespace)
                  for c in request.claims]
        return self._build_unprepare_response(
            claims, dict(self._callbacks.unprepare_claims(claims)))

    def node_unprepare_bytes(self, body: bytes) -> bytes:
        request = dra.NodeUnprepareResourcesRequest.FromString(body)
        return self.node_unprepare_msg(request).SerializeToString()

    @staticmethod
    def _build_unprepare_response(claims: List[Claim],
                                  errors: Dict[str, str]):
        for claim in claims:
            errors.setdefault(claim.uid,
                              "driver returned no result for claim")
        resp = dra.NodeUnprepareResourcesResponse()
        for uid, err in errors.items():
            if err:
                resp.claims[uid].error = err
            else:
                # Success: materialize an empty entry for the uid.
                resp.claims[uid].SetInParent()
        return resp

    # -- framed dispatch ----------------------------------------------------

    def dispatch_frame(self, method: int, body: bytes) -> bytes:
        if method == METHOD_PREPARE:
            return self.node_prepare_bytes(body)
        if method == METHOD_UNPREPARE:
            return self.node_unprepare_bytes(body)
        raise ValueError(f"unknown framed-RPC method id {method}")


def _dra_aio_services(handlers: DraHandlers) -> Dict[str, Dict[str, tuple]]:
    return {_DRA_SERVICE: {
        "NodePrepareResources": (
            handlers.node_prepare_msg,
            dra.NodePrepareResourcesRequest.FromString,
            dra.NodePrepareResourcesResponse.SerializeToString),
        "NodeUnprepareResources": (
            handlers.node_unprepare_msg,
            dra.NodeUnprepareResourcesRequest.FromString,
            dra.NodeUnprepareResourcesResponse.SerializeToString),
    }}


def _registration_services(driver_name: str, endpoint: str,
                           on_status: Optional[Callable[[bool, str], None]]
                           ) -> Dict[str, Dict[str, tuple]]:
    def get_info(request):
        return reg.PluginInfo(type="DRAPlugin", name=driver_name,
                              endpoint=endpoint, supported_versions=["v1"])

    def notify(request):
        if on_status:
            on_status(request.plugin_registered, request.error)
        return reg.RegistrationStatusResponse()

    return {_REG_SERVICE: {
        "GetInfo": (get_info, reg.InfoRequest.FromString,
                    reg.PluginInfo.SerializeToString),
        "NotifyRegistrationStatus": (
            notify, reg.RegistrationStatus.FromString,
            reg.RegistrationStatusResponse.SerializeToString),
    }}


def self_probe(server: "DRAPluginServer", timeout: float = 3.0) -> bool:
    """Liveness self-probe (gpu plugin health.go:118-144): dial the
    plugin's own sockets as kubelet would — GetInfo on the registration
    socket, NodePrepareResources with an empty request on the DRA socket
    — plus a ping on the framed fast socket, and report whether all
    answered."""
    try:
        channel, prepare, _ = kubelet_stubs(server.dra_socket)
        try:
            prepare(dra.NodePrepareResourcesRequest(), timeout=timeout)
        finally:
            channel.close()
        reg_sock = getattr(server, "registration_socket", None)
        if reg_sock:
            reg_channel = grpc.insecure_channel(f"unix://{reg_sock}")
            try:
                get_info = reg_channel.unary_unary(
                    f"/{_REG_SERVICE}/GetInfo",
                    request_serializer=reg.InfoRequest.SerializeToString,
                    response_deserializer=reg.PluginInfo.FromString)
                info = get_info(reg.InfoRequest(), timeout=timeout)
                if info.name != server.driver_name:
                    return False
            finally:
                reg_channel.close()
        if server.fast_socket and os.path.exists(server.fast_socket):
            client = FramedClient(server.fast_socket, timeout_s=timeout)
            try:
                if not client.ping():
                    return False
            finally:
                client.close()
        return True
    except (grpc.RpcError, OSError):
        return False


def kubelet_stubs(dra_socket: str):
    """Client-side gRPC stubs acting as kubelet: (channel, prepare,
    unprepare).

    Single source of truth for the DRA v1 method paths/serializers used
    by the e2e tests and the gRPC side of the bench harness; close the
    returned channel when done. The framed fast-path equivalent is
    ``framed_stubs``."""
    channel = grpc.insecure_channel(f"unix://{dra_socket}")
    prepare = channel.unary_unary(
        f"/{_DRA_SERVICE}/NodePrepareResources",
        request_serializer=dra.NodePrepareResourcesRequest.SerializeToString,
        response_deserializer=dra.NodePrepareResourcesResponse.FromString)
    unprepare = channel.unary_unary(
        f"/{_DRA_SERVICE}/NodeUnprepareResources",
        request_serializer=dra.NodeUnprepareResourcesRequest.SerializeToString,
        response_deserializer=dra.NodeUnprepareResourcesResponse.FromString)
    return channel, prepare, unprepare


class FramedRpcError(RuntimeError):
    """Server-side error surfaced over the framed transport."""


class FramedClient:
    """Blocking framed-RPC client over the plugin's fast socket.

    NOT thread-safe: one request/response in flight per connection by
    protocol design — use one client per thread (concurrency =
    connections, which is how the sustained bench drives depth)."""

    def __init__(self, fast_socket: str, timeout_s: float = 30.0):
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(timeout_s)
        self._sock.connect(fast_socket)

    def _call(self, method: int, payload: bytes) -> bytes:
        self._sock.sendall(FRAME_HEADER.pack(len(payload), method)
                           + payload)
        header = self._read_exact(FRAME_HEADER.size)
        length, resp_method = FRAME_HEADER.unpack(header)
        body = self._read_exact(length)
        if resp_method == METHOD_ERROR:
            raise FramedRpcError(body.decode("utf-8", "replace"))
        return body

    def _read_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self._sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("framed-RPC server closed the "
                                      "connection mid-response")
            buf += chunk
        return buf

    def prepare(self, request: "dra.NodePrepareResourcesRequest"
                ) -> "dra.NodePrepareResourcesResponse":
        body = self._call(METHOD_PREPARE, request.SerializeToString())
        return dra.NodePrepareResourcesResponse.FromString(body)

    def unprepare(self, request: "dra.NodeUnprepareResourcesRequest"
                  ) -> "dra.NodeUnprepareResourcesResponse":
        body = self._call(METHOD_UNPREPARE, request.SerializeToString())
        return dra.NodeUnprepareResourcesResponse.FromString(body)

    def ping(self) -> bool:
        return self._call(METHOD_PING, b"") == b""

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass  # drflow: swallow-ok[idempotent close on teardown]


def framed_stubs(fast_socket: str, timeout_s: float = 30.0):
    """Framed-transport analog of kubelet_stubs: (client, prepare,
    unprepare) with the same request/response message types — call
    ``client.close()`` when done."""
    client = FramedClient(fast_socket, timeout_s=timeout_s)
    return client, client.prepare, client.unprepare


RPC_RECONNECTS = DefaultRegistry.counter(
    "tpu_dra_rpc_reconnects_total",
    "framed-RPC client reconnect attempts while masking a plugin "
    "restart (SURVEY §22: each one is a socket gap the retry loop "
    "absorbed instead of failing the RPC)")


class RetryingFramedClient:
    """FramedClient wrapper that masks a plugin hot restart.

    During the restart window a caller sees three failure shapes: a
    ``PipelineDraining`` refusal surfaced as a framed METHOD_ERROR
    (old incarnation stopping admission), a socket error (socket
    unlinked / connection reset between incarnations), or a connect
    refusal (new incarnation not listening yet). All three are
    retried against a fresh connection with exponential backoff,
    bounded by a wall-clock deadline — the zero-failed-RPC half of
    the hot-upgrade contract. Safe because prepare/unprepare are
    idempotent on the server (checkpoint journal replays/dedupes a
    batch committed just before the cut).

    Like FramedClient: NOT thread-safe, one per worker thread."""

    def __init__(self, fast_socket: str, timeout_s: float = 30.0,
                 max_elapsed_s: float = 30.0, backoff_s: float = 0.05,
                 max_backoff_s: float = 1.0):
        self._fast_socket = fast_socket
        self._timeout_s = timeout_s
        self._max_elapsed_s = max_elapsed_s
        self._backoff_s = backoff_s
        self._max_backoff_s = max_backoff_s
        self._client: Optional[FramedClient] = None
        self.reconnects = 0

    def _ensure(self) -> FramedClient:
        if self._client is None:
            # Injection site: the reconnect dial itself fails (new
            # incarnation still binding). Declared degradation:
            # backoff — the retry loop sleeps and redials.
            FAULTS.check("prepare.reconnect", socket=self._fast_socket)
            self._client = FramedClient(self._fast_socket,
                                        timeout_s=self._timeout_s)
        return self._client

    @staticmethod
    def _retryable(e: Exception) -> bool:
        if isinstance(e, (OSError, ConnectionError, FaultInjected)):
            return True
        # METHOD_ERROR carries the server exception's text: only the
        # draining refusal is a restart-window artifact; any other
        # server error is a real failure the caller must see.
        return isinstance(e, FramedRpcError) and "draining" in str(e)

    def _reconnect_backoff(self, delay: float) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None
        self.reconnects += 1
        RPC_RECONNECTS.inc()
        time.sleep(delay)

    def _call(self, fn_name: str, *args):
        deadline = time.monotonic() + self._max_elapsed_s
        delay = self._backoff_s
        while True:
            try:
                return getattr(self._ensure(), fn_name)(*args)
            except (FramedRpcError, FaultInjected, OSError) as e:
                if not self._retryable(e) or time.monotonic() >= deadline:
                    raise
                self._reconnect_backoff(delay)
                delay = min(delay * 2.0, self._max_backoff_s)

    def prepare(self, request: "dra.NodePrepareResourcesRequest"
                ) -> "dra.NodePrepareResourcesResponse":
        return self._call("prepare", request)

    def unprepare(self, request: "dra.NodeUnprepareResourcesRequest"
                  ) -> "dra.NodeUnprepareResourcesResponse":
        return self._call("unprepare", request)

    def ping(self) -> bool:
        return self._call("ping")

    def close(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None


class DRAPluginServer:
    """Hosts the DRA + Registration services on unix sockets.

    plugin_dir:   /var/lib/kubelet/plugins/<driver>/   (dra.sock +
                  dra-fast.sock live here)
    registry_dir: /var/lib/kubelet/plugins_registry/   (watcher socket)

    One asyncio event loop thread (aio_server.EventLoopThread) reacts
    for every listener; one executor runs every blocking handler."""

    # Executor width bounds concurrent blocking DRA handlers, matching
    # the retired sync server's thread pool (and sitting BELOW the
    # pipeline's in-flight window of 16 — with the async front-end the
    # pool, not the window, is the binding concurrency limit; excess
    # RPCs queue in the executor instead of on handler threads).
    RPC_POOL_WORKERS = 8
    # Registration gets its own tiny pool, as the retired server gave
    # it a dedicated 2-thread gRPC server: kubelet's GetInfo/
    # NotifyRegistrationStatus must answer even while every RPC worker
    # is wedged in a stalled prepare (a data-path stall must not read
    # as a dead plugin and deregister the driver).
    REG_POOL_WORKERS = 2

    def __init__(self, driver_name: str, node_name: str,
                 callbacks: DriverCallbacks,
                 plugin_dir: str, registry_dir: Optional[str] = None):
        self.driver_name = driver_name
        self.node_name = node_name
        self._callbacks = callbacks
        self._plugin_dir = plugin_dir
        self._registry_dir = registry_dir
        os.makedirs(plugin_dir, exist_ok=True)
        if registry_dir:
            os.makedirs(registry_dir, exist_ok=True)
        self.dra_socket = os.path.join(plugin_dir, "dra.sock")
        self.fast_socket = os.path.join(plugin_dir, "dra-fast.sock")
        self.registration_registered = threading.Event()
        self._loop_thread: Optional[EventLoopThread] = None
        self._pool: Optional[ThreadPoolExecutor] = None
        self._reg_pool: Optional[ThreadPoolExecutor] = None
        self._server = None           # grpc.aio server (DRA socket)
        self._framed: Optional[FramedRpcServer] = None
        self._reg_server = None       # grpc.aio server (registration)
        self._stopped = False
        # Serializes start_registration() against stop(): they run on
        # different threads (publish retry queue vs driver shutdown).
        self._reg_lock = threading.Lock()

    # -- loop-side coroutines (no blocking work here: dralint R2) -----------

    async def _start_main(self) -> None:
        handlers = DraHandlers(self._callbacks)
        self._server = grpc.aio.server()
        for h in aio_service_handlers(_dra_aio_services(handlers),
                                      self._pool):
            self._server.add_generic_rpc_handlers([h])
        self._server.add_insecure_port(f"unix://{self.dra_socket}")
        await self._server.start()
        self._framed = FramedRpcServer(self.fast_socket,
                                       handlers.dispatch_frame, self._pool)
        await self._framed.start()
        asyncio.get_running_loop().create_task(aio_server.lag_monitor())

    async def _start_registration(self, reg_sock: str) -> None:
        self._reg_server = grpc.aio.server()
        services = _registration_services(
            self.driver_name, self.dra_socket,
            on_status=lambda ok, err: (
                self.registration_registered.set() if ok else None))
        for h in aio_service_handlers(services, self._reg_pool):
            self._reg_server.add_generic_rpc_handlers([h])
        self._reg_server.add_insecure_port(f"unix://{reg_sock}")
        await self._reg_server.start()

    async def _stop_servers(self, grace: float) -> None:
        if self._server is not None:
            await self._server.stop(grace)
        if self._framed is not None:
            await self._framed.stop()
        if self._reg_server is not None:
            await self._reg_server.stop(grace)

    # -- lifecycle (called from plain threads) ------------------------------

    def start(self, register: bool = True) -> None:
        for sock in (self.dra_socket, self.fast_socket):
            if os.path.exists(sock):
                os.unlink(sock)
        self._loop_thread = EventLoopThread()
        self._pool = ThreadPoolExecutor(
            max_workers=self.RPC_POOL_WORKERS,
            thread_name_prefix="tpu-dra-rpc")
        self._loop_thread.submit(self._start_main()).result(timeout=10.0)
        if register:
            self.start_registration()

    def start_registration(self) -> None:
        """Expose the plugin-watcher socket. Separate from start() so the
        driver can gate kubelet registration on the first successful
        ResourceSlice publish (the reference Helper's sequencing,
        driver.go:73-116): kubelet should not route claims here before the
        scheduler can see this node's inventory. Idempotent, and refuses
        after stop(): the gated first publish runs on the retry queue,
        whose worker can still be mid-callback when the driver shuts down
        — starting a registration server then would leak it (nothing will
        ever stop it) and advertise a dead plugin to kubelet."""
        with self._reg_lock:
            if self._stopped or self._reg_server is not None \
                    or not self._registry_dir:
                return
            reg_sock = os.path.join(
                self._registry_dir, f"{self.driver_name}-reg.sock")
            if os.path.exists(reg_sock):
                os.unlink(reg_sock)
            if self._reg_pool is None:
                self._reg_pool = ThreadPoolExecutor(
                    max_workers=self.REG_POOL_WORKERS,
                    thread_name_prefix="tpu-dra-reg")
            self._loop_thread.submit(
                self._start_registration(reg_sock)).result(timeout=10.0)
            self.registration_socket = reg_sock

    def stop(self, grace: float = 2.0) -> None:
        with self._reg_lock:
            self._stopped = True
        if self._loop_thread is not None:
            self._loop_thread.submit(self._stop_servers(grace)).result(
                timeout=grace + 10.0)
            # Drain the executors BEFORE stopping the loop: an
            # in-flight handler finishing after loop close would try to
            # deliver its future result onto a dead loop (noisy, and
            # the RPC's response frame would be lost mid-write).
            for pool in (self._pool, self._reg_pool):
                if pool is not None:
                    pool.shutdown(wait=True)
            self._loop_thread.stop()
        else:
            for pool in (self._pool, self._reg_pool):
                if pool is not None:
                    pool.shutdown(wait=True)


# ---------------------------------------------------------------------------
# ResourceSlice publishing
# ---------------------------------------------------------------------------

def build_resource_slice(driver_name: str, node_name: str,
                         devices: List[Dict], pool_generation: int = 1) -> Dict:
    """Render a resource.k8s.io/v1 ResourceSlice for this node's devices
    (publishResources, driver.go:217-235). `devices` entries are
    {name, attributes, capacity} dicts produced by the device model."""
    return {
        "apiVersion": "resource.k8s.io/v1",
        "kind": "ResourceSlice",
        "metadata": {
            "name": f"{node_name}-{driver_name}",
            "ownerReferences": [],
        },
        "spec": {
            "driver": driver_name,
            "nodeName": node_name,
            "pool": {
                "name": node_name,
                "generation": pool_generation,
                "resourceSliceCount": 1,
            },
            "devices": devices,
        },
    }


def publish_resources(client: ApiClient, driver_name: str, node_name: str,
                      devices: List[Dict], pool_generation: int = 1) -> Dict:
    """Create-or-update this node's ResourceSlice."""
    slice_obj = build_resource_slice(driver_name, node_name, devices,
                                     pool_generation)
    from tpu_dra.k8s.client import NotFoundError
    try:
        current = client.get(RESOURCESLICES, slice_obj["metadata"]["name"])
        slice_obj["metadata"]["resourceVersion"] = \
            current["metadata"].get("resourceVersion")
        return client.update(RESOURCESLICES, slice_obj)
    except NotFoundError:
        return client.create(RESOURCESLICES, slice_obj)
