"""Pipelined RPC admission: bounded in-flight window + keyed ordering.

The DRA gRPC server hands every RPC its own handler thread; what makes
them a *pipeline* is how little of each RPC is exclusive. This module
owns the two pieces the server/driver pair needs for that (SURVEY §14):

- **Bounded in-flight window** — at most `window` RPCs past admission
  at once (kubelet retry storms and chaos harnesses must not pile
  unbounded threads onto the claim-fetch fan-out), with the current
  depth exported as ``tpu_dra_prepare_inflight_rpcs``.

- **Per-claim-set keyed serialization** — two RPCs touching the same
  claim uid never reorder: each admitted RPC registers a completion
  gate per uid and waits for the gates of every predecessor holding one
  of its uids. RPCs on disjoint claim sets proceed concurrently — the
  whole point: while RPC N sits in its commit fdatasync, RPC N+1 is
  decoding and claim-fetching. The waits-for graph follows registration
  order, so it is acyclic by construction (no deadlock).

Ordering + the window compose safely: gates are registered at
admission, and an admitted RPC only ever waits on gates registered
BEFORE its own, whose owners are admitted and will complete.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List

from tpu_dra.infra.faults import FAULTS
from tpu_dra.infra.metrics import DefaultRegistry
from tpu_dra.infra.trace import dump_flight_recorder

INFLIGHT_RPCS = DefaultRegistry.gauge(
    "tpu_dra_prepare_inflight_rpcs",
    "prepare/unprepare RPCs currently admitted into the pipelined "
    "server (bounded by the in-flight window)")


class _Ticket:
    """One admitted RPC: its completion gate plus the predecessor gates
    it must wait out before touching driver state."""

    def __init__(self, uids: List[str], gate: threading.Event,
                 predecessors: List[threading.Event]):
        self.uids = uids
        self.gate = gate
        self.predecessors = predecessors
        self.queue_s = 0.0  # admission wait + predecessor wait


class PipelineTimeout(TimeoutError):
    pass


class RpcPipeline:
    # Fail-fast bound on queueing (admission + ordering): a wedged
    # predecessor RPC must surface as THIS RPC's error for kubelet to
    # retry, not wedge the whole plugin silently — the bound the
    # pre-pipeline per-RPC flock timeout used to provide.
    DEFAULT_TIMEOUT_S = 30.0

    def __init__(self, window: int = 16,
                 timeout_s: float = DEFAULT_TIMEOUT_S):
        self._window = threading.Semaphore(window)
        self._timeout_s = timeout_s
        self._gates_lock = threading.Lock()
        # uid -> the gate of the LAST admitted RPC touching it.
        self._last_gate: Dict[str, threading.Event] = {}
        self._inflight = 0

    def admit(self, uids: Iterable[str]) -> _Ticket:
        """Block for a window slot (bounded), then register this RPC's
        gates. Registration order IS the serialization order for
        overlapping claim sets. Raises PipelineTimeout when the window
        never frees — the caller fails the RPC."""
        unique = list(dict.fromkeys(uids))
        # Injection site for the async front-end's admission path
        # (SURVEY §21): an admission refusal must fail THIS RPC with a
        # per-claim error (kubelet retries) before any window slot or
        # gate registration exists to leak — the chaos prepare walk
        # arms it against exactly that invariant.
        FAULTS.check("prepare.rpc_admit", uids=unique)
        t0 = time.perf_counter()
        if not self._window.acquire(timeout=self._timeout_s):
            # A window that never frees means in-flight RPCs are wedged
            # somewhere past admission — exactly the moment the flight
            # recorder's evidence (open spans name the stuck stage and
            # thread) matters. Dump before failing the RPC (SURVEY
            # §19.3); the dump never raises, and it is rate-limited —
            # a sustained wedge fails every retrying RPC, and each one
            # writing a fresh multi-MB ring would fill the wedged
            # node's tmp with identical evidence.
            dump_path = dump_flight_recorder("pipeline-wedged",
                                             min_interval_s=60.0)
            raise PipelineTimeout(
                f"prepare pipeline window full for {self._timeout_s}s "
                "(in-flight RPCs wedged?); flight recorder dumped to "
                f"{dump_path}")
        gate = threading.Event()
        with self._gates_lock:
            predecessors = [self._last_gate[u] for u in unique
                            if u in self._last_gate]
            for u in unique:
                self._last_gate[u] = gate
            self._inflight += 1
            INFLIGHT_RPCS.set(self._inflight)
        ticket = _Ticket(unique, gate, predecessors)
        ticket.queue_s = time.perf_counter() - t0
        return ticket

    def order(self, ticket: _Ticket) -> None:
        """Wait (bounded) for every predecessor RPC sharing a claim
        uid. Call after any prefetch work that may overlap (the claim
        fan-out reads the API server, not driver state) and before
        touching DeviceState. Raises PipelineTimeout on a wedged
        predecessor; the caller must still done() its ticket."""
        t0 = time.perf_counter()
        deadline = t0 + self._timeout_s
        for gate in ticket.predecessors:
            if not gate.wait(timeout=max(0.0, deadline
                                         - time.perf_counter())):
                ticket.queue_s += time.perf_counter() - t0
                raise PipelineTimeout(
                    f"predecessor RPC on a shared claim still running "
                    f"after {self._timeout_s}s")
        ticket.queue_s += time.perf_counter() - t0

    def done(self, ticket: _Ticket) -> None:
        """Release the RPC: open its gate for successors, drop its
        uid registrations (only where it is still the latest), free the
        window slot. Always runs (finally) — an RPC that errors must
        not wedge its successors."""
        ticket.gate.set()
        with self._gates_lock:
            for u in ticket.uids:
                if self._last_gate.get(u) is ticket.gate:
                    del self._last_gate[u]
            self._inflight -= 1
            INFLIGHT_RPCS.set(self._inflight)
        self._window.release()
