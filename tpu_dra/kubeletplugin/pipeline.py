"""Pipelined RPC admission: bounded in-flight window + keyed ordering.

The DRA gRPC server hands every RPC its own handler thread; what makes
them a *pipeline* is how little of each RPC is exclusive. This module
owns the two pieces the server/driver pair needs for that (SURVEY §14):

- **Bounded in-flight window** — at most `window` RPCs past admission
  at once (kubelet retry storms and chaos harnesses must not pile
  unbounded threads onto the claim-fetch fan-out), with the current
  depth exported as ``tpu_dra_prepare_inflight_rpcs``.

- **Per-claim-set keyed serialization** — two RPCs touching the same
  claim uid never reorder: each admitted RPC registers a completion
  gate per uid and waits for the gates of every predecessor holding one
  of its uids. RPCs on disjoint claim sets proceed concurrently — the
  whole point: while RPC N sits in its commit fdatasync, RPC N+1 is
  decoding and claim-fetching. The waits-for graph follows registration
  order, so it is acyclic by construction (no deadlock).

Ordering + the window compose safely: gates are registered at
admission, and an admitted RPC only ever waits on gates registered
BEFORE its own, whose owners are admitted and will complete.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterable, List

from tpu_dra.infra.faults import FAULTS, FaultInjected
from tpu_dra.infra.metrics import DefaultRegistry
from tpu_dra.infra.trace import dump_flight_recorder

INFLIGHT_RPCS = DefaultRegistry.gauge(
    "tpu_dra_prepare_inflight_rpcs",
    "prepare/unprepare RPCs currently admitted into the pipelined "
    "server (bounded by the in-flight window)")

RPC_DRAIN_SECONDS = DefaultRegistry.histogram(
    "tpu_dra_rpc_drain_seconds",
    "time the hot-restart drain window spent waiting for in-flight "
    "RPCs to finish (SURVEY §22: the shutdown half of the "
    "zero-failed-RPC restart contract)",
    buckets=(0.001, 0.005, 0.02, 0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0))


class _Ticket:
    """One admitted RPC: its completion gate plus the predecessor gates
    it must wait out before touching driver state."""

    def __init__(self, uids: List[str], gate: threading.Event,
                 predecessors: List[threading.Event]):
        self.uids = uids
        self.gate = gate
        self.predecessors = predecessors
        self.queue_s = 0.0  # admission wait + predecessor wait


class PipelineTimeout(TimeoutError):
    pass


class PipelineDraining(RuntimeError):
    """Raised at admission while the plugin is draining for a hot
    restart. Deliberately NOT a TimeoutError/FaultInjected: the driver
    maps those to per-claim errors, but a draining plugin must fail the
    RPC at the transport (METHOD_ERROR / gRPC error) so the client's
    retry-on-reconnect masks the restart — the zero-failed-RPC
    contract (SURVEY §22)."""


class RpcPipeline:
    # Fail-fast bound on queueing (admission + ordering): a wedged
    # predecessor RPC must surface as THIS RPC's error for kubelet to
    # retry, not wedge the whole plugin silently — the bound the
    # pre-pipeline per-RPC flock timeout used to provide.
    DEFAULT_TIMEOUT_S = 30.0

    def __init__(self, window: int = 16,
                 timeout_s: float = DEFAULT_TIMEOUT_S):
        self._window = threading.Semaphore(window)
        self._timeout_s = timeout_s
        self._gates_lock = threading.Lock()
        # uid -> the gate of the LAST admitted RPC touching it.
        self._last_gate: Dict[str, threading.Event] = {}
        self._inflight = 0
        # Hot-restart drain: once set, admit() refuses new RPCs while
        # drain() waits (on _cv, notified by done()) for the admitted
        # ones to finish.
        self._draining = threading.Event()
        self._cv = threading.Condition(self._gates_lock)

    def admit(self, uids: Iterable[str]) -> _Ticket:
        """Block for a window slot (bounded), then register this RPC's
        gates. Registration order IS the serialization order for
        overlapping claim sets. Raises PipelineTimeout when the window
        never frees — the caller fails the RPC."""
        unique = list(dict.fromkeys(uids))
        if self._draining.is_set():
            # Refused BEFORE any slot/gate exists to leak. Propagates
            # past the driver's per-claim error mapping to the
            # transport, where the retrying client waits out the
            # restart.
            raise PipelineDraining(
                "plugin draining for hot restart; retry after reconnect")
        # Injection site for the async front-end's admission path
        # (SURVEY §21): an admission refusal must fail THIS RPC with a
        # per-claim error (kubelet retries) before any window slot or
        # gate registration exists to leak — the chaos prepare walk
        # arms it against exactly that invariant.
        FAULTS.check("prepare.rpc_admit", uids=unique)
        t0 = time.perf_counter()
        if not self._window.acquire(timeout=self._timeout_s):
            # A window that never frees means in-flight RPCs are wedged
            # somewhere past admission — exactly the moment the flight
            # recorder's evidence (open spans name the stuck stage and
            # thread) matters. Dump before failing the RPC (SURVEY
            # §19.3); the dump never raises, and it is rate-limited —
            # a sustained wedge fails every retrying RPC, and each one
            # writing a fresh multi-MB ring would fill the wedged
            # node's tmp with identical evidence.
            dump_path = dump_flight_recorder("pipeline-wedged",
                                             min_interval_s=60.0)
            raise PipelineTimeout(
                f"prepare pipeline window full for {self._timeout_s}s "
                "(in-flight RPCs wedged?); flight recorder dumped to "
                f"{dump_path}")
        gate = threading.Event()
        with self._gates_lock:
            predecessors = [self._last_gate[u] for u in unique
                            if u in self._last_gate]
            for u in unique:
                self._last_gate[u] = gate
            self._inflight += 1
            INFLIGHT_RPCS.set(self._inflight)
        ticket = _Ticket(unique, gate, predecessors)
        ticket.queue_s = time.perf_counter() - t0
        return ticket

    def order(self, ticket: _Ticket) -> None:
        """Wait (bounded) for every predecessor RPC sharing a claim
        uid. Call after any prefetch work that may overlap (the claim
        fan-out reads the API server, not driver state) and before
        touching DeviceState. Raises PipelineTimeout on a wedged
        predecessor; the caller must still done() its ticket."""
        t0 = time.perf_counter()
        deadline = t0 + self._timeout_s
        for gate in ticket.predecessors:
            if not gate.wait(timeout=max(0.0, deadline
                                         - time.perf_counter())):
                ticket.queue_s += time.perf_counter() - t0
                raise PipelineTimeout(
                    f"predecessor RPC on a shared claim still running "
                    f"after {self._timeout_s}s")
        ticket.queue_s += time.perf_counter() - t0

    def done(self, ticket: _Ticket) -> None:
        """Release the RPC: open its gate for successors, drop its
        uid registrations (only where it is still the latest), free the
        window slot. Always runs (finally) — an RPC that errors must
        not wedge its successors."""
        ticket.gate.set()
        with self._gates_lock:
            for u in ticket.uids:
                if self._last_gate.get(u) is ticket.gate:
                    del self._last_gate[u]
            self._inflight -= 1
            INFLIGHT_RPCS.set(self._inflight)
            if self._inflight == 0:
                self._cv.notify_all()  # a drain may be waiting
        self._window.release()

    def drain(self, timeout_s: float = DEFAULT_TIMEOUT_S) -> float:
        """Stop admitting and wait (bounded) for every in-flight RPC to
        finish — the shutdown half of the hot-restart contract: work
        past admission completes and commits (the journal barrier runs
        after this), work not yet admitted is refused for the client to
        retry against the next incarnation. Returns the seconds spent
        waiting; observed into ``tpu_dra_rpc_drain_seconds``. A drain
        that times out with RPCs still in flight dumps the flight
        recorder (the evidence names the stuck stage) and returns — the
        journal + idempotent prepare make the cut-off recoverable."""
        self._draining.set()
        t0 = time.perf_counter()
        try:
            # Injection site: the drain window itself wedges (an
            # in-flight RPC never completes). Declared degradation:
            # dump_flight_recorder — evidence out before the process
            # goes down.
            FAULTS.check("prepare.drain")
            deadline = t0 + timeout_s
            with self._cv:
                while self._inflight > 0:
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0.0:
                        break
                    self._cv.wait(timeout=remaining)
                stuck = self._inflight
            if stuck:
                dump_flight_recorder("drain-timeout", min_interval_s=60.0)
        except FaultInjected:
            dump_flight_recorder("drain-faulted", min_interval_s=60.0)
        elapsed = time.perf_counter() - t0
        RPC_DRAIN_SECONDS.observe(elapsed)
        return elapsed

    @property
    def draining(self) -> bool:
        return self._draining.is_set()
