"""Asyncio RPC front-end for the DRA plugin (SURVEY §21).

One event loop on a dedicated thread hosts BOTH prepare transports:

- **grpc.aio** on the kubelet DRA socket — wire-compatible with
  kubelet's gRPC client (the protocol is non-negotiable), served by
  async behaviors that offload the blocking handler to an executor.
  grpc.aio's *registered-method* fast path
  (``add_registered_method_handlers``) was measured first and rejected:
  with hand-rolled stubs (no grpc_tools gencode, see server.py) every
  key spelling returns UNIMPLEMENTED in grpc 1.68 — the server-side
  registered table only resolves calls a gencode client pre-registered
  on its channel. The generic-handler aio path works but measured
  *slower* than the sync server it replaces (~287µs vs ~186µs echo
  round-trip), so it carries compatibility, not the latency gate.

- **framed-RPC** on a second unix socket (``dra-fast.sock``) — the
  hand-rolled sidecar path ROADMAP item 5 sanctions: 5-byte header
  (u32 LE body length + u8 method id) framing the SAME dra.v1 protobuf
  payloads, one request/response in flight per connection (concurrency
  = connections), ~39µs echo round-trip (~66µs with the executor hop).
  This is the transport the sub-0.5ms single-claim gate rides.

Event-loop/thread boundary discipline (the satellite contract, enforced
by dralint R2's coroutine check): coroutines here only frame, parse
headers, and await — every blocking stage (pipeline admission with its
window semaphore, SharedFlock, DeviceState group commit with its
fdatasync) runs inside ``run_in_executor`` on the RPC pool. The framed
dispatcher runs decode→handler→encode as ONE executor task so the
driver's per-thread wire-attribution pairing (record_wire reads a
thread-local queue share) holds exactly as it did under the
thread-per-RPC sync server.
"""

from __future__ import annotations

import asyncio
import struct
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Callable, Dict, Optional

from tpu_dra.infra.metrics import DefaultRegistry

# Event-loop scheduling lag: how late a timed callback fires vs its
# deadline. The front-end's "is the loop healthy" observable — a
# blocking call smuggled onto the loop shows up here long before RPC
# p99 does (buckets sized for µs-scale lag up to a seized loop).
RPC_LOOP_LAG = DefaultRegistry.histogram(
    "tpu_dra_rpc_loop_lag_seconds",
    "asyncio event-loop scheduling lag of the RPC front-end: observed "
    "minus intended delay of a periodic timer on the loop; sustained "
    "growth means blocking work leaked onto the loop thread",
    buckets=(0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
             0.05, 0.25, 1.0))

# RPCs currently offloaded past framing (decode→handler→encode running
# on the executor). Distinct from tpu_dra_prepare_inflight_rpcs: that
# gauge counts RPCs past PIPELINE admission; this one counts everything
# the front-end accepted, including RPCs still queued on the admission
# window — the difference is the admission backlog under sustained load.
SUSTAINED_INFLIGHT = DefaultRegistry.gauge(
    "tpu_dra_rpc_sustained_inflight",
    "RPCs currently dispatched by the async front-end (framed + gRPC), "
    "admitted or queued on the pipeline window; bounded by client "
    "concurrency, watched by the sustained-load bench")

# Framed-RPC wire format: u32 LE body length + u8 method id, then the
# dra.v1 protobuf payload. Responses reuse the header with method id
# echoing the request's (or METHOD_ERROR carrying a utf-8 message).
FRAME_HEADER = struct.Struct("<IB")
METHOD_PREPARE = 1
METHOD_UNPREPARE = 2
METHOD_PING = 3
METHOD_ERROR = 0xFF
MAX_FRAME_BYTES = 16 << 20  # a NodePrepareResources batch is ~KBs; 16MiB
# rejects a corrupt/hostile length before readexactly tries to buffer it

_LAG_INTERVAL_S = 0.05


class EventLoopThread:
    """One asyncio loop on a daemon thread, submit-from-anywhere.

    The loop is the front-end's reactor; everything blocking belongs on
    the executor the caller passes to the servers (never here)."""

    def __init__(self, name: str = "tpu-dra-rpc-loop"):
        self.loop = asyncio.new_event_loop()
        self._thread = threading.Thread(target=self._run, name=name,
                                        daemon=True)
        self._thread.start()

    def _run(self) -> None:
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()
        # Drain callbacks scheduled during shutdown, then close.
        self.loop.run_until_complete(self.loop.shutdown_asyncgens())
        self.loop.close()

    def submit(self, coro) -> Future:
        """Schedule a coroutine on the loop; returns a concurrent
        Future (callers block on .result() from plain threads)."""
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    def stop(self, timeout: float = 5.0) -> None:
        def _cancel_all() -> None:
            for task in asyncio.all_tasks(self.loop):
                task.cancel()
            self.loop.call_soon(self.loop.stop)

        self.loop.call_soon_threadsafe(_cancel_all)
        self._thread.join(timeout)


async def lag_monitor(interval_s: float = _LAG_INTERVAL_S) -> None:
    """Periodic timer observing its own scheduling lag into
    RPC_LOOP_LAG. Cancelled by EventLoopThread.stop()."""
    loop = asyncio.get_running_loop()
    while True:
        deadline = loop.time() + interval_s
        await asyncio.sleep(interval_s)
        RPC_LOOP_LAG.observe(max(loop.time() - deadline, 0.0))


class FramedRpcServer:
    """The framed-RPC unix-socket listener.

    ``dispatch(method_id, body) -> bytes`` is the blocking handler
    (decode + driver callback + encode), run on `pool` — one executor
    task per request, never on the loop. Per-connection requests are
    processed in order (the client blocks on its response), so
    concurrency equals client connections — which is exactly how the
    sustained-load bench keeps the admission window and the journal
    barrier queue full."""

    def __init__(self, path: str, dispatch: Callable[[int, bytes], bytes],
                 pool: ThreadPoolExecutor):
        self.path = path
        self._dispatch = dispatch
        self._pool = pool
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> None:
        self._server = await asyncio.start_unix_server(
            self._serve_conn, path=self.path)

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()

    async def _serve_conn(self, reader: asyncio.StreamReader,
                          writer: asyncio.StreamWriter) -> None:
        loop = asyncio.get_running_loop()
        try:
            while True:
                header = await reader.readexactly(FRAME_HEADER.size)
                length, method = FRAME_HEADER.unpack(header)
                if length > MAX_FRAME_BYTES:
                    payload = f"frame of {length} bytes exceeds " \
                              f"{MAX_FRAME_BYTES}".encode()
                    writer.write(FRAME_HEADER.pack(len(payload),
                                                   METHOD_ERROR) + payload)
                    await writer.drain()
                    break
                body = await reader.readexactly(length)
                if method == METHOD_PING:
                    writer.write(FRAME_HEADER.pack(0, METHOD_PING))
                    await writer.drain()
                    continue
                _inflight_adjust(+1)
                try:
                    try:
                        payload = await loop.run_in_executor(
                            self._pool, self._dispatch, method, body)
                        out_method = method
                    except Exception as e:  # noqa: BLE001 — one bad
                        # request must fail ITS response, not the conn
                        payload = str(e).encode()
                        out_method = METHOD_ERROR
                finally:
                    _inflight_adjust(-1)
                writer.write(FRAME_HEADER.pack(len(payload), out_method)
                             + payload)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError,
                BrokenPipeError):
            pass  # drflow: swallow-ok[client closed the connection —
            # the disconnect IS the protocol's end-of-stream]
        finally:
            writer.close()


_inflight_lock = threading.Lock()
_inflight_count = 0


def _inflight_adjust(delta: int) -> None:
    """Process-wide in-flight counter feeding SUSTAINED_INFLIGHT (the
    gauge spans every front-end instance in the process; the bench and
    tests read one number). The gauge set happens INSIDE the counter
    lock: set-after-release would let two finishing RPCs publish out
    of order and park a stale nonzero value on an idle front-end."""
    global _inflight_count
    with _inflight_lock:
        _inflight_count += delta
        SUSTAINED_INFLIGHT.set(_inflight_count)


def aio_service_handlers(services: Dict[str, Dict[str, tuple]],
                         pool: ThreadPoolExecutor):
    """Build grpc.aio generic handlers from {service: {method:
    (sync_behavior, req_deserializer, resp_serializer)}}.

    Each async behavior awaits the SYNC behavior on the executor — the
    whole blocking handler (pipeline admission, flock, group commit)
    stays off the loop, and runs on one executor thread end-to-end so
    the driver's thread-local wire attribution pairs correctly."""
    import grpc

    out = []
    for service_name, methods in services.items():
        handlers = {}
        for method_name, (behavior, req_des, resp_ser) in methods.items():
            async def call(request, context, _behavior=behavior):
                loop = asyncio.get_running_loop()
                _inflight_adjust(+1)
                try:
                    return await loop.run_in_executor(pool, _behavior,
                                                      request)
                finally:
                    _inflight_adjust(-1)

            handlers[method_name] = grpc.unary_unary_rpc_method_handler(
                call, request_deserializer=req_des,
                response_serializer=resp_ser)
        out.append(grpc.method_handlers_generic_handler(service_name,
                                                        handlers))
    return out
