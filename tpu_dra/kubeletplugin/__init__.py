"""L3 DRA kubelet-plugin framework (the k8s.io/dynamic-resource-allocation
kubeletplugin.Helper analog the reference builds its drivers on,
driver.go:73-82)."""

from tpu_dra.kubeletplugin.server import (  # noqa: F401
    DRAPluginServer, DriverCallbacks, PreparedDevice, PrepareResult,
    build_resource_slice,
)
