"""Validating admission webhook (reference: cmd/webhook).

Rejects ResourceClaims/ResourceClaimTemplates carrying malformed opaque
device configs owned by this driver *at admission time*, instead of at
node-side prepare where the pod is already scheduled.
"""

from tpu_dra.webhook.server import AdmissionHandler, WebhookServer  # noqa: F401
