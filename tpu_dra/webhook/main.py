"""Webhook entrypoint.

Reference: cmd/webhook/main.go:40-124.
Run: ``python -m tpu_dra.webhook.main [flags]``
"""

from __future__ import annotations

import signal
import threading

from tpu_dra.infra import debug
from tpu_dra.infra.flags import (
    Flag, FlagSet, apply_feature_gates, feature_gate_flag, logging_flags,
    setup_logging,
)
from tpu_dra.webhook.server import WebhookServer


def flags() -> FlagSet:
    return FlagSet("tpu-dra-webhook", [
        Flag("port", "WEBHOOK_PORT", default=8443, type=int,
             help="HTTPS listen port"),
        Flag("tls-cert-file", "TLS_CERT_FILE", default="",
             help="PEM certificate (empty = plain HTTP, dev only)"),
        Flag("tls-key-file", "TLS_KEY_FILE", default="",
             help="PEM private key"),
        feature_gate_flag(),
        *logging_flags(),
    ])


def main(argv=None) -> int:
    fs = flags()
    ns = fs.parse(argv)
    logger = setup_logging(ns.v, ns.log_json)
    apply_feature_gates(ns)
    fs.dump_config(ns, logger)
    debug.start_debug_signal_handlers()

    server = WebhookServer(port=ns.port,
                           cert_file=ns.tls_cert_file or None,
                           key_file=ns.tls_key_file or None)
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())
    server.start()
    logger.info("webhook serving on :%d", server.port)
    stop.wait()
    server.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
