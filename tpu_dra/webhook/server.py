"""Admission review handling + HTTPS server.

Reference: cmd/webhook/main.go:113-124 (routes
``/validate-resource-claim-parameters`` + ``/readyz``), resource.go:83-160
(extracts ResourceClaim/Template at resource.k8s.io v1/v1beta1/v1beta2 and
converts to v1), main.go:201-306 (strict-decode + Normalize + Validate
every opaque config owned by this driver; unknown drivers pass through).

The handler is transport-independent (AdmissionHandler.review(dict) ->
dict) so it unit-tests without TLS; WebhookServer wraps it in an
http.server with optional TLS for in-cluster deployment.
"""

from __future__ import annotations

import json
import logging
import ssl
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Tuple

from tpu_dra.api import scheme as apischeme
from tpu_dra.api import types as apitypes

log = logging.getLogger("tpu_dra.webhook")

VALIDATE_PATH = "/validate-resource-claim-parameters"
READYZ_PATH = "/readyz"

# API versions of resource.k8s.io we accept (resource.go:83-160).
SUPPORTED_VERSIONS = ("v1", "v1beta1", "v1beta2")
OWNED_DRIVERS = (apitypes.TPU_DRIVER_NAME,
                 apitypes.COMPUTE_DOMAIN_DRIVER_NAME)

# v1beta1 DeviceRequest fields that moved under the `exactly` wrapper when
# v1beta2 introduced prioritized-list requests (the one structural break in
# the resource.k8s.io version history; v1beta2 and v1 share the v1 shape).
_V1BETA1_REQUEST_FIELDS = ("deviceClassName", "selectors", "allocationMode",
                           "count", "adminAccess", "tolerations",
                           "capacity")


class ConversionError(ValueError):
    pass


def convert_device_spec_to_v1(devices: Dict, version: str) -> Dict:
    """Field-by-field conversion of a DeviceClaim ('spec.devices') to the
    v1 shape (the scheme.Convert analog, resource.go:83-160). v1beta2 is
    already the v1 shape; v1beta1 requests are flat and must be lifted
    into the `exactly` wrapper."""
    if version not in SUPPORTED_VERSIONS:
        raise ConversionError(f"unsupported resource version {version!r}")
    out = json.loads(json.dumps(devices))  # deep copy; input untouched
    if version in ("v1", "v1beta2"):
        return out
    requests = out.get("requests") or []
    converted = []
    for i, req in enumerate(requests):
        if not isinstance(req, dict):
            raise ConversionError(f"requests[{i}] must be an object")
        if "exactly" in req:
            # v1beta2/v1 syntax inside a v1beta1 object: the API server
            # would have rejected it; refuse rather than guess.
            raise ConversionError(
                f"requests[{i}]: 'exactly' is not a v1beta1 field")
        if "firstAvailable" in req:
            # DRAPrioritizedList added firstAvailable to v1beta1 too
            # (k8s 1.33), and subrequests are flat in every version —
            # already the v1 shape, pass through.
            converted.append(req)
            continue
        exactly = {k: req[k] for k in _V1BETA1_REQUEST_FIELDS if k in req}
        rest = {k: v for k, v in req.items()
                if k not in _V1BETA1_REQUEST_FIELDS}
        converted.append({**rest, "exactly": exactly})
    if requests:
        out["requests"] = converted
    return out


class AdmissionHandler:
    """Pure request->response admission logic."""

    def review(self, admission_review: Dict) -> Dict:
        request = admission_review.get("request") or {}
        uid = request.get("uid", "")
        allowed, message = self._validate_request(request)
        response: Dict = {"uid": uid, "allowed": allowed}
        if not allowed:
            response["status"] = {"message": message, "code": 422}
        return {
            "apiVersion": admission_review.get(
                "apiVersion", "admission.k8s.io/v1"),
            "kind": "AdmissionReview",
            "response": response,
        }

    # -- internals ----------------------------------------------------------

    def _validate_request(self, request: Dict) -> Tuple[bool, str]:
        obj = request.get("object")
        if obj is None:
            return False, "no object in admission request"
        group, version, kind = self._gvk(request, obj)
        if group != "resource.k8s.io":
            return True, ""
        if version not in SUPPORTED_VERSIONS:
            # Unknown future version: admit — the strict node-side decode
            # still guards prepare (fail-open on version skew, resource.go).
            return True, ""
        try:
            device_specs = [convert_device_spec_to_v1(d, version)
                            for d in self._device_specs(kind, obj)]
        except ValueError as e:
            return False, str(e)
        errors: List[str] = []
        for spec in device_specs:
            errors.extend(self._validate_device_spec(spec))
        if errors:
            return False, "; ".join(errors)
        return True, ""

    def _gvk(self, request: Dict, obj: Dict) -> Tuple[str, str, str]:
        res = request.get("resource") or {}
        group = res.get("group")
        version = res.get("version")
        kind = (request.get("kind") or {}).get("kind") or obj.get("kind", "")
        if group is None or version is None:
            api_version = obj.get("apiVersion", "")
            group, _, version = api_version.partition("/")
        return group, version, kind

    def _device_specs(self, kind: str, obj: Dict) -> List[Dict]:
        """Extract the DeviceClaim ('spec.devices') objects from a claim or
        template; version conversion to v1 happens in
        convert_device_spec_to_v1 (resource.go:83-160)."""
        if kind == "ResourceClaim":
            spec = obj.get("spec") or {}
        elif kind == "ResourceClaimTemplate":
            spec = ((obj.get("spec") or {}).get("spec") or {})
        else:
            return []
        devices = spec.get("devices") or {}
        if not isinstance(devices, dict):
            raise ValueError("spec.devices must be an object")
        return [devices]

    def _validate_device_spec(self, devices: Dict) -> List[str]:
        errors = []
        # Request names in v1 shape: plain names plus `req/sub` for
        # prioritized-list subrequests. Only meaningful AFTER conversion —
        # v1beta1's flat requests carry the same names, so the lift keeps
        # this check version-uniform.
        names = set()
        for req in devices.get("requests") or []:
            n = (req or {}).get("name")
            if not n:
                continue
            names.add(n)
            for sub in (req.get("firstAvailable") or []):
                if (sub or {}).get("name"):
                    names.add(f"{n}/{sub['name']}")
        for i, entry in enumerate(devices.get("config") or []):
            opaque = (entry or {}).get("opaque") or {}
            driver = opaque.get("driver", "")
            if driver not in OWNED_DRIVERS:
                continue  # not ours: admit
            for r in (entry or {}).get("requests") or []:
                if r not in names:
                    errors.append(
                        f"config[{i}]: targets unknown request {r!r}")
            params = opaque.get("parameters")
            if params is None:
                errors.append(f"config[{i}]: missing opaque parameters")
                continue
            try:
                cfg = apischeme.StrictDecoder.decode(params)
                cfg.normalize()
                cfg.validate()
            except (apischeme.DecodeError, apitypes.ValidationError) as e:
                errors.append(f"config[{i}]: {e}")
        return errors


class WebhookServer:
    """HTTPS (or plain HTTP for tests) server hosting the handler."""

    def __init__(self, handler: Optional[AdmissionHandler] = None,
                 addr: str = "0.0.0.0", port: int = 8443,  # noqa: S104
                 cert_file: Optional[str] = None,
                 key_file: Optional[str] = None):
        self._handler = handler or AdmissionHandler()
        outer = self

        class _Req(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                log.debug(fmt, *args)

            def do_GET(self):
                if self.path == READYZ_PATH:
                    self._respond(200, b"ok", "text/plain")
                else:
                    self._respond(404, b"not found", "text/plain")

            def do_POST(self):
                if self.path != VALIDATE_PATH:
                    self._respond(404, b"not found", "text/plain")
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    review = json.loads(self.rfile.read(length))
                    out = outer._handler.review(review)
                except Exception as e:  # noqa: BLE001 — malformed request
                    self._respond(400, str(e).encode(), "text/plain")
                    return
                self._respond(200, json.dumps(out).encode(),
                              "application/json")

            def _respond(self, code: int, body: bytes, ctype: str):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        if cert_file and key_file:
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(cert_file, key_file)

            class _TLSReq(_Req):
                """Handshake in the worker thread's setup(), NOT on the
                listening socket or in get_request (both run on the accept
                loop): one stalled client (port scanner, plain-TCP health
                check) must not block all admission traffic."""

                def setup(self):
                    self.request.settimeout(10.0)
                    try:
                        self.request = ctx.wrap_socket(self.request,
                                                       server_side=True)
                    except (ssl.SSLError, OSError) as e:
                        # Non-TLS probe or stalled client: drop quietly
                        # instead of a per-connection stderr traceback.
                        log.debug("TLS handshake failed: %s", e)
                        self._handshake_failed = True
                    super().setup()

                def handle(self):
                    if getattr(self, "_handshake_failed", False):
                        return
                    super().handle()

            self._server = ThreadingHTTPServer((addr, port), _TLSReq)
        else:
            self._server = ThreadingHTTPServer((addr, port), _Req)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self) -> None:
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        daemon=True, name="webhook")
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread:
            self._thread.join(timeout=5)
