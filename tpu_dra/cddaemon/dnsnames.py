"""Stable peer naming: /etc/hosts block + nodes.cfg rendering.

Reference: cmd/compute-domain-daemon/dnsnames.go:34-214 — in the default
DNS-names mode the rendezvous config lists *stable* per-slice names
(``compute-domain-daemon-%04d`` there, ``tpu-cd-daemon-%04d`` here) so the
native daemon's config never churns when IPs change; the name→IP mapping
lives in a managed /etc/hosts block that is atomically rewritten on
membership updates, after which the daemon gets SIGUSR1 to re-resolve.
"""

from __future__ import annotations

import os
import tempfile
from typing import List, Tuple

STABLE_NAME_FMT = "tpu-cd-daemon-{:04d}"
BLOCK_BEGIN = "# BEGIN tpu-dra compute-domain peers\n"
BLOCK_END = "# END tpu-dra compute-domain peers\n"


def stable_name(index: int) -> str:
    return STABLE_NAME_FMT.format(index)


def render_hosts_block(nodes: List[Tuple[int, str]]) -> str:
    """nodes: [(index, ip)] within this slice group."""
    lines = [BLOCK_BEGIN]
    for index, ip in sorted(nodes):
        lines.append(f"{ip}\t{stable_name(index)}\n")
    lines.append(BLOCK_END)
    return "".join(lines)


def update_hosts_file(path: str, nodes: List[Tuple[int, str]]) -> bool:
    """Replace (or append) the managed block, writing IN PLACE — /etc/hosts
    is a kubelet bind mount in pods and rename-over-mount fails EBUSY, so a
    torn read is theoretically possible but heals on the next resolve (the
    reference accepts the same tradeoff, dnsnames.go:182). Returns True if
    the content changed."""
    try:
        with open(path) as f:
            content = f.read()
    except FileNotFoundError:
        content = ""
    begin = content.find(BLOCK_BEGIN)
    end = content.find(BLOCK_END)
    block = render_hosts_block(nodes)
    if begin >= 0 and end >= 0:
        new = content[:begin] + block + content[end + len(BLOCK_END):]
    else:
        sep = "" if content.endswith("\n") or not content else "\n"
        new = content + sep + block
    if new == content:
        return False
    # In-place write, NOT rename: in a pod /etc/hosts is a kubelet bind
    # mount and rename-over-mount fails with EBUSY (the reference writes
    # in place for the same reason, dnsnames.go:182).
    with open(path, "w") as f:
        f.write(new)
    return True


def write_nodes_config(path: str, names_or_ips: List[str], port: int) -> bool:
    """Write the native daemon's peer list (one host:port per line).
    Returns True if content changed."""
    body = "".join(f"{n}:{port}\n" for n in names_or_ips)
    try:
        with open(path) as f:
            if f.read() == body:
                return False
    except FileNotFoundError:
        pass
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                               prefix=".nodes-")
    try:
        with os.fdopen(fd, "w") as f:
            f.write(body)
        os.replace(tmp, path)
    except BaseException:
        os.unlink(tmp)
        raise
    return True
