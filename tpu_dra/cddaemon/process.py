"""Child-process supervision for the native slice daemon.

Reference: cmd/compute-domain-daemon/process.go:38-247 — start/stop/restart
with buffered wait-reaping, a 1s watchdog that restarts the child on
unexpected exit (:170-203), and signal forwarding.

Beyond the reference: the watchdog is a real supervisor — consecutive
crashes back off exponentially (capped) instead of respawning a
crash-looping child every tick, and an ``on_restart`` hook lets the
owner republish readiness the moment a replacement child is spawned
(the readiness mirror otherwise waits a full steady-state probe period
to notice the daemon it reported Ready is gone).

Locking discipline (dralint R2): ``self._lock`` is a data lock — it
guards the manager's fields and is never held across blocking work.
The fork/exec and child-reap syscalls run OUTSIDE it, serialized by a
*spawn slot* (``_spawning``) claimed under the lock: whichever of
ensure_started / restart / the watchdog claims the slot performs the
blocking spawn alone, and racers skip (the watchdog retries on its
next tick). Before this protocol, a wedged exec (ENOMEM, cold image
pull) stalled every signal/readiness/pid call behind ``_lock`` for the
duration of the spawn.
"""

from __future__ import annotations

import logging
import signal
import subprocess
import threading
import time
from typing import Callable, List, Optional

from tpu_dra.infra.faults import FAULTS

log = logging.getLogger("tpu_dra.cddaemon.process")


class ProcessManager:
    # Consecutive-crash restart backoff: first respawn is immediate (the
    # common one-off crash), then 0.5s * 2^n capped at 15s — a corrupt
    # config must not fork-bomb the node at watchdog frequency.
    RESTART_BACKOFF_BASE = 0.5
    RESTART_BACKOFF_MAX = 15.0

    def __init__(self, argv: List[str], watchdog_interval: float = 1.0,
                 on_restart: Optional[Callable[[], None]] = None):
        self._argv = argv
        self._interval = watchdog_interval
        self._on_restart = on_restart
        self._proc: Optional[subprocess.Popen] = None
        self._lock = threading.RLock()
        self._want_running = False
        self._stop = threading.Event()
        self._watchdog: Optional[threading.Thread] = None
        self.restarts = 0
        self._crashes = 0           # consecutive, reset on confirmed-ready
        self._next_restart_at = 0.0
        # Non-fatal signals are held until the child is confirmed alive
        # (mark_ready(), driven by the wrapper's first successful READY
        # probe): a SIGUSR1 delivered in the exec->handler-install window
        # kills the child with its default disposition. Observed as
        # "child exited unexpectedly (rc=-10)" in BENCH_r03.
        self._confirmed_ready = False
        self._pending_signals: List[int] = []
        # Spawn slot: True while one thread runs the blocking fork/exec
        # outside _lock; claimed/released only under _lock. _spawn_done
        # is the slot's completion signal: cleared at claim, set after
        # the spawn committed, aborted-and-reaped, or failed — stop()
        # waits on it so a freshly spawned child can never outlive stop.
        self._spawning = False
        self._spawn_done = threading.Event()
        self._spawn_done.set()
        # Watchdog start slot: same shape as the spawn slot, so two
        # concurrent ensure_started() calls cannot start two watchdogs.
        self._watchdog_starting = False

    # -- lifecycle ----------------------------------------------------------

    def ensure_started(self) -> None:
        with self._lock:
            self._want_running = True
            spawn = ((self._proc is None or self._proc.poll() is not None)
                     and self._claim_spawn_slot_locked())
        if spawn:
            # Raises on exec failure (fault site / OSError): propagate to
            # the caller without starting the watchdog — same contract as
            # the pre-slot code, where the spawn failed inside the lock.
            self._spawn_and_commit()
        with self._lock:
            wd = self._watchdog
        if wd is not None and wd.is_alive() and self._stop.is_set():
            # A previous stop() left an exiting (or spawn-wedged)
            # watchdog behind: give it a moment to finish so the child
            # spawned above does not run unsupervised.
            wd.join(timeout=2)
        start = False
        with self._lock:
            wd = self._watchdog
            if wd is not None and not wd.is_alive():
                wd = self._watchdog = None  # stop() kept a dead handle
            if wd is None and not self._watchdog_starting:
                self._watchdog_starting = True
                start = True
        if wd is not None and self._stop.is_set():
            log.warning("previous watchdog still wedged; supervision "
                        "re-arms on a later ensure_started()")
        if start:
            # Re-arm after a previous stop(): a set _stop would make the new
            # watchdog thread exit immediately, leaving the child unwatched.
            self._stop.clear()
            wd = threading.Thread(
                target=self._watch, daemon=True, name="process-watchdog")
            # Start BEFORE publishing: a concurrent stop() must never
            # join() a thread that was never started (RuntimeError). If
            # it reads None instead, the fresh watchdog sees _stop set
            # and exits on its first wait.
            try:
                wd.start()
            except BaseException:
                with self._lock:
                    self._watchdog_starting = False  # slot must not wedge
                raise
            with self._lock:
                self._watchdog = wd
                self._watchdog_starting = False

    def _claim_spawn_slot_locked(self) -> bool:
        """Claim the single spawn slot (False: another thread is already
        mid-spawn — skip; the watchdog re-checks on its next tick)."""
        if self._spawning:
            return False
        self._spawning = True
        self._spawn_done.clear()
        # The child being replaced can no longer confirm readiness;
        # hold non-fatal signals for the replacement's exec window.
        self._confirmed_ready = False
        return True

    def _spawn_and_commit(self) -> Optional[subprocess.Popen]:
        """Blocking fork/exec, run OUTSIDE _lock with the spawn slot
        held. Commits the child under the lock; returns None when a
        concurrent stop() made the spawn moot (the fresh child is
        terminated, not committed)."""
        try:
            # Injection site: exec failure (binary missing after an image
            # upgrade, ENOMEM) — the supervisor must back off and keep
            # trying, not die with the watchdog thread.
            FAULTS.check("cddaemon.spawn", argv=self._argv)
            log.info("starting: %s", " ".join(self._argv))
            proc = subprocess.Popen(self._argv)
        except BaseException:
            with self._lock:
                self._spawning = False
            self._spawn_done.set()
            raise
        with self._lock:
            self._spawning = False
            abort = not self._want_running
            if not abort:
                self._confirmed_ready = False
                self._proc = proc
        if abort:
            # Reap BEFORE signaling done: a stop() blocked on
            # _spawn_done must find the aborted child already dead.
            self._reap(proc)
            self._spawn_done.set()
            return None
        self._spawn_done.set()
        return proc

    @staticmethod
    def _reap(proc: subprocess.Popen, grace: float = 5.0) -> None:
        """Terminate + wait (escalating to SIGKILL); never under _lock."""
        if proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=grace)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()

    def stop(self, grace: float = 5.0) -> None:
        with self._lock:
            self._want_running = False
            proc = self._proc
        self._stop.set()
        if proc is not None:
            self._reap(proc, grace)
        # An in-flight spawn either commits (visible below) or aborts —
        # reaping its child — before signaling done; wait so no fresh
        # child outlives stop(). A Popen wedged past `grace` is the one
        # bounded exception, mirroring the reap escalation timeout.
        self._spawn_done.wait(timeout=grace)
        with self._lock:
            committed = self._proc
        if committed is not None and committed is not proc:
            self._reap(committed, grace)  # spawn committed mid-stop
        with self._lock:
            wd = self._watchdog
        if wd is not None:
            wd.join(timeout=2)
            if wd.is_alive():
                # Wedged mid-spawn past every grace: keep the handle so
                # ensure_started() cannot start a duplicate watchdog;
                # the thread exits on its own when the spawn unwedges
                # (_stop is set).
                log.warning("watchdog did not stop within 2s; "
                            "keeping handle to prevent a duplicate")
            else:
                with self._lock:
                    if self._watchdog is wd:
                        self._watchdog = None

    def restart(self) -> None:
        """Full stop/start (legacy IP-mode membership change)."""
        with self._lock:
            proc = self._proc
            spawn = self._want_running and self._claim_spawn_slot_locked()
        if proc is not None:
            self._reap(proc)
        if spawn and self._spawn_and_commit() is not None:
            with self._lock:
                self.restarts += 1

    def signal(self, sig: int = signal.SIGUSR1) -> None:
        """Forward a signal (SIGUSR1 = re-resolve peers, main.go:368).

        Held (coalesced) until mark_ready() if the current child has not
        yet been confirmed ready; a membership-change nudge is idempotent,
        so one deferred delivery is equivalent to many.
        """
        with self._lock:
            if not self._confirmed_ready:
                if sig not in self._pending_signals:
                    self._pending_signals.append(sig)
                return
            if self._proc is not None and self._proc.poll() is None:
                self._proc.send_signal(sig)

    def pid(self) -> Optional[int]:
        """Pid of the current child, or None. Snapshot this *before* a
        readiness probe and pass it to mark_ready() so a probe answered by
        a child that has since been restarted cannot confirm its
        replacement."""
        with self._lock:
            return None if self._proc is None else self._proc.pid

    def mark_ready(self, pid: Optional[int] = None) -> None:
        """The child answered its readiness probe: safe to deliver held
        signals (its handlers are necessarily installed by then).

        ``pid``: the pid() snapshot taken before the probe. If the child
        has been replaced since (watchdog restart), the confirmation is
        stale — ignoring it keeps held signals out of the new child's
        exec window, which is the exact race this hold exists to close.
        """
        with self._lock:
            if self._proc is None:
                return
            if pid is not None and self._proc.pid != pid:
                return
            self._confirmed_ready = True
            # A child that reached ready ends the crash streak: the next
            # unexpected exit restarts immediately again.
            self._crashes = 0
            self._next_restart_at = 0.0
            pending, self._pending_signals = self._pending_signals, []
            for sig in pending:
                if self._proc.poll() is None:
                    self._proc.send_signal(sig)

    def running(self) -> bool:
        with self._lock:
            return self._proc is not None and self._proc.poll() is None

    # -- watchdog -----------------------------------------------------------

    def _watch(self) -> None:
        while not self._stop.wait(self._interval):
            with self._lock:
                if not self._want_running or self._spawning:
                    continue
                if self._proc is None or self._proc.poll() is None:
                    continue
                now = time.monotonic()
                if now < self._next_restart_at:
                    continue  # crash-looping: hold the backoff
                log.warning("child exited unexpectedly (rc=%s); restarting"
                            " (crash streak %d)", self._proc.returncode,
                            self._crashes + 1)
                self._crashes += 1
                self._next_restart_at = now + min(
                    self.RESTART_BACKOFF_BASE * (2 ** (self._crashes - 1)),
                    self.RESTART_BACKOFF_MAX)
                self._claim_spawn_slot_locked()
            try:
                restarted = self._spawn_and_commit() is not None
            except Exception:  # noqa: BLE001 — spawn failed: the backoff
                # above already schedules the next attempt; the watchdog
                # thread must survive to make it.
                log.exception("respawn failed; retrying after backoff")
                continue
            if restarted:
                with self._lock:
                    self.restarts += 1
            if restarted and self._on_restart is not None:
                # On its own thread: the hook touches the API server
                # (readiness republish, with retries that can run long
                # during an outage) and must stall neither supervision —
                # a child dying mid-hook still gets its backed-off
                # respawn — nor stop()'s watchdog join.
                threading.Thread(target=self._run_restart_hook,
                                 daemon=True,
                                 name="process-on-restart").start()

    def _run_restart_hook(self) -> None:
        try:
            self._on_restart()
        except Exception:  # noqa: BLE001 — a broken hook must not kill
            # the supervisor
            log.exception("on_restart hook failed")
