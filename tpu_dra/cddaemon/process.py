"""Child-process supervision for the native slice daemon.

Reference: cmd/compute-domain-daemon/process.go:38-247 — start/stop/restart
with buffered wait-reaping, a 1s watchdog that restarts the child on
unexpected exit (:170-203), and signal forwarding.

Beyond the reference: the watchdog is a real supervisor — consecutive
crashes back off exponentially (capped) instead of respawning a
crash-looping child every tick, and an ``on_restart`` hook lets the
owner republish readiness the moment a replacement child is spawned
(the readiness mirror otherwise waits a full steady-state probe period
to notice the daemon it reported Ready is gone).
"""

from __future__ import annotations

import logging
import signal
import subprocess
import threading
import time
from typing import Callable, List, Optional

from tpu_dra.infra.faults import FAULTS

log = logging.getLogger("tpu_dra.cddaemon.process")


class ProcessManager:
    # Consecutive-crash restart backoff: first respawn is immediate (the
    # common one-off crash), then 0.5s * 2^n capped at 15s — a corrupt
    # config must not fork-bomb the node at watchdog frequency.
    RESTART_BACKOFF_BASE = 0.5
    RESTART_BACKOFF_MAX = 15.0

    def __init__(self, argv: List[str], watchdog_interval: float = 1.0,
                 on_restart: Optional[Callable[[], None]] = None):
        self._argv = argv
        self._interval = watchdog_interval
        self._on_restart = on_restart
        self._proc: Optional[subprocess.Popen] = None
        self._lock = threading.RLock()
        self._want_running = False
        self._stop = threading.Event()
        self._watchdog: Optional[threading.Thread] = None
        self.restarts = 0
        self._crashes = 0           # consecutive, reset on confirmed-ready
        self._next_restart_at = 0.0
        # Non-fatal signals are held until the child is confirmed alive
        # (mark_ready(), driven by the wrapper's first successful READY
        # probe): a SIGUSR1 delivered in the exec->handler-install window
        # kills the child with its default disposition. Observed as
        # "child exited unexpectedly (rc=-10)" in BENCH_r03.
        self._confirmed_ready = False
        self._pending_signals: List[int] = []

    # -- lifecycle ----------------------------------------------------------

    def ensure_started(self) -> None:
        with self._lock:
            self._want_running = True
            if self._proc is None or self._proc.poll() is not None:
                self._spawn_locked()
        if self._watchdog is None:
            # Re-arm after a previous stop(): a set _stop would make the new
            # watchdog thread exit immediately, leaving the child unwatched.
            self._stop.clear()
            self._watchdog = threading.Thread(
                target=self._watch, daemon=True, name="process-watchdog")
            self._watchdog.start()

    def _spawn_locked(self) -> None:
        # Injection site: exec failure (binary missing after an image
        # upgrade, ENOMEM) — the supervisor must back off and keep
        # trying, not die with the watchdog thread.
        FAULTS.check("cddaemon.spawn", argv=self._argv)
        log.info("starting: %s", " ".join(self._argv))
        self._confirmed_ready = False
        self._proc = subprocess.Popen(self._argv)

    def stop(self, grace: float = 5.0) -> None:
        with self._lock:
            self._want_running = False
            proc = self._proc
        self._stop.set()
        if proc is not None and proc.poll() is None:
            proc.terminate()
            try:
                proc.wait(timeout=grace)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
        if self._watchdog:
            self._watchdog.join(timeout=2)
            self._watchdog = None

    def restart(self) -> None:
        """Full stop/start (legacy IP-mode membership change)."""
        with self._lock:
            proc = self._proc
            if proc is not None and proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait()
            if self._want_running:
                self._spawn_locked()
                self.restarts += 1

    def signal(self, sig: int = signal.SIGUSR1) -> None:
        """Forward a signal (SIGUSR1 = re-resolve peers, main.go:368).

        Held (coalesced) until mark_ready() if the current child has not
        yet been confirmed ready; a membership-change nudge is idempotent,
        so one deferred delivery is equivalent to many.
        """
        with self._lock:
            if not self._confirmed_ready:
                if sig not in self._pending_signals:
                    self._pending_signals.append(sig)
                return
            if self._proc is not None and self._proc.poll() is None:
                self._proc.send_signal(sig)

    def pid(self) -> Optional[int]:
        """Pid of the current child, or None. Snapshot this *before* a
        readiness probe and pass it to mark_ready() so a probe answered by
        a child that has since been restarted cannot confirm its
        replacement."""
        with self._lock:
            return None if self._proc is None else self._proc.pid

    def mark_ready(self, pid: Optional[int] = None) -> None:
        """The child answered its readiness probe: safe to deliver held
        signals (its handlers are necessarily installed by then).

        ``pid``: the pid() snapshot taken before the probe. If the child
        has been replaced since (watchdog restart), the confirmation is
        stale — ignoring it keeps held signals out of the new child's
        exec window, which is the exact race this hold exists to close.
        """
        with self._lock:
            if self._proc is None:
                return
            if pid is not None and self._proc.pid != pid:
                return
            self._confirmed_ready = True
            # A child that reached ready ends the crash streak: the next
            # unexpected exit restarts immediately again.
            self._crashes = 0
            self._next_restart_at = 0.0
            pending, self._pending_signals = self._pending_signals, []
            for sig in pending:
                if self._proc.poll() is None:
                    self._proc.send_signal(sig)

    def running(self) -> bool:
        with self._lock:
            return self._proc is not None and self._proc.poll() is None

    # -- watchdog -----------------------------------------------------------

    def _watch(self) -> None:
        while not self._stop.wait(self._interval):
            restarted = False
            with self._lock:
                if not self._want_running:
                    continue
                if self._proc is None or self._proc.poll() is None:
                    continue
                now = time.monotonic()
                if now < self._next_restart_at:
                    continue  # crash-looping: hold the backoff
                log.warning("child exited unexpectedly (rc=%s); restarting"
                            " (crash streak %d)", self._proc.returncode,
                            self._crashes + 1)
                self._crashes += 1
                self._next_restart_at = now + min(
                    self.RESTART_BACKOFF_BASE * (2 ** (self._crashes - 1)),
                    self.RESTART_BACKOFF_MAX)
                try:
                    self._spawn_locked()
                except Exception:  # noqa: BLE001 — spawn failed: the
                    # backoff above already schedules the next attempt;
                    # the watchdog thread must survive to make it.
                    log.exception("respawn failed; retrying after backoff")
                    continue
                self.restarts += 1
                restarted = True
            if restarted and self._on_restart is not None:
                # On its own thread: the hook touches the API server
                # (readiness republish, with retries that can run long
                # during an outage) and must stall neither supervision —
                # a child dying mid-hook still gets its backed-off
                # respawn — nor stop()'s watchdog join.
                threading.Thread(target=self._run_restart_hook,
                                 daemon=True,
                                 name="process-on-restart").start()

    def _run_restart_hook(self) -> None:
        try:
            self._on_restart()
        except Exception:  # noqa: BLE001 — a broken hook must not kill
            # the supervisor
            log.exception("on_restart hook failed")
