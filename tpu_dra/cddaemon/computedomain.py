"""Daemon-side ComputeDomain registration and membership tracking.

Reference: cmd/compute-domain-daemon/computedomain.go —
``EnsureNodeInfoInCD`` (:232-356) inserts {name, ip, sliceID, index} into
the CD status with gap-filling index allocation *within the node's slice
group* (stable DNS names derive from the index), bounded by
maxNodesPerSliceDomain; node-set changes are deduped and pushed over a
queue (:386-434); the node removes itself from the status on shutdown.
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

from tpu_dra.api import types as apitypes
from tpu_dra.infra.trace import ENV_TRACEPARENT, TRACER
from tpu_dra.infra.workqueue import default_cd_daemon_rate_limiter
from tpu_dra.k8s import ApiClient, COMPUTEDOMAINS
from tpu_dra.k8s.client import ConflictError, NotFoundError
from tpu_dra.k8s.informer import Informer

log = logging.getLogger("tpu_dra.cddaemon.cd")

# A membership snapshot: tuple of (name, ip, slice_id, index) per node.
NodeSet = Tuple[Tuple[str, str, str, int], ...]


class IndexAllocationError(Exception):
    pass


def allocate_index(nodes: List[Dict], slice_id: str, max_nodes: int) -> int:
    """Smallest free index within the slice group (computedomain.go:311-356).
    Gap-filling keeps DNS names stable when members churn."""
    used = {n.get("index", 0) for n in nodes
            if n.get("sliceID", "") == slice_id}
    for candidate in range(max_nodes):
        if candidate not in used:
            return candidate
    raise IndexAllocationError(
        f"slice {slice_id!r} is full ({max_nodes} nodes)")


class ComputeDomainManager:
    def __init__(self, client: ApiClient, *, cd_name: str, cd_namespace: str,
                 cd_uid: str, node_name: str, node_ip: str, slice_id: str,
                 max_nodes: int = 64):
        self._client = client
        self._cd_name = cd_name
        self._cd_ns = cd_namespace
        self._cd_uid = cd_uid
        self._node_name = node_name
        self._node_ip = node_ip
        self._slice_id = slice_id
        self._max_nodes = max_nodes
        self.index: Optional[int] = None
        # Deduped membership updates; maxsize=1 with latest-wins put.
        self.updates: "queue.Queue[NodeSet]" = queue.Queue(maxsize=1)
        self._last_set: Optional[NodeSet] = None
        self._lock = threading.Lock()
        # Name-filtered informer (controller.go:28-120).
        self.informer = Informer(
            client, COMPUTEDOMAINS, namespace=cd_namespace,
            field_filter=lambda obj: (obj.get("metadata", {}).get("name")
                                      == cd_name))
        self.informer.on_add(self._on_change)
        self.informer.on_update(lambda _old, new: self._on_change(new))

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self.informer.start()
        self.informer.wait_for_sync()

    def stop(self) -> None:
        self.informer.stop()

    # -- registration -------------------------------------------------------

    def _get_cd(self) -> Dict:
        cd = self._client.get(COMPUTEDOMAINS, self._cd_name, self._cd_ns)
        if self._cd_uid and cd["metadata"].get("uid") != self._cd_uid:
            raise NotFoundError(
                f"computedomain {self._cd_name} uid changed")
        return cd

    def ensure_node_info(self, retries: int = 20) -> int:
        """Insert/refresh this node in the CD status; returns the stable
        index. Conflict-retried with jittered exponential backoff: at
        fleet startup up to max_nodes daemons race writes on one status
        object, and a tight loop exhausts its budget and crashes the pod
        (the reference drives this through DefaultCDDaemonRateLimiter)."""
        backoff = default_cd_daemon_rate_limiter()
        for _ in range(retries):
            cd = self._get_cd()
            status = cd.setdefault("status", {})
            status.setdefault(
                "status", apitypes.COMPUTE_DOMAIN_STATUS_NOT_READY)
            nodes = status.setdefault("nodes", [])
            mine = next((n for n in nodes
                         if n.get("name") == self._node_name), None)
            if mine is not None:
                if (mine.get("ipAddress") == self._node_ip
                        and mine.get("sliceID") == self._slice_id):
                    self.index = mine.get("index", 0)
                    return self.index
                mine["ipAddress"] = self._node_ip
                if mine.get("sliceID") != self._slice_id:
                    # Re-provisioned into a different slice: the old index
                    # may collide inside the new group — reallocate there.
                    mine["sliceID"] = self._slice_id
                    mine["index"] = allocate_index(
                        [n for n in nodes if n is not mine],
                        self._slice_id, self._max_nodes)
                index = mine.get("index", 0)
            else:
                index = allocate_index(nodes, self._slice_id, self._max_nodes)
                nodes.append({
                    "name": self._node_name,
                    "ipAddress": self._node_ip,
                    "sliceID": self._slice_id,
                    "index": index,
                    "status": apitypes.COMPUTE_DOMAIN_STATUS_NOT_READY,
                })
            try:
                self._client.update_status(COMPUTEDOMAINS, cd)
                self.index = index
                return index
            except ConflictError:
                time.sleep(backoff.when(0))
                continue
        raise ConflictError(
            f"could not register node {self._node_name} after {retries} tries")

    def remove_node_info(self, retries: int = 20) -> None:
        """Self-removal on shutdown (computedomain.go:386-434)."""
        backoff = default_cd_daemon_rate_limiter()
        for _ in range(retries):
            try:
                cd = self._get_cd()
            except NotFoundError:
                return
            nodes = (cd.get("status") or {}).get("nodes") or []
            kept = [n for n in nodes if n.get("name") != self._node_name]
            if len(kept) == len(nodes):
                return
            cd["status"]["nodes"] = kept
            try:
                self._client.update_status(COMPUTEDOMAINS, cd)
                return
            except ConflictError:
                time.sleep(backoff.when(0))
                continue
        # A silently stale registration holds the index and keeps the node
        # counted Ready; surface the failure to the caller.
        raise ConflictError(
            f"could not deregister node {self._node_name} after "
            f"{retries} tries")

    def set_node_status(self, ready: bool, retries: int = 20) -> None:
        """Mirror local daemon readiness into the per-node status field
        (podmanager.go:35-120)."""
        want = (apitypes.COMPUTE_DOMAIN_STATUS_READY if ready
                else apitypes.COMPUTE_DOMAIN_STATUS_NOT_READY)
        backoff = default_cd_daemon_rate_limiter()
        for _ in range(retries):
            try:
                cd = self._get_cd()
            except NotFoundError:
                return
            nodes = (cd.get("status") or {}).get("nodes") or []
            mine = next((n for n in nodes
                         if n.get("name") == self._node_name), None)
            if mine is None or mine.get("status") == want:
                return
            mine["status"] = want
            try:
                self._client.update_status(COMPUTEDOMAINS, cd)
                if ready:
                    # Trace-loop closure (SURVEY §19): a daemon launched
                    # from a CD claim's CDI env carries the claim's
                    # TPU_DRA_TRACEPARENT — the readiness mirror is the
                    # claim's last control-plane hop, landed as a closed
                    # ``cd.ready`` span on the same trace. No env, no
                    # span (in-sim daemons run without the claim env).
                    tp = os.environ.get(ENV_TRACEPARENT)
                    if tp:
                        TRACER.record_span(
                            "cd.ready", 0.0, traceparent=tp,
                            attributes={"node": self._node_name})
                return
            except ConflictError:
                time.sleep(backoff.when(0))
                continue
        # Surface exhaustion so the caller retries (a silent return would
        # let the readiness loop record the mirror as done).
        raise ConflictError(
            f"could not mirror node status for {self._node_name} "
            f"after {retries} tries")

    # -- membership updates -------------------------------------------------

    def _on_change(self, cd: Dict) -> None:
        nodes = (cd.get("status") or {}).get("nodes") or []
        node_set: NodeSet = tuple(sorted(
            (n.get("name", ""), n.get("ipAddress", ""),
             n.get("sliceID", ""), n.get("index", 0))
            for n in nodes))
        with self._lock:
            if node_set == self._last_set:
                return
            self._last_set = node_set
        # Latest wins: drop a stale queued snapshot if the consumer lags.
        while True:
            try:
                self.updates.put_nowait(node_set)
                return
            except queue.Full:
                try:
                    self.updates.get_nowait()
                except queue.Empty:
                    pass

    def slice_peers(self, node_set: NodeSet) -> List[Tuple[int, str]]:
        """[(index, ip)] of members in this node's slice group — the set
        that rendezvous over ICI; other slices are DCN-reachable peers
        (heterogeneous CD, main.go:205-213 analog)."""
        return [(index, ip) for (_name, ip, slice_id, index) in node_set
                if slice_id == self._slice_id]
