"""ComputeDomain node daemon (reference: cmd/compute-domain-daemon).

Runs in each per-CD DaemonSet pod. Wraps the native ``tpu-slice-daemon``
binary (the nvidia-imex analog), registers this node into the CD status
with a stable per-slice index, maintains the peer rendezvous config
(/etc/hosts + nodes.cfg, SIGUSR1 re-resolve), and exposes the ``check``
readiness probe.
"""
