"""ComputeDomain daemon entrypoint: ``run`` and ``check`` subcommands.

Reference: cmd/compute-domain-daemon/main.go —
``run`` (:190-294): write the native daemon's config with the pod IP,
register this node into the CD status, spawn the update loop + process
watchdog; membership changes rewrite /etc/hosts + nodes.cfg and SIGUSR1 the
daemon (DNS-names mode, :296-377) or rewrite IPs and restart (legacy mode).
``check`` (:381-405): local readiness probe — READY or exit 1.

Divergence from the reference, by design: the slice daemon runs on every
member (the reference skips IMEX on empty-clique nodes, main.go:205-213,
because IMEX would export memory over a fabric that is not there; our
daemon is a rendezvous/health server with no fabric side effects, so
DCN-only members get the same probe path — their peer list is just empty).

Run: ``python -m tpu_dra.cddaemon.main run|check [flags]``
"""

from __future__ import annotations

import logging
import os
import queue
import signal
import socket
import sys
import threading
from typing import Optional

from tpu_dra.cddaemon.computedomain import ComputeDomainManager
from tpu_dra.cddaemon.dnsnames import (
    stable_name, update_hosts_file, write_nodes_config,
)
from tpu_dra.cddaemon.process import ProcessManager
from tpu_dra.infra import debug, featuregates
from tpu_dra.infra.faults import FAULTS
from tpu_dra.infra.flags import (
    Flag, FlagSet, apply_feature_gates, feature_gate_flag, logging_flags,
    setup_logging,
)
from tpu_dra.k8s.client import HttpApiClient, RetryingApiClient
from tpu_dra.native.tpuinfo import get_backend

log = logging.getLogger("tpu_dra.cddaemon")

DEFAULT_PORT = 7551

# DNS-stable rendezvous needs an accel driver that re-resolves peer names
# on SIGUSR1 (the driver >= 570.158.01 gate of the reference,
# cd-plugin device_state.go:666-690).
MIN_DNS_DRIVER_VERSION = (0, 9, 0)


def parse_driver_version(raw: str):
    """'1.2.3-suffix' -> (1, 2, 3); unparseable -> None."""
    parts = raw.split("-")[0].split(".")
    try:
        return tuple(int(p) for p in parts[:3])
    except ValueError:
        return None


def dns_names_supported(raw_version: str) -> bool:
    parsed = parse_driver_version(raw_version)
    return parsed is not None and parsed >= MIN_DNS_DRIVER_VERSION


def _default_daemon_binary() -> str:
    here = os.path.dirname(os.path.abspath(__file__))
    candidates = [
        os.environ.get("TPU_DRA_SLICE_DAEMON", ""),
        os.path.join(here, "..", "..", "native", "build", "tpu-slice-daemon"),
        "/usr/local/bin/tpu-slice-daemon",
    ]
    for c in candidates:
        if c and os.path.exists(c):
            return os.path.abspath(c)
    return "tpu-slice-daemon"


def flags() -> FlagSet:
    return FlagSet("tpu-cd-daemon", [
        Flag("cd-uid", "CD_UID", required=True,
             help="UID of the ComputeDomain this daemon belongs to"),
        Flag("cd-name", "CD_NAME", required=True, help="ComputeDomain name"),
        Flag("cd-namespace", "CD_NAMESPACE", required=True,
             help="ComputeDomain namespace"),
        Flag("node-name", "NODE_NAME", required=True, help="node name"),
        Flag("pod-ip", "POD_IP", required=True, help="this pod's IP"),
        Flag("port", "SLICE_DAEMON_PORT", default=DEFAULT_PORT, type=int,
             help="slice daemon rendezvous/status port"),
        Flag("work-dir", "WORK_DIR", default="/var/run/tpu-dra-cd",
             help="config/state directory (the /imexd analog)"),
        Flag("hosts-file", "HOSTS_FILE", default="/etc/hosts",
             help="hosts file managed for stable peer names"),
        Flag("daemon-binary", "SLICE_DAEMON_BINARY",
             default=_default_daemon_binary(),
             help="path to the native tpu-slice-daemon"),
        Flag("max-nodes-per-slice-domain", "MAX_NODES_PER_SLICE_DOMAIN",
             default=64, type=int, help="index allocation bound"),
        Flag("kube-api-url", "KUBE_API_URL", default=None,
             help="API server URL (default: in-cluster config)"),
        feature_gate_flag(),
        *logging_flags(),
    ])


def discover_slice_id(backend) -> str:
    """cliqueID discovery analog (cd-plugin nvlib.go:187-258): every chip on
    the node must agree on the slice identity; '' = not part of an ICI slice
    (DCN-only member of a heterogeneous domain)."""
    ids = {c.slice_id for c in backend.chips()}
    if not ids:
        return ""
    if len(ids) > 1:
        raise RuntimeError(
            f"chips disagree on slice identity: {sorted(ids)}")
    return ids.pop()


class DaemonRunner:
    """Wires CD registration, the native process, and the update loop;
    factored as a class so tests can drive it without a real pod."""

    # Member-loss settle: a dying slice produces a BURST of removals
    # (one CD status write per departing daemon). Reconfiguring the
    # native daemon per removal means N hosts-file rewrites and — in
    # legacy IP mode — N full child restarts in quick succession, a
    # self-inflicted crash loop on every surviving node exactly when
    # the domain is most fragile. Shrinks therefore wait this long and
    # drain to the LATEST membership snapshot before reconfiguring:
    # one burst, one reconfigure. Growth stays immediate (a joining
    # member should rendezvous at probe latency).
    MEMBER_LOSS_SETTLE_S = 0.25

    def __init__(self, client, ns):
        self.ns = ns
        self.client = client
        self.backend = get_backend()
        chips = self.backend.chips()
        self.slice_id = discover_slice_id(self.backend)
        # Version-gate input, captured once (native backends rescan chips
        # per call). Chipless DCN-only members have no accel driver to
        # impose the constraint — treat DNS mode as supported there.
        self.dns_supported = (not chips
                              or dns_names_supported(chips[0].driver_version))
        self.cd = ComputeDomainManager(
            client, cd_name=ns.cd_name, cd_namespace=ns.cd_namespace,
            cd_uid=ns.cd_uid, node_name=ns.node_name, node_ip=ns.pod_ip,
            slice_id=self.slice_id, max_nodes=ns.max_nodes_per_slice_domain)
        self.config_path = os.path.join(ns.work_dir, "slice-daemon.cfg")
        self.nodes_path = os.path.join(ns.work_dir, "nodes.cfg")
        self.process = ProcessManager(
            [ns.daemon_binary, "--config", self.config_path],
            on_restart=self._on_daemon_restart)
        self._stop = threading.Event()
        self._threads = []
        self._last_ready = None

    # -- setup --------------------------------------------------------------

    def write_config(self, index: int) -> None:
        os.makedirs(self.ns.work_dir, exist_ok=True)
        with open(self.config_path, "w") as f:
            f.write(f"node_ip={self.ns.pod_ip}\n"
                    f"port={self.ns.port}\n"
                    f"nodes_config={self.nodes_path}\n"
                    f"slice_id={self.slice_id}\n"
                    f"worker_index={index}\n")

    def start(self) -> None:
        self.cd.start()
        index = self.cd.ensure_node_info()
        log.info("registered node %s (slice %r, index %d)",
                 self.ns.node_name, self.slice_id, index)
        self.write_config(index)
        write_nodes_config(self.nodes_path, [], self.ns.port)
        self.process.ensure_started()
        self._threads = [
            threading.Thread(target=self._update_loop, daemon=True,
                             name="cd-update-loop"),
            threading.Thread(target=self._readiness_loop, daemon=True,
                             name="cd-readiness"),
        ]
        for t in self._threads:
            t.start()

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=3)
        self.process.stop()
        try:
            self.cd.remove_node_info()
        except Exception:  # noqa: BLE001 — still stop the informer below
            log.exception("deregistration failed; stale entry will be "
                          "cleaned by the controller's pod-delete handler")
        self.cd.stop()

    def _on_daemon_restart(self) -> None:
        """Supervisor hook: a crashed slice daemon was respawned. Force
        the readiness mirror pessimistic NOW — workloads gating on the CD
        channel must not ride a Ready status backed by a daemon that just
        died — and drop the loop back to its fast startup cadence so the
        recovered daemon republishes Ready at probe latency.

        Publish BEFORE updating _last_ready: clearing the marker first
        opens a race where the (now fast-cadence) readiness loop probes
        the new child ready, publishes True and records it, and this
        hook's delayed False write lands last — wedging the mirror at
        False with nothing left to notice the mismatch. With the write
        first, whatever order the two publishes land in, the next loop
        tick sees marker != probe and reconverges."""
        try:
            self.cd.set_node_status(False)
            self._last_ready = False
        except Exception:  # noqa: BLE001 — the readiness loop retries
            log.exception("post-restart readiness republish failed")
            self._last_ready = None  # force a republish on the next tick

    # -- loops --------------------------------------------------------------

    def _update_loop(self) -> None:
        """Membership changes -> peer config refresh (main.go:296-377).

        Member LOSS (the peer set shrank — a node died, a slice is
        going away) is handled with a settle window + latest-snapshot
        drain (MEMBER_LOSS_SETTLE_S) so a dying slice's burst of
        removals coalesces into ONE reconfigure instead of a restart
        storm; a failed update re-offers its snapshot to the latest-wins
        queue so the loop RETRIES instead of waiting for the next
        membership change that may never come (the dead peer is not
        coming back to nudge us)."""
        dns_mode = featuregates.enabled(featuregates.SliceDaemonsWithDNSNames)
        if dns_mode and not self.dns_supported:
            # Version gate (device_state.go:666-690 analog): fall back to
            # legacy IP mode on drivers without SIGUSR1 re-resolve.
            log.warning("accel driver predates DNS-stable rendezvous; "
                        "falling back to IP mode")
            dns_mode = False
        prev_ids: Optional[set] = None
        while not self._stop.is_set():
            try:
                node_set = self.cd.updates.get(timeout=0.2)
            except queue.Empty:
                continue
            try:
                peers = self.cd.slice_peers(node_set)
                ids = {i for i, _ip in peers}
                if prev_ids is not None and prev_ids - ids:
                    # Injection site: the member-loss reconfigure path
                    # fails (hosts rewrite EIO, restart refusal) — the
                    # re-offer below must retry it; surviving daemons
                    # must not crash-loop or silently keep dead peers.
                    FAULTS.check("cd.member_loss",
                                 node=self.ns.node_name,
                                 lost=sorted(prev_ids - ids))
                    self._stop.wait(self.MEMBER_LOSS_SETTLE_S)
                    node_set, peers, ids = self._drain_latest(
                        node_set, peers, ids)
                if dns_mode:
                    hosts_changed = update_hosts_file(
                        self.ns.hosts_file, peers)
                    names = [stable_name(i) for i, _ip in sorted(peers)]
                    cfg_changed = write_nodes_config(
                        self.nodes_path, names, self.ns.port)
                    if hosts_changed or cfg_changed:
                        self.process.signal(signal.SIGUSR1)
                else:
                    ips = [ip for _i, ip in sorted(peers)]
                    if write_nodes_config(self.nodes_path, ips, self.ns.port):
                        self.process.restart()
                prev_ids = ids
            except Exception:  # noqa: BLE001 — keep consuming updates,
                # and RETRY this snapshot: put it back unless a newer
                # one already superseded it (latest-wins), then back off
                # a tick so a hard failure cannot spin the loop.
                log.exception("membership update failed; retrying")
                try:
                    self.cd.updates.put_nowait(node_set)
                except queue.Full:
                    pass  # newer snapshot queued: it wins
                self._stop.wait(0.1)

    def _drain_latest(self, node_set, peers, ids):
        """Collapse whatever queued during the settle window to the
        newest membership snapshot (one burst, one reconfigure)."""
        while True:
            try:
                node_set = self.cd.updates.get_nowait()
            except queue.Empty:
                break
            peers = self.cd.slice_peers(node_set)
            ids = {i for i, _ip in peers}
        return node_set, peers, ids

    def _readiness_loop(self) -> None:
        """Probe the local daemon and mirror readiness into the per-node CD
        status (the PodManager startup-probe mirror, podmanager.go:35-120).

        Adaptive cadence, like a kubelet startupProbe with a small period
        vs. the steady-state readinessProbe: while NOT ready (startup, or
        after a watchdog restart) probe every 50ms so workload claims
        blocked on the readiness dance release at probe latency — a fixed
        1s tick was the single largest term of CD convergence (bench
        cd_convergence ~1.0s of which ~0.9s was waiting for this mirror).
        Once ready, 1s is plenty to notice a died daemon."""
        while not self._stop.wait(0.05 if not self._last_ready else 1.0):
            probed_pid = self.process.pid()
            ready = probe_ready(self.ns.port)
            if ready:
                # Unblocks held SIGUSR1s (process.py): the native daemon
                # answered a probe, so its signal handlers are installed.
                # Every tick, not on-change: a watchdog restart resets the
                # hold and the port coming back looks like no change. The
                # pid snapshot stops a probe answered by a since-restarted
                # child from confirming its replacement mid-exec.
                self.process.mark_ready(probed_pid)
            if ready != self._last_ready:
                try:
                    self.cd.set_node_status(ready)
                    self._last_ready = ready
                except Exception:  # noqa: BLE001 — retried next tick
                    log.exception("node status update failed")


def probe_ready(port: int, host: str = "127.0.0.1",
                timeout: float = 1.0) -> bool:
    """The `tpu-slice-daemon --check` / `nvidia-imex-ctl -q` analog."""
    try:
        with socket.create_connection((host, port), timeout=timeout) as s:
            s.settimeout(timeout)
            s.sendall(b"Q\n")
            return s.recv(128).startswith(b"READY")
    except OSError:
        return False


def run(argv=None) -> int:
    fs = flags()
    ns = fs.parse(argv)
    logger = setup_logging(ns.v, ns.log_json)
    apply_feature_gates(ns)
    fs.dump_config(ns, logger)
    debug.start_debug_signal_handlers()

    # Transient API-server failures (rolling upgrade, LB blips)
    # retry with jittered backoff instead of crash-looping the pod.
    client = RetryingApiClient(HttpApiClient(base_url=ns.kube_api_url))
    runner = DaemonRunner(client, ns)

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())

    runner.start()
    logger.info("cd daemon running (cd %s/%s)", ns.cd_namespace, ns.cd_name)
    stop.wait()
    runner.stop()
    return 0


def check(argv=None) -> int:
    port = int(os.environ.get("SLICE_DAEMON_PORT", str(DEFAULT_PORT)))
    if argv:
        for i, a in enumerate(argv):
            if a == "--port" and i + 1 < len(argv):
                port = int(argv[i + 1])
    ok = probe_ready(port)
    print("READY" if ok else "NOT_READY")
    return 0 if ok else 1


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] not in ("run", "check"):
        print("usage: tpu_dra.cddaemon.main run|check [flags]",
              file=sys.stderr)
        return 2
    return run(argv[1:]) if argv[0] == "run" else check(argv[1:])


if __name__ == "__main__":
    raise SystemExit(main())
