"""Retry-aware CD plugin driver.

Reference: cmd/compute-domain-kubelet-plugin/driver.go:39-98, 164-231 —
every claim is retried with backoff inside a 45s ``ErrorRetryMaxTimeout``
envelope (kubelet re-calls prepare until the pod leaves
ContainerCreating, so returning an error after 45s is safe and keeps the
retry loop responsive); ``permanentError`` short-circuits. Claims are
processed concurrently (``Serialize(false)``) because daemon-prepare and
channel-prepare are co-dependent: the channel claim's readiness wait can
only resolve once the daemon pod (whose own claim prepares through this
same server) is up.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from tpu_dra.cdplugin.computedomain import PermanentError, RetryableNotReady
from tpu_dra.cdplugin.device_state import DeviceState
from tpu_dra.infra.metrics import DefaultRegistry
from tpu_dra.k8s import ApiClient, RESOURCECLAIMS
from tpu_dra.k8s.client import NotFoundError
from tpu_dra.kubeletplugin.server import (
    Claim, DRAPluginServer, DriverCallbacks, PrepareResult, publish_resources,
)
from tpu_dra.cdplugin.deviceinfo import published_devices

log = logging.getLogger("tpu_dra.cdplugin")

ERROR_RETRY_MAX_TIMEOUT = 45.0  # driver.go:39-50

cd_prepare_seconds = DefaultRegistry.histogram(
    "tpu_dra_cd_claim_prepare_seconds",
    "CD plugin per-claim prepare latency (includes readiness wait)")


class CDDriver(DriverCallbacks):
    def __init__(self, *, state: DeviceState, client: ApiClient,
                 driver_name: str, node_name: str, slice_id: str,
                 plugin_dir: str, registry_dir: Optional[str] = None,
                 retry_timeout: float = ERROR_RETRY_MAX_TIMEOUT):
        self._state = state
        self._client = client
        self._driver_name = driver_name
        self._node_name = node_name
        self._slice_id = slice_id
        self._retry_timeout = retry_timeout
        self.server = DRAPluginServer(
            driver_name=driver_name, node_name=node_name, callbacks=self,
            plugin_dir=plugin_dir, registry_dir=registry_dir)

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        self.server.start()
        self.publish_resources()

    def shutdown(self) -> None:
        self.server.stop()

    def publish_resources(self) -> None:
        publish_resources(self._client, self._driver_name, self._node_name,
                          published_devices(self._slice_id))

    # -- DRA callbacks ------------------------------------------------------

    def prepare_claims(self, claims: List[Claim]) -> Dict[str, PrepareResult]:
        """Concurrent per-claim preparation (Serialize(false))."""
        results: Dict[str, PrepareResult] = {}
        threads = []
        lock = threading.Lock()

        def work(claim: Claim) -> None:
            res = self._prepare_with_retry(claim)
            with lock:
                results[claim.uid] = res

        for claim in claims:
            t = threading.Thread(target=work, args=(claim,),
                                 name=f"cd-prepare-{claim.uid[:8]}")
            t.start()
            threads.append(t)
        for t in threads:
            t.join()
        return results

    def unprepare_claims(self, claims: List[Claim]) -> Dict[str, str]:
        errors: Dict[str, str] = {}
        for claim in claims:
            err = self._state.unprepare(claim.uid)
            errors[claim.uid] = err or ""
        return errors

    # -- retry envelope -----------------------------------------------------

    def _prepare_with_retry(self, claim: Claim) -> PrepareResult:
        """Retry ladder: the CD-daemon rate-limiter preset (5ms–6s expo
        with 0.5 relative jitter — workqueue.go DefaultCDDaemonRateLimiter)
        inside the retry envelope. The fast base matters: the CD readiness
        dance usually converges in hundreds of ms (daemon pod start +
        status registration), and a coarse 250ms ladder was the dominant
        term of the whole CD claim-to-ready time (bench cd_convergence
        1.76s, ~1.75s of which was backoff sleep)."""
        from tpu_dra.infra.workqueue import default_cd_daemon_rate_limiter

        t0 = time.monotonic()
        deadline = t0 + self._retry_timeout
        limiter = default_cd_daemon_rate_limiter()
        attempt = 0
        # Per-CD change generation (learned from the first retryable
        # failure): `seen` from the PREVIOUS wait, so a CD event landing
        # while an attempt runs makes the next wait return immediately.
        seen = None
        cd_uid = ""
        while True:
            attempt += 1
            try:
                obj = self._fetch_claim(claim)
                result = self._state.prepare(obj)
                cd_prepare_seconds.observe(time.monotonic() - t0)
                return result
            except PermanentError as e:
                return PrepareResult(error=f"permanent: {e}")
            except RetryableNotReady as e:
                now = time.monotonic()
                if now >= deadline:
                    return PrepareResult(
                        error=f"retry budget exhausted after {attempt} "
                              f"attempts: {e}")
                log.debug("claim %s not ready (attempt %d): %s",
                          claim.uid, attempt, e)
                if getattr(e, "cd_uid", "") and e.cd_uid != cd_uid:
                    cd_uid, seen = e.cd_uid, None
                # Event-driven wake: readiness converges at watch latency;
                # the ladder delay is only the no-event fallback, clipped
                # to the remaining budget (a 6s ladder rung must not
                # forfeit a deadline an event would have beaten).
                delay = min(limiter.when(0), deadline - now)
                seen = self._state.wait_cd_change(cd_uid, seen, delay)
            except Exception as e:  # noqa: BLE001 — unexpected: report
                return PrepareResult(error=f"prepare: {e}")

    def _fetch_claim(self, claim: Claim) -> Dict:
        try:
            obj = self._client.get(RESOURCECLAIMS, claim.name,
                                   claim.namespace)
        except NotFoundError as e:
            raise PermanentError(
                f"resourceclaim {claim.namespace}/{claim.name} not found"
            ) from e
        if obj["metadata"].get("uid") != claim.uid:
            raise PermanentError(
                f"claim UID mismatch for {claim.namespace}/{claim.name}")
        return obj
