"""Node-side ComputeDomain operations for the CD kubelet plugin.

Reference: cmd/compute-domain-kubelet-plugin/computedomain.go —
namespace assertion (:264-278, permanent error), node labeling (:280-332 —
*this* is what pulls the per-CD DaemonSet pod onto the node), readiness
assertion (:237-262, retried inside the prepare envelope), and the daemon
config-dir lifecycle (:131-235, :352-407).
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
import time
from typing import Dict, List, Optional, Tuple

from tpu_dra.api import types as apitypes
from tpu_dra.cddaemon.dnsnames import stable_name
from tpu_dra.k8s import ApiClient, COMPUTEDOMAINS, NODES
from tpu_dra.k8s.client import NotFoundError
from tpu_dra.k8s.informer import Informer, uid_index

log = logging.getLogger("tpu_dra.cdplugin")

UID_INDEX = "uid"

# Default port for the JAX coordinator service on the index-0 worker
# (jax.distributed.initialize convention).
COORDINATOR_PORT = 8476


class PermanentError(Exception):
    """Not retryable inside the prepare envelope (driver.go permanentError)."""


class ComputeDomainManager:
    def __init__(self, client: ApiClient, *, node_name: str,
                 driver_plugin_dir: str):
        self._client = client
        self._node_name = node_name
        self._domains_root = os.path.join(driver_plugin_dir, "domains")
        self.informer = Informer(client, COMPUTEDOMAINS)
        self.informer.add_indexer(UID_INDEX, uid_index)
        # Change signal for readiness waiters (wait_for_change): a CD
        # add/update bumps that CD's generation and wakes sleepers, so
        # the readiness dance converges at watch-event latency instead of
        # the next poll tick. Generations are PER CD UID: a node with a
        # prepare blocked on CD X must not pay a retry attempt (claim
        # fetch + prepare pass) for every unrelated CD churning status.
        self._change_cond = threading.Condition()
        self._change_gens: Dict[str, int] = {}
        self._membership_ts: Dict[str, float] = {}
        self._last_membership: Dict[str, object] = {}
        self.informer.on_add(lambda obj: self._bump(obj))
        self.informer.on_update(lambda old, new: self._bump(new, old=old))
        # Deleted CDs drop their generation entry (bounded map in a
        # node-lifetime daemon) — with a final bump so a waiter blocked
        # on a CD that just vanished re-checks and fails fast.
        self.informer.on_delete(lambda obj: self._bump(obj, drop=True))

    def _bump(self, obj: Dict, drop: bool = False,
              old: Optional[Dict] = None) -> None:
        uid = (obj.get("metadata") or {}).get("uid", "")
        with self._change_cond:
            if drop:
                self._change_gens.pop(uid, None)
                self._membership_ts.pop(uid, None)
                self._last_membership.pop(uid, None)
            else:
                self._change_gens[uid] = self._change_gens.get(uid, 0) + 1
                # Membership compared against OUR OWN last-seen value, not
                # the handler's `old`: watch relists replay adds for every
                # cached object (old=None), and stamping on those would
                # re-arm the settle grace cluster-wide on each reconnect.
                m = self._membership(obj)
                if uid not in self._last_membership \
                        or m != self._last_membership[uid]:
                    # Membership progress (a node registered / flipped):
                    # timestamped so the settle grace can distinguish "the
                    # domain is still forming" from "nothing is coming".
                    self._last_membership[uid] = m
                    self._membership_ts[uid] = time.monotonic()
            self._change_cond.notify_all()

    @staticmethod
    def _membership(obj: Optional[Dict]):
        if not obj:
            return None
        return sorted((n.get("name", ""), n.get("status", ""))
                      for n in (obj.get("status") or {}).get("nodes") or [])

    def last_membership_change(self, cd_uid: str, default: float = 0.0
                               ) -> float:
        with self._change_cond:
            return self._membership_ts.get(cd_uid, default)

    def change_gen(self, cd_uid: str) -> int:
        with self._change_cond:
            return self._change_gens.get(cd_uid, 0)

    def wait_for_change(self, cd_uid: str, seen_gen: Optional[int],
                        timeout: float) -> int:
        """Block until an event for THIS CD lands after `seen_gen` (or
        timeout). Returns the current generation. Capture change_gen()
        BEFORE checking state: an event between check and wait then
        returns immediately instead of being missed. seen_gen=None (uid
        not known before the first failure) waits from the CURRENT
        generation — the only rung where an event landing mid-attempt can
        be slept through, bounded by the ladder's 5ms first delay.

        Loops on the shared condition: notify_all fires for EVERY CD's
        events, and a spurious wake must not be reported as a change —
        the caller would pay a full retry attempt per unrelated event."""
        deadline = time.monotonic() + timeout
        with self._change_cond:
            if seen_gen is None:
                seen_gen = self._change_gens.get(cd_uid, 0)
            while self._change_gens.get(cd_uid, 0) == seen_gen:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._change_cond.wait(remaining)
            return self._change_gens.get(cd_uid, 0)

    def start(self) -> None:
        self.informer.start()
        self.informer.wait_for_sync()

    def stop(self) -> None:
        self.informer.stop()

    # -- lookups ------------------------------------------------------------

    def get_by_uid(self, uid: str) -> Optional[Dict]:
        hits = self.informer.get_by_index(UID_INDEX, uid)
        if hits:
            return hits[0]
        # Fall back to a live list: the claim may arrive before the watch.
        for cd in self._client.list(COMPUTEDOMAINS):
            if cd["metadata"].get("uid") == uid:
                self.informer.update_cache(cd)
                return cd
        return None

    # -- assertions (computedomain.go:237-278) ------------------------------

    def assert_namespace(self, cd_uid: str, claim_namespace: str) -> Dict:
        """The workload claim must live in the CD's namespace; a mismatch is
        permanent — retrying cannot fix a cross-namespace reference."""
        cd = self.get_by_uid(cd_uid)
        if cd is None:
            raise RetryableNotReady(f"computedomain {cd_uid} not found (yet)",
                                    cd_uid=cd_uid)
        if cd["metadata"].get("namespace") != claim_namespace:
            raise PermanentError(
                f"claim namespace {claim_namespace!r} does not match "
                f"computedomain namespace {cd['metadata'].get('namespace')!r}")
        return cd

    def assert_node_ready(self, cd_uid: str,
                          require_domain_ready: bool = True) -> Dict:
        """Block the prepare until the CD reports *this* node Ready — and,
        while require_domain_ready, the domain itself Ready (the
        controller flips that only once the expected membership is ready,
        controller._update_readiness).

        The domain-level gate matters here where it doesn't in the
        reference: its channel device is a composition-independent
        char-dev, while our workload env snapshots the CD's node list
        (TPU_WORKER_HOSTNAMES, MEGASCALE_* topology) — preparing as soon
        as the local daemon was up could inject a peer list missing nodes
        that hadn't registered yet (seen as a missing-megascale-env race
        in the multislice e2e once convergence got fast).

        The caller BOUNDS the strict gate (device_state's settle grace):
        daemons are summoned by channel prepares' own node labels, so a
        workload running fewer pods than spec.numNodes would never flip
        the domain Ready — an unconditional gate would wedge it in
        ContainerCreating forever. After the grace the prepare degrades
        to this-node-Ready with a best-effort env snapshot (the
        pre-domain-gate behavior).
        """
        cd = self.get_by_uid(cd_uid)
        if cd is None:
            raise RetryableNotReady(f"computedomain {cd_uid} not found",
                                    cd_uid=cd_uid)
        nodes = (cd.get("status") or {}).get("nodes") or []
        mine = next((n for n in nodes
                     if n.get("name") == self._node_name), None)
        if mine is None:
            raise RetryableNotReady(
                f"node {self._node_name} not yet registered in cd {cd_uid}",
                cd_uid=cd_uid)
        if mine.get("status") != apitypes.COMPUTE_DOMAIN_STATUS_READY:
            raise RetryableNotReady(
                f"node {self._node_name} not Ready in cd {cd_uid}",
                cd_uid=cd_uid)
        if (require_domain_ready
                and (cd.get("status") or {}).get("status")
                != apitypes.COMPUTE_DOMAIN_STATUS_READY):
            raise RetryableNotReady(
                f"cd {cd_uid} membership still settling (domain not Ready)",
                cd_uid=cd_uid)
        return cd

    # -- node labeling (computedomain.go:280-332) ---------------------------

    def add_node_label(self, cd_uid: str) -> None:
        node = self._client.get(NODES, self._node_name)
        labels = node["metadata"].get("labels") or {}
        current = labels.get(apitypes.COMPUTE_DOMAIN_LABEL_KEY)
        if current == cd_uid:
            return
        if current and self.get_by_uid(current) is not None:
            # One CD at a time per node: TPU slices are exclusive hardware.
            raise PermanentError(
                f"node {self._node_name} already belongs to computedomain "
                f"{current}")
        self._client.patch(NODES, self._node_name, {"metadata": {"labels": {
            apitypes.COMPUTE_DOMAIN_LABEL_KEY: cd_uid}}})

    def remove_node_label(self, cd_uid: str) -> None:
        try:
            node = self._client.get(NODES, self._node_name)
        except NotFoundError:
            return
        labels = node["metadata"].get("labels") or {}
        if labels.get(apitypes.COMPUTE_DOMAIN_LABEL_KEY) != cd_uid:
            return
        self._client.patch(NODES, self._node_name, {"metadata": {"labels": {
            apitypes.COMPUTE_DOMAIN_LABEL_KEY: None}}})

    # -- rendezvous env (the IMEX-channel injection analog) -----------------

    def workload_env(self, cd: Dict, channel_ids: List[int],
                     allocation_mode: str) -> Dict[str, str]:
        """Env a workload container needs to run collectives over the
        provisioned slice: worker identity, peer list, coordinator, and
        multi-slice (DCN) topology for heterogeneous domains."""
        nodes = (cd.get("status") or {}).get("nodes") or []
        mine = next(n for n in nodes if n.get("name") == self._node_name)
        my_slice = mine.get("sliceID", "")
        group = sorted(((n.get("index", 0), n) for n in nodes
                        if n.get("sliceID", "") == my_slice),
                       key=lambda pair: pair[0])
        peers = [stable_name(i) for i, _n in group]
        coordinator = next((n for i, n in group if i == 0), None)
        slice_ids = sorted({n.get("sliceID", "") for n in nodes})
        # Global coordinator for cross-slice (megascale) rendezvous: every
        # slice must agree on ONE address — the index-0 member of the first
        # slice in sorted order, not the per-slice coordinator.
        global_coord = next(
            (n for n in sorted(nodes, key=lambda n: (n.get("sliceID", ""),
                                                     n.get("index", 0)))
             if n.get("sliceID", "") == slice_ids[0]
             and n.get("index", 0) == 0), None) if slice_ids else None

        env = {
            "COMPUTE_DOMAIN_UUID": cd["metadata"].get("uid", ""),
            "COMPUTE_DOMAIN_NAME": cd["metadata"].get("name", ""),
            "COMPUTE_DOMAIN_NAMESPACE": cd["metadata"].get("namespace", ""),
            "TPU_SLICE_ID": my_slice,
            "TPU_WORKER_ID": str(mine.get("index", 0)),
            "TPU_WORKER_HOSTNAMES": ",".join(peers),
            "TPU_PROCESS_COUNT": str(len(group)),
        }
        if coordinator is not None:
            env["TPU_COORDINATOR_ADDRESS"] = (
                f"{coordinator.get('ipAddress', '')}:{COORDINATOR_PORT}")
        # Allocation -> mesh handoff (SURVEY §17): surface the
        # controller-stamped slice-alignment verdict (status.topology,
        # cdcontroller) so a workload's mesh builder can tell a
        # slice-aligned domain (ICI end to end) from one stitched
        # across slices (DCN hops) without an API-server round trip.
        topo = (cd.get("status") or {}).get("topology") or {}
        if topo:
            env["TPU_CD_SLICES"] = str(topo.get("slices", 1))
            env["TPU_CD_SLICE_ALIGNED"] = (
                "true" if topo.get("sliceAligned") else "false")
        if len(slice_ids) > 1:
            # Heterogeneous domain: slices talk over DCN (megascale-style).
            env["MEGASCALE_NUM_SLICES"] = str(len(slice_ids))
            env["MEGASCALE_SLICE_ID"] = str(slice_ids.index(my_slice))
            if global_coord is not None:
                env["MEGASCALE_COORDINATOR_ADDRESS"] = (
                    f"{global_coord.get('ipAddress', '')}:{COORDINATOR_PORT}")
        if allocation_mode == apitypes.ALLOCATION_MODE_ALL:
            env["TPU_CD_CHANNELS"] = "all"
        else:
            env["TPU_CD_CHANNELS"] = ",".join(str(c) for c in channel_ids)
        return env

    # -- daemon config dirs (computedomain.go:131-235) ----------------------

    def domain_dir(self, cd_uid: str) -> str:
        return os.path.join(self._domains_root, cd_uid)

    def prepare_daemon_dir(self, cd: Dict, slice_id: str) -> str:
        """Per-CD config dir handed to the daemon pod (the /imexd mount)."""
        path = self.domain_dir(cd["metadata"]["uid"])
        os.makedirs(path, exist_ok=True)
        with open(os.path.join(path, "domain.env"), "w") as f:
            f.write(f"COMPUTE_DOMAIN_UUID={cd['metadata'].get('uid', '')}\n"
                    f"COMPUTE_DOMAIN_NAME={cd['metadata'].get('name', '')}\n"
                    f"COMPUTE_DOMAIN_NAMESPACE="
                    f"{cd['metadata'].get('namespace', '')}\n"
                    f"TPU_SLICE_ID={slice_id}\n")
        return path

    def gc_domain_dirs(self) -> List[str]:
        """Remove config dirs of CDs that no longer exist (the plugin-side
        dir GC, computedomain.go:352-407). Returns removed uids."""
        removed = []
        if not os.path.isdir(self._domains_root):
            return removed
        for uid in os.listdir(self._domains_root):
            if self.get_by_uid(uid) is None:
                shutil.rmtree(os.path.join(self._domains_root, uid),
                              ignore_errors=True)
                removed.append(uid)
        return removed


class RetryableNotReady(Exception):
    """Retried by the prepare envelope until the 45s budget runs out.
    Carries the CD uid (when known) so the retry can sleep on that CD's
    change signal instead of the global ladder."""

    def __init__(self, msg: str, cd_uid: str = ""):
        super().__init__(msg)
        self.cd_uid = cd_uid
