"""ComputeDomain kubelet plugin entrypoint.

Reference: cmd/compute-domain-kubelet-plugin/main.go — env-mirrored flags,
slice-identity discovery at startup (the cliqueID discovery analog),
driver + GC construction, serve until signalled.

Run: ``python -m tpu_dra.cdplugin.main [flags]``
"""

from __future__ import annotations

import signal
import threading

from tpu_dra.api.types import COMPUTE_DOMAIN_DRIVER_NAME
from tpu_dra.cddaemon.main import discover_slice_id
from tpu_dra.cdi.handler import CDIHandler
from tpu_dra.cdplugin.cleanup import CheckpointCleanup
from tpu_dra.cdplugin.computedomain import ComputeDomainManager
from tpu_dra.cdplugin.device_state import DeviceState
from tpu_dra.cdplugin.driver import CDDriver
from tpu_dra.infra import debug
from tpu_dra.infra.flags import (
    Flag, FlagSet, apply_feature_gates, feature_gate_flag, logging_flags,
    setup_logging,
)
from tpu_dra.infra.metrics import MetricsServer
from tpu_dra.k8s.client import HttpApiClient, RetryingApiClient
from tpu_dra.native.tpuinfo import get_backend
from tpu_dra.tpuplugin.checkpoint import CheckpointManager

CDI_VENDOR_CD = "k8s.compute-domain.tpu.dev"


def flags() -> FlagSet:
    return FlagSet("tpu-cd-kubelet-plugin", [
        Flag("node-name", "NODE_NAME", required=True,
             help="name of the node this plugin runs on"),
        Flag("cdi-root", "CDI_ROOT", default="/var/run/cdi",
             help="directory for CDI spec files"),
        Flag("plugin-dir", "PLUGIN_DIR",
             default=f"/var/lib/kubelet/plugins/{COMPUTE_DOMAIN_DRIVER_NAME}",
             help="kubelet plugin dir (dra.sock, checkpoint, domains/)"),
        Flag("registry-dir", "REGISTRY_DIR",
             default="/var/lib/kubelet/plugins_registry",
             help="kubelet plugin watcher registry dir"),
        Flag("kube-api-url", "KUBE_API_URL", default=None,
             help="API server URL (default: in-cluster config)"),
        Flag("healthcheck-port", "HEALTHCHECK_PORT", default=0, type=int,
             help="metrics/health HTTP port (0 = disabled)"),
        Flag("gc-interval-seconds", "GC_INTERVAL_SECONDS", default=600,
             type=int, help="checkpoint/domain-dir GC period"),
        feature_gate_flag(),
        *logging_flags(),
    ])


def main(argv=None) -> int:
    fs = flags()
    ns = fs.parse(argv)
    logger = setup_logging(ns.v, ns.log_json)
    apply_feature_gates(ns)
    fs.dump_config(ns, logger)
    debug.start_debug_signal_handlers()

    backend = get_backend()
    slice_id = discover_slice_id(backend)
    # Transient API-server failures (rolling upgrade, LB blips)
    # retry with jittered backoff instead of crash-looping the pod.
    client = RetryingApiClient(HttpApiClient(base_url=ns.kube_api_url))
    cd_manager = ComputeDomainManager(
        client, node_name=ns.node_name, driver_plugin_dir=ns.plugin_dir)
    cd_manager.start()

    cdi = CDIHandler(ns.cdi_root, vendor=CDI_VENDOR_CD)
    state = DeviceState(
        cd_manager=cd_manager, cdi=cdi,
        checkpoints=CheckpointManager(ns.plugin_dir),
        driver_name=COMPUTE_DOMAIN_DRIVER_NAME, node_name=ns.node_name,
        slice_id=slice_id)
    driver = CDDriver(
        state=state, client=client,
        driver_name=COMPUTE_DOMAIN_DRIVER_NAME, node_name=ns.node_name,
        slice_id=slice_id, plugin_dir=ns.plugin_dir,
        registry_dir=ns.registry_dir)
    gc = CheckpointCleanup(client=client, state=state, cd_manager=cd_manager,
                           interval=ns.gc_interval_seconds)

    metrics_srv = None
    if ns.healthcheck_port:
        from tpu_dra.kubeletplugin.server import self_probe
        metrics_srv = MetricsServer(
            addr="0.0.0.0", port=ns.healthcheck_port,  # noqa: S104
            health_probe=lambda: self_probe(driver.server))
        metrics_srv.start()

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())

    driver.start()
    gc.start()
    logger.info("cd kubelet plugin serving on %s (slice %r)",
                driver.server.dra_socket, slice_id)
    stop.wait()
    gc.stop()
    driver.shutdown()
    cd_manager.stop()
    if metrics_srv:
        metrics_srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
