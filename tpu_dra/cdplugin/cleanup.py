"""CD plugin checkpoint + domain-dir garbage collection.

Reference: cmd/compute-domain-kubelet-plugin/cleanup.go:41-271 — periodic
GC of ``PrepareStarted`` (partially prepared) claims whose ResourceClaim no
longer exists in the API server (compared by name+UID so a recreated
same-name claim is not collected), plus the per-CD config-dir sweep.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

from tpu_dra.cdplugin.computedomain import ComputeDomainManager
from tpu_dra.cdplugin.device_state import DeviceState
from tpu_dra.k8s import ApiClient, RESOURCECLAIMS
from tpu_dra.k8s.client import NotFoundError
from tpu_dra.tpuplugin.checkpoint import PREPARE_STARTED

log = logging.getLogger("tpu_dra.cdplugin.cleanup")


class CheckpointCleanup:
    def __init__(self, *, client: ApiClient, state: DeviceState,
                 cd_manager: ComputeDomainManager,
                 interval: float = 600.0):
        self._client = client
        self._state = state
        self._cd = cd_manager
        self._interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="cd-ckpt-gc")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.sweep()
            except Exception:  # noqa: BLE001 — GC must not die
                log.exception("checkpoint GC failed")

    def sweep(self) -> int:
        """Collect abandoned PrepareStarted claims; returns count."""
        collected = 0
        snapshot = self._state.checkpoint_snapshot()
        # Lazily-built uid index over ONE cluster-wide LIST per sweep:
        # only legacy records need it, and N of them must not cost N lists.
        uid_index: Optional[Dict[str, Dict]] = None
        for uid, prepared in list(snapshot.claims.items()):
            if prepared.state != PREPARE_STARTED:
                continue
            if not prepared.name:
                # Legacy (V1-era) record without claim identity: backfill
                # it from the API server by UID (cd device_state.go:231-254
                # analog). Found -> record becomes collectible on a later
                # sweep once the claim disappears; not found anywhere ->
                # the claim is gone and the record is abandoned now.
                if uid_index is None:
                    uid_index = {c["metadata"].get("uid", ""): c
                                 for c in self._client.list(RESOURCECLAIMS)}
                match = uid_index.get(uid)
                if match is not None:
                    if self._state.backfill_claim_identity(
                            uid, match["metadata"]["name"],
                            match["metadata"].get("namespace", "")):
                        log.info("backfilled legacy checkpoint identity "
                                 "for claim %s (%s/%s)", uid,
                                 match["metadata"].get("namespace", ""),
                                 match["metadata"]["name"])
                    # else: record unprepared between snapshot and now —
                    # nothing was written, nothing to collect.
                    continue  # claim still exists: kubelet will retry
                if self._state.drop_claim(uid):
                    log.info("GC abandoned legacy claim %s", uid)
                    collected += 1
                continue
            try:
                obj = self._client.get(RESOURCECLAIMS, prepared.name,
                                       prepared.namespace)
                if obj["metadata"].get("uid") == uid:
                    continue  # claim still exists: kubelet will retry
            except NotFoundError:
                pass
            if self._state.drop_claim(uid):
                log.info("GC abandoned PrepareStarted claim %s (%s/%s)",
                         uid, prepared.namespace, prepared.name)
                collected += 1
        self._cd.gc_domain_dirs()
        return collected
