"""ComputeDomain kubelet plugin (reference: cmd/compute-domain-kubelet-plugin).

Node-side half of the ComputeDomain machinery: advertises synthetic
``channel`` + ``daemon`` devices, and on claim prepare performs the
readiness dance — label the node (pulling a slice-daemon pod here), wait
for the CD to report this node Ready, then inject the slice rendezvous env
(worker id, peer hostnames, coordinator address) into the workload
container via CDI.
"""
