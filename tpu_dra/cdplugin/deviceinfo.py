"""Synthetic ComputeDomain devices.

Reference: cmd/compute-domain-kubelet-plugin/{nvlib.go:159-185,
deviceinfo.go:26-78, driver.go:104-119} — the CD plugin advertises devices
that are not hardware: up to 2048 per-node ``channel`` devices (the
claimable handle that gates workload readiness) and one ``daemon`` device
(claimed by the slice-daemon pod itself). Only channel 0 and the daemon
are published in the ResourceSlice — channels are a cluster-granted
resource, not per-node inventory.

On TPU the channel carries no char-dev (SURVEY §2.9): preparing it injects
rendezvous *env*; the device exists so DRA scheduling and readiness gating
work identically.
"""

from __future__ import annotations

from typing import Dict, List

CHANNEL_COUNT = 2048  # getImexChannelCount analog (nvlib.go:260-263)

DEVICE_TYPE_CHANNEL = "channel"
DEVICE_TYPE_DAEMON = "daemon"


def channel_device_name(channel_id: int) -> str:
    return f"channel-{channel_id}"


DAEMON_DEVICE_NAME = "daemon"


def published_devices(slice_id: str) -> List[Dict]:
    """resourceapi devices for the ResourceSlice: channel-0 + daemon."""
    return [
        {
            "name": channel_device_name(0),
            "attributes": {
                "type": {"string": DEVICE_TYPE_CHANNEL},
                "id": {"int": 0},
                "sliceID": {"string": slice_id},
            },
            "capacity": {},
        },
        {
            "name": DAEMON_DEVICE_NAME,
            "attributes": {
                "type": {"string": DEVICE_TYPE_DAEMON},
                "sliceID": {"string": slice_id},
            },
            "capacity": {},
        },
    ]


def parse_channel_id(device_name: str) -> int:
    """channel-N -> N; raises ValueError for non-channel devices."""
    if not device_name.startswith("channel-"):
        raise ValueError(f"not a channel device: {device_name!r}")
    return int(device_name.split("-", 1)[1])
