"""CD plugin device state: checkpointed channel/daemon prepare.

Reference: cmd/compute-domain-kubelet-plugin/device_state.go —
channel prepare (:456-504): namespace assert (permanent), node label (pulls
the daemon pod here), block until this node is Ready in the CD status, then
inject rendezvous env via CDI (char-devs on NVIDIA, env on TPU — SURVEY
§2.9). Daemon prepare (:506-563): per-CD config dir + identity env.
Channel exclusivity (:625-664): checkpoint-based node-local assertion that
a channel is not already held by a different completed claim.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from tpu_dra.api import scheme as apischeme
from tpu_dra.api import types as apitypes
from tpu_dra.cdi.handler import CDIHandler
from tpu_dra.cdplugin import deviceinfo
from tpu_dra.infra.trace import (
    ENV_TRACEPARENT, TRACEPARENT_ANNOTATION, TRACER,
)
from tpu_dra.cdplugin.computedomain import (
    ComputeDomainManager, PermanentError, RetryableNotReady,
)
from tpu_dra.kubeletplugin.server import PreparedDevice, PrepareResult
from tpu_dra.tpuplugin.checkpoint import (
    Checkpoint, CheckpointManager, PREPARE_COMPLETED, PREPARE_STARTED,
    PreparedClaim,
)

log = logging.getLogger("tpu_dra.cdplugin")


class DeviceState:
    def __init__(self, *, cd_manager: ComputeDomainManager, cdi: CDIHandler,
                 checkpoints: CheckpointManager, driver_name: str,
                 node_name: str, slice_id: str):
        self._cd = cd_manager
        self._cdi = cdi
        self._ckpt_mgr = checkpoints
        self._driver_name = driver_name
        self._node_name = node_name
        self._slice_id = slice_id
        self._lock = threading.Lock()
        # Serializes every checkpoint-read→label-write sequence: unprepare()
        # end-to-end, and prepare's checkpoint-record + add_node_label pair.
        # still_used is computed from the checkpoint and then acted on
        # outside self._lock (label removal is a network call); without this
        # mutex, (a) two concurrent unprepares of the last two channel
        # claims of one CD can each see the other still checkpointed, both
        # skip remove_node_label, and the label leaks with no kubelet retry
        # left; (b) an in-flight unprepare that computed still_used == {}
        # can remove the label *after* a concurrent prepare checkpointed a
        # new claim and added it. One node-global lock is deliberate — the
        # reference holds a per-node flock across entire prepare/unprepare
        # calls for the same reason (gpu driver.go:49-116); the held section
        # here is one checkpoint read plus at most one label API call, and a
        # hung API server stalls kubelet's envelope either way. Ordering:
        # _label_lock is always taken outside self._lock.
        self._label_lock = threading.Lock()
        # (first, last) attempt timestamps per claim (the domain-settle
        # grace in _prepare_channel); in-memory only — a restart just
        # re-grants the grace, which is the safe direction. Entries drop
        # on success and on unprepare.
        self._first_attempt: Dict[str, tuple] = {}
        self._checkpoint = self._ckpt_mgr.load_or_init()

    # ------------------------------------------------------------------
    # Prepare
    # ------------------------------------------------------------------

    # How long a channel prepare insists on DOMAIN-level Ready before
    # degrading to this-node-Ready with a best-effort env snapshot (see
    # ComputeDomainManager.assert_node_ready). Generous vs the measured
    # ~0.1s convergence; a fraction of kubelet's retry horizon.
    DOMAIN_SETTLE_GRACE_S = 10.0
    # Attempts further apart than this start a NEW grace window (a fresh
    # kubelet envelope after a long gap re-arms the strict gate; within
    # one envelope the retry ladder never pauses longer than ~7.5s).
    ATTEMPT_GAP_RESET_S = 15.0

    def wait_cd_change(self, cd_uid: str, seen_gen, timeout: float) -> int:
        """See ComputeDomainManager.wait_for_change (event-driven retry
        wake, keyed by CD uid)."""
        return self._cd.wait_for_change(cd_uid, seen_gen, timeout)

    def prepare(self, claim: Dict) -> PrepareResult:
        """May raise RetryableNotReady (the driver retries inside its 45s
        envelope) or PermanentError (short-circuits)."""
        uid = claim["metadata"]["uid"]
        with self._lock:
            existing = self._checkpoint.claims.get(uid)
            if existing is not None and existing.state == PREPARE_COMPLETED \
                    and self._cdi.claim_spec_exists(uid):
                # Same gate as tpuplugin's fast path (drmc crash
                # enumeration, SURVEY §13): a crash can persist the
                # terminal checkpoint sync yet lose the claim spec's
                # never-synced rename — vouching for the vanished file
                # would fail container creation forever. Fall through
                # and re-run the prepare (idempotent) to rewrite it.
                return PrepareResult(devices=[
                    self._rehydrate(r) for r in existing.devices])

        allocation = ((claim.get("status") or {}).get("allocation") or {})
        results = [r for r in (allocation.get("devices") or {})
                   .get("results", [])
                   if r.get("driver") == self._driver_name]
        if not results:
            raise PermanentError("claim has no allocation results for this driver")

        config = self._decode_config(allocation, results)
        if isinstance(config, apitypes.ComputeDomainChannelConfig):
            return self._prepare_channel(claim, results, config)
        if isinstance(config, apitypes.ComputeDomainDaemonConfig):
            return self._prepare_daemon(claim, results, config)
        raise PermanentError(
            f"unsupported config kind {type(config).__name__}")

    def _decode_config(self, allocation: Dict, results: List[Dict]):
        entries = (allocation.get("devices") or {}).get("config", []) or []
        for entry in entries:
            opaque = entry.get("opaque") or {}
            if opaque.get("driver") != self._driver_name:
                continue
            try:
                cfg = apischeme.StrictDecoder.decode(
                    opaque.get("parameters", {}))
            except apischeme.DecodeError as e:
                raise PermanentError(f"invalid opaque config: {e}") from e
            cfg.normalize()
            cfg.validate()
            return cfg
        raise PermanentError(
            "claim carries no ComputeDomain opaque config for this driver")

    # -- channel (workload) claims ------------------------------------------

    def _prepare_channel(self, claim: Dict, results: List[Dict],
                         config: apitypes.ComputeDomainChannelConfig
                         ) -> PrepareResult:
        uid = claim["metadata"]["uid"]
        ns = claim["metadata"].get("namespace", "")
        cd = self._cd.assert_namespace(config.domain_id, ns)

        channel_ids = [deviceinfo.parse_channel_id(r["device"])
                       for r in results]
        # _label_lock spans checkpoint-record + add_node_label so a
        # concurrent unprepare of this CD's last old claim cannot compute
        # still_used == {} before this claim is recorded and then strip the
        # label after we add it (see __init__). The long readiness wait
        # below is NOT under the lock.
        with self._label_lock:
            with self._lock:
                self._assert_channels_free_locked(uid, channel_ids)
                # Record intent before side effects (crash consistency).
                self._checkpoint.claims[uid] = PreparedClaim(
                    uid=uid, state=PREPARE_STARTED,
                    name=claim["metadata"].get("name", ""), namespace=ns)
                self._checkpoint.claims[uid].devices = [{
                    "type": deviceinfo.DEVICE_TYPE_CHANNEL,
                    "device": r["device"],
                    "request": r.get("request", ""),
                    "channel_id": deviceinfo.parse_channel_id(r["device"]),
                    "cd_uid": config.domain_id,
                    "pool": self._node_name,
                    "cdi_ids": [self._cdi.get_claim_device(uid)],
                } for r in results]
                # Transient mid-prepare record: side slot (the primary
                # keeps only settled state for downgrade readers — see
                # tpuplugin/checkpoint.py CheckpointManager).
                self._ckpt_mgr.store(self._checkpoint, intent=True)

            # Label first (this is what summons the daemon pod), then wait.
            self._cd.add_node_label(config.domain_id)
        # Strict domain-Ready gate while the domain is SETTLING, so a
        # workload smaller than spec.numNodes (whose labels will never
        # summon enough daemons to flip the domain) degrades to the
        # node-Ready gate instead of wedging (assert_node_ready doc).
        # "Settling" = within the grace of this claim's first attempt OR
        # of the CD's last membership change: registrations trickling in
        # on a slow cluster keep re-arming the gate (degrading mid-trickle
        # would snapshot a partial peer env — the flake this fixes), while
        # a quiet domain that simply isn't growing degrades after one
        # grace. A long gap between attempts also re-arms (a fresh kubelet
        # envelope after the first one exhausted gets the strict gate
        # back).
        now = time.monotonic()
        with self._lock:
            # Under self._lock: prepare runs on gRPC handler threads, and
            # the (first, last) read-modify-write is not atomic without
            # it. Claims that never succeed and are never unprepared
            # would otherwise pin entries for the daemon's lifetime —
            # prune anything idle past the gap-reset horizon (its grace
            # would restart anyway).
            stale = [u for u, (_, l) in self._first_attempt.items()
                     if now - l > self.ATTEMPT_GAP_RESET_S and u != uid]
            for u in stale:
                del self._first_attempt[u]
            first, last = self._first_attempt.get(uid, (now, now))
            if now - last > self.ATTEMPT_GAP_RESET_S:
                first = now
            self._first_attempt[uid] = (first, now)
        settled_ref = max(first,
                          self._cd.last_membership_change(config.domain_id,
                                                          default=first))
        strict = (now - settled_ref) < self.DOMAIN_SETTLE_GRACE_S
        cd = self._cd.assert_node_ready(
            config.domain_id, require_domain_ready=strict)  # raises retryable

        env = self._cd.workload_env(cd, channel_ids, config.allocation_mode)
        # Trace continuation (SURVEY §19): a scheduler-allocated CD
        # channel claim carries a traceparent annotation; the cd.prepare
        # span rides into the workload env so the CD daemon's readiness
        # mirror closes the loop on the same trace.
        span = TRACER.begin(
            "cd.prepare", root=True,
            traceparent=(claim["metadata"].get("annotations") or {}).get(
                TRACEPARENT_ANNOTATION),
            attributes={"claim_uid": uid})
        ok = False
        try:
            tp = span.traceparent()
            if tp:
                env[ENV_TRACEPARENT] = tp
            self._cdi.create_claim_spec_file(uid, env)
            ok = True
        finally:
            if ok:
                span.end()
            else:
                span.abandon("cd claim spec write failed")
        self._first_attempt.pop(uid, None)
        return self._complete(uid)

    def _assert_channels_free_locked(self, claim_uid: str,
                                     channel_ids: List[int]) -> None:
        """Channel exclusivity (device_state.go:625-664): a channel held by
        a *different* claim that completed prepare must first be
        unprepared — orders prepare-after-unprepare correctly when kubelet
        races a new pod against a terminating one. Iterates checkpoint
        claims, so the caller must hold ``self._lock`` (draracer R10
        caught the undeclared requirement)."""
        for other_uid, other in self._checkpoint.claims.items():
            if other_uid == claim_uid or other.state != PREPARE_COMPLETED:
                continue
            held = {d.get("channel_id") for d in other.devices
                    if d.get("type") == deviceinfo.DEVICE_TYPE_CHANNEL}
            clash = held.intersection(channel_ids)
            if clash:
                raise RetryableNotReady(
                    f"channel(s) {sorted(clash)} still prepared for claim "
                    f"{other_uid}")

    # -- daemon claims ------------------------------------------------------

    def _prepare_daemon(self, claim: Dict, results: List[Dict],
                        config: apitypes.ComputeDomainDaemonConfig
                        ) -> PrepareResult:
        uid = claim["metadata"]["uid"]
        cd = self._cd.get_by_uid(config.domain_id)
        if cd is None:
            raise RetryableNotReady(
                f"computedomain {config.domain_id} not found",
                cd_uid=config.domain_id)
        with self._lock:
            self._checkpoint.claims[uid] = PreparedClaim(
                uid=uid, state=PREPARE_STARTED,
                name=claim["metadata"].get("name", ""),
                namespace=claim["metadata"].get("namespace", ""))
            self._checkpoint.claims[uid].devices = [{
                "type": deviceinfo.DEVICE_TYPE_DAEMON,
                "device": r["device"],
                "request": r.get("request", ""),
                "cd_uid": config.domain_id,
                "pool": self._node_name,
                "cdi_ids": [self._cdi.get_claim_device(uid)],
            } for r in results]
            # Mid-prepare intent record: side slot only (see
            # tpuplugin/checkpoint.py CheckpointManager).
            self._ckpt_mgr.store(self._checkpoint, intent=True)

        domain_dir = self._cd.prepare_daemon_dir(cd, self._slice_id)
        env = {
            "COMPUTE_DOMAIN_UUID": cd["metadata"].get("uid", ""),
            "COMPUTE_DOMAIN_NAME": cd["metadata"].get("name", ""),
            "COMPUTE_DOMAIN_NAMESPACE": cd["metadata"].get("namespace", ""),
            "TPU_SLICE_ID": self._slice_id,
        }
        mounts = [{
            "hostPath": domain_dir,
            "containerPath": "/var/run/tpu-dra-cd/domain",
            "options": ["rw", "bind"],
        }]
        self._cdi.create_claim_spec_file(uid, env, mounts=mounts)
        return self._complete(uid)

    def _complete(self, uid: str) -> PrepareResult:
        with self._lock:
            prepared = self._checkpoint.claims.get(uid)
            if prepared is None:
                # GC collected the claim (deleted from the API server) while
                # the readiness wait was in flight; don't resurrect it.
                return PrepareResult(
                    error="claim was garbage-collected during prepare")
            prepared.state = PREPARE_COMPLETED
            self._ckpt_mgr.store(self._checkpoint)
            return PrepareResult(devices=[
                self._rehydrate(r) for r in prepared.devices])

    # ------------------------------------------------------------------
    # Unprepare
    # ------------------------------------------------------------------

    def unprepare(self, claim_uid: str) -> Optional[str]:
        self._first_attempt.pop(claim_uid, None)
        # Whole-method serialization: see _label_lock in __init__.
        with self._label_lock:
            return self._unprepare_locked(claim_uid)

    def _unprepare_locked(self, claim_uid: str) -> Optional[str]:
        with self._lock:
            prepared = self._checkpoint.claims.get(claim_uid)
            if prepared is None:
                return None
            cd_uids = {d.get("cd_uid") for d in prepared.devices
                       if d.get("type") == deviceinfo.DEVICE_TYPE_CHANNEL}
            # Last channel claim for a CD releases the node from the domain
            # (the daemon settings/dir GC is deferred, §3.4).
            still_used = {
                d.get("cd_uid")
                for uid, c in self._checkpoint.claims.items()
                if uid != claim_uid
                for d in c.devices
                if d.get("type") == deviceinfo.DEVICE_TYPE_CHANNEL}
        # Side effects are rolled back *before* the claim leaves the
        # checkpoint: if label removal fails transiently, kubelet's
        # unprepare retry still finds the claim and completes the cleanup
        # (the reference orders unprepare work before checkpoint removal
        # for the same reason, cd device_state.go:208-278). Deleting the
        # record first would make the retry a no-op and leak the label,
        # pinning the daemon pod and blocking other CDs on this node.
        for cd_uid in cd_uids - still_used:
            if cd_uid:
                try:
                    self._cd.remove_node_label(cd_uid)
                except Exception as e:  # noqa: BLE001
                    return f"remove node label for {cd_uid}: {e}"
        with self._lock:
            if claim_uid not in self._checkpoint.claims:
                return None
            # Spec-file delete precedes the pop: if it raises, the claim is
            # still checkpointed and the kubelet retry can finish; popping
            # first would diverge memory from disk and leak the spec file.
            self._cdi.delete_claim_spec_file(claim_uid)
            del self._checkpoint.claims[claim_uid]
            self._ckpt_mgr.store(self._checkpoint)
        return None

    # ------------------------------------------------------------------

    def _rehydrate(self, record: Dict) -> PreparedDevice:
        return PreparedDevice(
            pool_name=record.get("pool", ""),
            device_name=record.get("device", ""),
            cdi_device_ids=list(record.get("cdi_ids") or []),
            request_names=([record["request"]]
                           if record.get("request") else []))

    def prepared_claim_uids(self) -> List[str]:
        with self._lock:
            return list(self._checkpoint.claims)

    def checkpoint_snapshot(self) -> Checkpoint:
        """Deep copy under the lock: GC iterates this while prepare threads
        mutate the live checkpoint."""
        import copy
        with self._lock:
            return copy.deepcopy(self._checkpoint)

    def backfill_claim_identity(self, claim_uid: str, name: str,
                                namespace: str) -> bool:
        """Write name/namespace into a legacy (V1-era) checkpoint record
        that predates claim identity, and persist. The reference pulls the
        missing fields from the API server on first touch
        (cd device_state.go:231-254, checkpoint_legacy.go); here the GC
        sweep does it so legacy records become collectible. Returns False
        when the record vanished meanwhile."""
        with self._lock:
            prepared = self._checkpoint.claims.get(claim_uid)
            if prepared is None:
                return False
            if not prepared.name:
                prepared.name = name
                prepared.namespace = namespace
                self._ckpt_mgr.store(self._checkpoint)
            return True

    def drop_claim(self, claim_uid: str) -> bool:
        """Checkpoint GC hook (cleanup.py). Runs the full unprepare path —
        an abandoned PREPARE_STARTED claim may have added the node label
        before its ResourceClaim was deleted, and kubelet will never call
        unprepare for it; dropping the record without the last-claim label
        accounting would leak the label with nothing left to remove it.
        Returns False when cleanup failed transiently: the record is
        retained and the next GC sweep retries (the caller must not count
        the claim as collected)."""
        err = self.unprepare(claim_uid)
        if err:
            log.warning("GC drop of claim %s deferred: %s", claim_uid, err)
            return False
        return True
