"""ICI mesh/torus model.

A TPU slice is a cuboid of chips wired as a per-generation mesh or
torus: v4/v5p are 3D tori (wraparound links close each ring once the
slice spans the full dimension), v5e/v6e are 2D meshes (z is always 1).
The driver discovers per-chip ``coords`` (native sysfs topology files,
``native/tpuinfo.py``); this module turns those into a validated
:class:`Mesh` the placement layer can scan.

Coordinate validation happens at publish time (``DeviceState`` building
its allocatable inventory): duplicate or out-of-bounds coordinates mean
the inventory lies about the fabric, and every topology-scored decision
downstream would be wrong — reject early, loudly.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

Coord = Tuple[int, int, int]

# Generations whose ICI closes into a torus once a dimension spans the
# full slice extent. 2D generations (v5e/v6e) are modeled as meshes.
TORUS_GENERATIONS = frozenset({"v4", "v5p"})

# Native dimensionality of each generation's ICI fabric.
GEN_NDIMS: Dict[str, int] = {"v4": 3, "v5p": 3, "v5e": 2, "v6e": 2}


class TopologyError(ValueError):
    """Invalid fabric description (duplicate/out-of-bounds coords,
    malformed topology strings)."""


def format_topology(dims: Sequence[int]) -> str:
    """(4, 4, 4) -> '4x4x4' (the ``tpu.dev/sliceTopology`` attribute)."""
    return "x".join(str(d) for d in dims)


def parse_topology(text: str) -> Optional[Tuple[int, int, int]]:
    """'4x4x4' -> (4, 4, 4); '4x4' -> (4, 4, 1); None when malformed."""
    if not text:
        return None
    parts = text.lower().split("x")
    if not 1 <= len(parts) <= 3 or not all(p.isdigit() for p in parts):
        return None
    dims = [int(p) for p in parts]
    if any(d < 1 for d in dims):
        return None
    while len(dims) < 3:
        dims.append(1)
    return (dims[0], dims[1], dims[2])


def _balanced_factors(n: int, ndims: int) -> List[int]:
    """Factor n into `ndims` factors as near-equal as possible (largest
    first). Greedy: peel the divisor closest to the remaining
    ndims-th root, preferring the smaller-or-equal side so 8 -> [2,2,2],
    16 -> [4,2,2], 12 -> [3,2,2], primes degrade to [n,1,..]."""
    dims: List[int] = []
    remaining = n
    for k in range(ndims, 1, -1):
        target = round(remaining ** (1.0 / k)) or 1
        best = 1
        for d in range(target, 0, -1):
            if remaining % d == 0:
                best = d
                break
        dims.append(best)
        remaining //= best
    dims.append(remaining)
    return sorted(dims, reverse=True)


def topology_dims(generation: str, count: int) -> Tuple[int, int, int]:
    """Canonical slice dims for `count` chips of `generation`: 3D
    near-cubic for v4/v5p, 2D near-square (z=1) for v5e/v6e. 4 v5p
    chips -> (2,2,1); 64 -> (4,4,4); 16 v5e -> (4,4,1)."""
    if count < 1:
        raise TopologyError(f"chip count must be >= 1, got {count}")
    ndims = GEN_NDIMS.get(generation, 3)
    dims = _balanced_factors(count, ndims)
    while len(dims) < 3:
        dims.append(1)
    return (dims[0], dims[1], dims[2])


@dataclass(frozen=True)
class Mesh:
    """One cuboid fabric block: dims plus per-dim wraparound. Coords are
    local to the block (0-based); ``neighbors``/``distance`` honor the
    torus closure where wrap is set."""

    dims: Tuple[int, int, int]
    wrap: Tuple[bool, bool, bool] = (False, False, False)

    @property
    def volume(self) -> int:
        return self.dims[0] * self.dims[1] * self.dims[2]

    def contains(self, c: Coord) -> bool:
        return all(0 <= c[i] < self.dims[i] for i in range(3))

    def all_coords(self) -> List[Coord]:
        return [(x, y, z)
                for x in range(self.dims[0])
                for y in range(self.dims[1])
                for z in range(self.dims[2])]

    def neighbors(self, c: Coord) -> List[Coord]:
        """ICI-linked coords of `c` inside this block (wraparound links
        included where wrap is set; a dim of size <= 1 has no links;
        size 2 has one direct link, never a duplicate wrap edge)."""
        out: List[Coord] = []
        for axis in range(3):
            size = self.dims[axis]
            if size <= 1:
                continue
            for step in (-1, 1):
                v = c[axis] + step
                if 0 <= v < size:
                    pass
                elif self.wrap[axis] and size > 2:
                    v %= size
                else:
                    continue
                n = list(c)
                n[axis] = v
                t = (n[0], n[1], n[2])
                if t not in out:
                    out.append(t)
        return out

    def distance(self, a: Coord, b: Coord) -> int:
        """Hop distance over the fabric (per-dim ring distance where the
        dim wraps, Manhattan otherwise)."""
        total = 0
        for axis in range(3):
            d = abs(a[axis] - b[axis])
            if self.wrap[axis] and self.dims[axis] > 2:
                d = min(d, self.dims[axis] - d)
            total += d
        return total


def for_slice(generation: str, count: int) -> Mesh:
    """The canonical full-slice mesh for `count` chips: torus closure on
    every dim a torus generation spans fully (and meaningfully: a ring
    of 2 is just the direct link)."""
    dims = topology_dims(generation, count)
    torus = generation in TORUS_GENERATIONS
    return Mesh(dims=dims, wrap=tuple(torus and d > 2 for d in dims))


def block_mesh(coords: Iterable[Coord], generation: str = "",
               slice_dims: Optional[Tuple[int, int, int]] = None,
               ) -> Tuple[Mesh, Coord]:
    """(mesh, offset) for a host's sub-block of a slice: dims are the
    bounding extent of `coords`, offset the per-dim minimum (callers
    normalize by subtracting it). Wraparound applies only where the
    block spans the FULL slice dim of a torus generation — a partial
    ring has no closure. Raises TopologyError on duplicates, negative
    coords, or coords outside declared `slice_dims`."""
    pts = list(coords)
    seen = set()
    for c in pts:
        if c in seen:
            raise TopologyError(f"duplicate chip coordinate {c}")
        seen.add(c)
        if any(v < 0 for v in c):
            raise TopologyError(f"negative chip coordinate {c}")
        if slice_dims is not None and any(c[i] >= slice_dims[i]
                                          for i in range(3)):
            raise TopologyError(
                f"chip coordinate {c} outside declared slice topology "
                f"{format_topology(slice_dims)}")
    if not pts:
        return Mesh(dims=(0, 0, 0)), (0, 0, 0)
    lo = tuple(min(c[i] for c in pts) for i in range(3))
    hi = tuple(max(c[i] for c in pts) for i in range(3))
    dims = tuple(hi[i] - lo[i] + 1 for i in range(3))
    torus = generation in TORUS_GENERATIONS
    wrap = tuple(
        torus and dims[i] > 2
        and slice_dims is not None and dims[i] == slice_dims[i]
        for i in range(3))
    return Mesh(dims=dims, wrap=wrap), lo  # type: ignore[return-value]


def validate_chips(chips: Iterable) -> None:
    """Publish-time validation of a discovered chip inventory
    (``DeviceState`` building its allocatable set): within each
    (slice_id, worker_index) host block, coordinates must be unique,
    non-negative, and inside the declared ``slice_topology`` when one is
    published. Raises TopologyError — an inventory that lies about the
    fabric must not reach a ResourceSlice.

    A block where EVERY chip sits at the default (0,0,0) with no
    declared topology published no fabric information at all (real
    accel sysfs without topology/ files zero-fills coords) — that is
    "no topology", not a duplicate-coordinate lie, and must not refuse
    plugin startup; the scheduler's topology path falls back to
    first-fit for such nodes."""
    groups: Dict[Tuple[str, int], List] = {}
    for chip in chips:
        groups.setdefault((chip.slice_id, chip.worker_index),
                          []).append(chip)
    for (slice_id, worker), members in groups.items():
        if (len(members) > 1
                and all(c.coords == (0, 0, 0) for c in members)
                and not any(getattr(c, "slice_topology", "")
                            for c in members)):
            continue  # coordinate-less inventory: nothing to validate
        declared = None
        for chip in members:
            topo = parse_topology(getattr(chip, "slice_topology", ""))
            if topo is not None:
                if declared is not None and topo != declared:
                    raise TopologyError(
                        f"chips of slice {slice_id!r} worker {worker} "
                        f"declare conflicting topologies "
                        f"{format_topology(declared)} vs "
                        f"{format_topology(topo)}")
                declared = topo
        try:
            block_mesh((c.coords for c in members),
                       generation=members[0].generation,
                       slice_dims=declared)
        except TopologyError as e:
            raise TopologyError(
                f"invalid chip topology (slice={slice_id!r} "
                f"worker={worker}): {e}") from e
