"""Slice-shape library, free-set scanner, and fragmentation scoring.

An N-chip request on an ICI fabric is only useful as a *cuboid* — the
compiler lays collectives over contiguous sub-tori, and a scattered
allocation silently degrades every all-reduce to DCN hops. This module
enumerates the valid cuboid sub-shapes for a chip count, scans a free
coordinate set for placements, and scores them by a fragmentation
metric: prefer placements that consume already-fragmented regions
(fewest free neighbors left around the placement) so large free cuboids
survive for the next big claim — best-fit packing, adapted to a torus.

Also home to the kube-facing adapters: ``node_topology_from_slices``
(published ResourceSlice devices -> per-node topology view),
``rank_candidate_nodes`` (inter-node ICI adjacency ordering by
``sliceId``/``workerIndex``), ``domain_topology`` (ComputeDomain member
alignment), and the chaos verifier ``allocation_violations``.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tpu_dra.topology.mesh import (
    Coord, Mesh, TopologyError, block_mesh, parse_topology,
)

Shape = Tuple[int, int, int]


def _surface(shape: Shape) -> int:
    a, b, c = shape
    return 2 * (a * b + b * c + c * a)


def enumerate_shapes(count: int, dims: Shape) -> List[Shape]:
    """All cuboid orientations (a,b,c) with a*b*c == count that fit in
    `dims`, most compact first (smallest surface area == best ICI
    bisection and least boundary to fragment against), deterministic
    tie-break on the shape tuple."""
    shapes: Set[Shape] = set()
    for a in range(1, min(count, dims[0]) + 1):
        if count % a:
            continue
        rest = count // a
        for b in range(1, min(rest, dims[1]) + 1):
            if rest % b:
                continue
            c = rest // b
            if c <= dims[2]:
                shapes.add((a, b, c))
    return sorted(shapes, key=lambda s: (_surface(s), s))


def _axis_bases(size: int, dim: int, wrap: bool) -> range:
    """Base offsets along one axis: every offset when the ring wraps (a
    placement may straddle the seam), sliding-window otherwise; a
    full-span shape has exactly one distinct placement."""
    if size == dim:
        return range(1)
    if wrap:
        return range(dim)
    return range(dim - size + 1)


def placement_coords(base: Coord, shape: Shape, mesh: Mesh
                     ) -> Tuple[Coord, ...]:
    axes = []
    for i in range(3):
        if mesh.wrap[i]:
            axes.append([(base[i] + d) % mesh.dims[i]
                         for d in range(shape[i])])
        else:
            axes.append([base[i] + d for d in range(shape[i])])
    return tuple(itertools.product(*axes))  # type: ignore[return-value]


def enumerate_placements(mesh: Mesh, count: int
                         ) -> Iterable[Tuple[Shape, Coord, Tuple[Coord, ...]]]:
    """Every (shape, base, coords) placement of `count` chips on `mesh`
    — each is a contiguous cuboid by construction, within bounds, with
    `count` mutually distinct coords."""
    for shape in enumerate_shapes(count, mesh.dims):
        for bx in _axis_bases(shape[0], mesh.dims[0], mesh.wrap[0]):
            for by in _axis_bases(shape[1], mesh.dims[1], mesh.wrap[1]):
                for bz in _axis_bases(shape[2], mesh.dims[2], mesh.wrap[2]):
                    base = (bx, by, bz)
                    yield shape, base, placement_coords(base, shape, mesh)


def fragmentation_score(coords: Iterable[Coord], free_after: Set[Coord],
                        mesh: Mesh) -> int:
    """Free cells ICI-adjacent to the placement once it is carved out:
    LOW means the placement nests into an already-fragmented pocket
    (against allocations or the fabric edge), HIGH means it was punched
    into the middle of a large free region — the fragmenting move."""
    score = 0
    for c in coords:
        for n in mesh.neighbors(c):
            if n in free_after:
                score += 1
    return score


def best_placement(mesh: Mesh, free: Set[Coord], count: int
                   ) -> Optional[Tuple[Coord, ...]]:
    """The best-scoring contiguous placement of `count` chips inside
    `free`, or None when no cuboid of that count fits. Deterministic:
    ties break on (shape enumeration order, base coord)."""
    if count <= 0 or count > len(free):
        return None
    best: Optional[Tuple[Coord, ...]] = None
    best_key: Optional[Tuple[int, int, Coord]] = None
    for shape_idx, (shape, base, coords) in enumerate_index(mesh, count):
        if not all(c in free for c in coords):
            continue
        after = free.difference(coords)
        key = (fragmentation_score(coords, after, mesh), shape_idx, base)
        if best_key is None or key < best_key:
            best_key = key
            best = coords
    return best


def enumerate_index(mesh: Mesh, count: int):
    """enumerate_placements with a shape-order index for tie-breaking."""
    shape_order: Dict[Shape, int] = {}
    for shape, base, coords in enumerate_placements(mesh, count):
        idx = shape_order.setdefault(shape, len(shape_order))
        yield idx, (shape, base, coords)


def max_free_cuboid(mesh: Mesh, free: Set[Coord]) -> int:
    """Volume of the largest cuboid wholly inside `free` (the
    fragmentation observable: a churned fabric whose max free cuboid
    collapses can no longer host big claims even at low utilization).
    Scans candidate volumes descending and returns on first fit."""
    if not free:
        return 0
    volumes = sorted({a * b * c
                      for a in range(1, mesh.dims[0] + 1)
                      for b in range(1, mesh.dims[1] + 1)
                      for c in range(1, mesh.dims[2] + 1)
                      if a * b * c <= len(free)}, reverse=True)
    for vol in volumes:
        for _shape, _base, coords in enumerate_placements(mesh, vol):
            if all(c in free for c in coords):
                return vol
    return 1 if free else 0


def _circular_run(vals: List[int], dim: int, wrap: bool
                  ) -> Optional[List[int]]:
    """The ordered run the sorted distinct values form along one axis:
    a plain interval, the full axis, or (when wrapping) an interval
    straddling the seam; None when the values are not one run."""
    k = len(vals)
    if k == dim:
        return vals
    if vals == list(range(vals[0], vals[0] + k)):
        return vals
    if wrap:
        present = set(vals)
        for start in vals:
            run = [(start + i) % dim for i in range(k)]
            if set(run) == present:
                return run
    return None


def is_contiguous_block(coords: Iterable[Coord], mesh: Mesh) -> bool:
    """True iff `coords` is exactly one cuboid placement on `mesh`
    (axis projections each form a single run — modulo the ring where
    the axis wraps — and the set is their full cartesian product)."""
    pts = list(coords)
    block = set(pts)
    if len(block) != len(pts) or not pts:
        return False
    runs = []
    for axis in range(3):
        vals = sorted({c[axis] for c in block})
        run = _circular_run(vals, mesh.dims[axis],
                            mesh.wrap[axis] and mesh.dims[axis] > 2)
        if run is None:
            return False
        runs.append(run)
    if len(block) != len(runs[0]) * len(runs[1]) * len(runs[2]):
        return False
    return block == set(itertools.product(*runs))


# ---------------------------------------------------------------------------
# Kube adapters: published ResourceSlice devices -> topology views
# ---------------------------------------------------------------------------

def _attr(dev: Dict, name: str, kind: str):
    a = (dev.get("attributes") or {}).get(name) or {}
    return a.get(kind)


@dataclass
class NodeTopology:
    """One node's view of the fabric, extracted from its published
    ResourceSlice chip devices. Coords are normalized to the node's own
    block (offset removed) so the scanner works in local space."""

    mesh: Mesh
    coord_of: Dict[str, Coord] = field(default_factory=dict)   # device name
    name_of: Dict[Coord, str] = field(default_factory=dict)
    driver_of: Dict[str, str] = field(default_factory=dict)
    slice_id: str = ""
    worker_index: int = 0


def node_topology_from_slices(slices: List[Dict]) -> Optional[NodeTopology]:
    """Build a NodeTopology from one node's ResourceSlices, or None when
    the node publishes no usable topology (no chip devices carry
    coordinates, or the coordinates are invalid — an invalid fabric
    must not be scored, only validated at publish time)."""
    raw: Dict[str, Tuple[Coord, str]] = {}
    slice_id = ""
    worker = 0
    generation = ""
    declared: Optional[Tuple[int, int, int]] = None
    for sl in sorted(slices, key=lambda s: s["metadata"]["name"]):
        spec = sl.get("spec") or {}
        driver = spec.get("driver", "")
        for dev in spec.get("devices") or []:
            if _attr(dev, "type", "string") not in (None, "chip"):
                continue  # subslices partition a chip; the chip carries coords
            cx = _attr(dev, "coordX", "int")
            cy = _attr(dev, "coordY", "int")
            cz = _attr(dev, "coordZ", "int")
            if cx is None or cy is None or cz is None:
                continue
            raw[dev["name"]] = ((int(cx), int(cy), int(cz)), driver)
            slice_id = slice_id or (_attr(dev, "sliceID", "string") or "")
            worker = int(_attr(dev, "workerIndex", "int") or 0)
            generation = generation or (_attr(dev, "generation", "string")
                                        or "")
            declared = declared or parse_topology(
                _attr(dev, "sliceTopology", "string") or "")
    if len(raw) < 2:
        return None  # nothing to lay out
    try:
        mesh, offset = block_mesh((c for c, _ in raw.values()),
                                  generation=generation, slice_dims=declared)
    except TopologyError:
        return None
    topo = NodeTopology(mesh=mesh, slice_id=slice_id, worker_index=worker)
    for name, (c, driver) in raw.items():
        local = (c[0] - offset[0], c[1] - offset[1], c[2] - offset[2])
        topo.coord_of[name] = local
        topo.name_of[local] = name
        topo.driver_of[name] = driver
    return topo


def rank_candidate_nodes(infos: List[Tuple[str, str, int]]) -> List[str]:
    """Order candidate nodes so multi-node placements land on ONE
    physical slice: group by sliceId, largest slice group first (a small
    group exhausts before a big ComputeDomain fills), inside a group by
    workerIndex (ranks then match the fabric's worker order); nodes
    with no slice identity trail in name order. `infos` is
    (node_name, slice_id, worker_index)."""
    groups: Dict[str, List[Tuple[int, str]]] = {}
    loose: List[str] = []
    for name, slice_id, worker in infos:
        if slice_id:
            groups.setdefault(slice_id, []).append((worker, name))
        else:
            loose.append(name)
    out: List[str] = []
    for slice_id in sorted(groups, key=lambda s: (-len(groups[s]), s)):
        out.extend(name for _w, name in sorted(groups[slice_id]))
    out.extend(sorted(loose))
    return out


def domain_topology(members: List[Dict]) -> Dict:
    """ComputeDomain member-set ICI summary from ``cd.status.nodes``
    entries (each carries the daemon-registered ``sliceID``/``index``):
    how many physical slices the domain spans and whether it is
    slice-aligned (one slice, contiguous worker indices) — the
    multi-node analog of an intra-node contiguous cuboid."""
    slice_ids = sorted({n.get("sliceID", "") for n in members})
    aligned = False
    if len(slice_ids) == 1 and members:
        idx = sorted(n.get("index", 0) for n in members)
        aligned = idx == list(range(idx[0], idx[0] + len(idx)))
    return {"slices": len(slice_ids), "sliceAligned": aligned}


def allocation_violations(claims: List[Dict], slices: List[Dict]
                          ) -> List[str]:
    """Chaos invariant: every allocated multi-chip claim on a node that
    publishes coordinates must be an ICI-contiguous cuboid. Built from
    cluster truth (claim listing + ResourceSlice listing), independent
    of any scheduler state."""
    by_node: Dict[str, List[Dict]] = {}
    for sl in slices:
        node = (sl.get("spec") or {}).get("nodeName")
        if node:
            by_node.setdefault(node, []).append(sl)
    topos: Dict[str, Optional[NodeTopology]] = {
        node: node_topology_from_slices(sls)
        for node, sls in by_node.items()}
    out: List[str] = []
    for claim in claims:
        alloc = (claim.get("status") or {}).get("allocation") or {}
        results = (alloc.get("devices") or {}).get("results") or []
        per_pool: Dict[str, List[str]] = {}
        for r in results:
            per_pool.setdefault(r.get("pool", ""), []).append(
                r.get("device", ""))
        for pool, devices in per_pool.items():
            topo = topos.get(pool)
            if topo is None or len(devices) < 2:
                continue
            coords = [topo.coord_of[d] for d in devices
                      if d in topo.coord_of]
            if len(coords) != len(devices):
                continue  # subslice/unknown devices: no chip-level layout
            if not is_contiguous_block(coords, topo.mesh):
                name = claim.get("metadata", {}).get("name", "?")
                out.append(
                    f"claim {name}: devices {sorted(devices)} on {pool} "
                    f"are not an ICI-contiguous cuboid (coords "
                    f"{sorted(coords)})")
    return out
