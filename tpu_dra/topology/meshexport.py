"""Allocation → mesh contract: coordinate export, rank order, ICI cost.

The control-plane half of the data-plane loop (SURVEY §17). The driver
allocates torus-contiguous chip sets (topology/placement); a workload
container then has to lay a ``jax.sharding.Mesh`` over exactly those
chips in an order that keeps neighboring ranks on neighboring ICI links.
This module owns everything about that contract that does NOT need JAX:

- **coordinate export** (``export_topology_env``): the per-claim CDI env
  the tpuplugin emits next to ``TPU_VISIBLE_CHIPS`` — per-chip torus
  coordinates, the declared slice topology, slice/worker identity — so
  the workload's mesh builder consumes the same allocation result the
  scheduler scored, not a rediscovered one.
- **rank→coordinate mapping** (``snake_order``): the deterministic
  device order every process of a multi-process mesh must agree on.
  Boustrophedon over the allocation's bounding box: consecutive ranks
  of a contiguous cuboid are ICI neighbors (1 hop), and the order is a
  pure function of the coordinate set — same allocation ⇒ same order in
  every worker, no coordination round needed.
- **ICI cost model** (``ring_hops`` / ``modeled_ring_allreduce_gbps``):
  hop-count-weighted link bandwidth for the fake multi-host backend.
  On real hardware the measured collective is the truth; on the fake
  backend the model makes placement quality *measurable and
  deterministic* — the contiguous-vs-fragmented bench A/B gates on it.
- **MeshPlan** (``plan_from_coords`` and its adapters): the validated,
  ordered result handed to ``workloads.meshbuild``. Construction
  REFUSES lies (rank/topology mismatch, duplicate or out-of-bounds
  coords) — a wrong mesh silently degrades every collective, so the
  error surface is loud and early, mirroring ``mesh.validate_chips``.

Ownership rules: this module holds no allocation state and never
mutates its inputs; plans are frozen snapshots of one claim's
allocation result. The exported env is written once at prepare time
into the claim's CDI spec — consumers treat it as immutable, and a
re-prepare rewrites the whole spec. Fault sites ``mesh.build`` and
``workload.launch`` guard the two seams where the data plane first
trusts control-plane output.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from tpu_dra.infra.faults import FAULTS
from tpu_dra.infra.metrics import MESH_BUILDS
from tpu_dra.infra.trace import ENV_TRACEPARENT, TRACER
from tpu_dra.topology.mesh import (
    Coord, Mesh, TORUS_GENERATIONS, format_topology, parse_topology,
)
from tpu_dra.topology.placement import is_contiguous_block

# Modeled per-link, per-direction ICI bandwidth in GB/s by generation.
# Only the RATIOS matter to anything gated (the A/B compares placements
# of the same generation); absolute values are public-order-of-magnitude
# so modeled numbers read plausibly next to measured ones.
ICI_LINK_GBPS: Dict[str, float] = {
    "v4": 50.0,
    "v5p": 100.0,
    "v5e": 50.0,
    "v6e": 100.0,
}

# Env keys of the exported contract (also consumed by workloads.meshbuild).
ENV_CHIP_COORDS = "TPU_CHIP_COORDS"
ENV_SLICE_TOPOLOGY = "TPU_SLICE_TOPOLOGY"
ENV_GENERATION = "TPU_GENERATION"
ENV_SLICE_ID = "TPU_SLICE_ID"
ENV_WORKER_INDEX = "TPU_WORKER_INDEX"


class MeshBuildError(ValueError):
    """The allocation result cannot back a trustworthy mesh (rank or
    topology mismatch, duplicate/out-of-bounds coordinates, missing
    coordinate export). Refusal, not degradation: a silently-wrong
    device order turns every ICI-adjacent collective into a slow one."""


# ---------------------------------------------------------------------------
# Coordinate export (prepare-time env, next to TPU_VISIBLE_CHIPS)
# ---------------------------------------------------------------------------

def format_chip_coords(coords_by_index: Dict[int, Coord]) -> str:
    """{0: (0,0,0), 1: (1,0,0)} -> '0:0.0.0,1:1.0.0' (index-sorted)."""
    return ",".join(f"{i}:{c[0]}.{c[1]}.{c[2]}"
                    for i, c in sorted(coords_by_index.items()))


def parse_chip_coords(text: str) -> Dict[int, Coord]:
    """Inverse of format_chip_coords; raises MeshBuildError on malformed
    entries (a torn env var must not silently drop chips)."""
    out: Dict[int, Coord] = {}
    if not text:
        return out
    for part in text.split(","):
        try:
            idx_s, coord_s = part.split(":")
            x, y, z = coord_s.split(".")
            idx, c = int(idx_s), (int(x), int(y), int(z))
        except ValueError as e:
            raise MeshBuildError(
                f"malformed {ENV_CHIP_COORDS} entry {part!r}") from e
        if idx in out:
            raise MeshBuildError(
                f"duplicate chip index {idx} in {ENV_CHIP_COORDS}")
        out[idx] = c
    return out


def export_topology_env(chips: Iterable) -> Dict[str, str]:
    """The claim-env topology block for an allocated chip set, or {}
    when the inventory published no fabric information (every chip at
    the default (0,0,0) with no declared topology — the coordinate-less
    real-sysfs case validate_chips documents). Emitted by the tpuplugin
    at prepare time into the claim's CDI spec."""
    members = list(chips)
    if not members:
        return {}
    if (all(c.coords == (0, 0, 0) for c in members)
            and not any(getattr(c, "slice_topology", "") for c in members)):
        # No topology published: nothing to export. Unlike
        # validate_chips (where a single chip AT (0,0,0) is a valid
        # fabric), an export here cannot distinguish "really at the
        # origin" from "zero-filled sysfs default" without a declared
        # topology — exporting a fabricated coordinate would feed the
        # mesh builder a guess, so coordless claims of ANY size keep
        # their exact old env and plan_from_env refuses loudly instead.
        return {}
    declared = ""
    for chip in members:
        topo = getattr(chip, "slice_topology", "")
        if topo:
            declared = topo
            break
    env = {
        ENV_CHIP_COORDS: format_chip_coords(
            {c.index: c.coords for c in members}),
        ENV_GENERATION: members[0].generation,
        ENV_WORKER_INDEX: str(members[0].worker_index),
    }
    if declared:
        env[ENV_SLICE_TOPOLOGY] = declared
    slice_id = getattr(members[0], "slice_id", "")
    if slice_id:
        env[ENV_SLICE_ID] = slice_id
    return env


# ---------------------------------------------------------------------------
# Rank → coordinate mapping
# ---------------------------------------------------------------------------

def snake_order(coords: Iterable[Coord]) -> List[Coord]:
    """Deterministic boustrophedon order over the coordinate set's
    bounding box: z-planes ascending, y-rows serpentine within a plane
    (direction flips per plane), x serpentine within a row (direction
    flips per traversed row, continuing across planes). For a full
    cuboid every consecutive pair — including the plane transitions —
    is exactly one ICI hop apart, so ring collectives over this order
    ride neighbor links. A pure function of the set: every process
    computes the same order from the same allocation."""
    pts = sorted(set(coords))
    if not pts:
        return []
    lo = tuple(min(c[i] for c in pts) for i in range(3))
    hi = tuple(max(c[i] for c in pts) for i in range(3))
    dx, dy = hi[0] - lo[0] + 1, hi[1] - lo[1] + 1

    def key(c: Coord):
        x, y, z = c[0] - lo[0], c[1] - lo[1], c[2] - lo[2]
        yy = y if z % 2 == 0 else dy - 1 - y
        row = z * dy + yy
        xx = x if row % 2 == 0 else dx - 1 - x
        return (z, yy, xx)

    return sorted(pts, key=key)


def ring_hops(ordered: Sequence[Coord], slice_mesh: Mesh) -> List[int]:
    """Per-step ICI hop distances of the ring over `ordered` (wrapping
    back to the first coord), measured on the FULL slice mesh so torus
    closure counts where the slice wraps."""
    n = len(ordered)
    if n < 2:
        return []
    return [slice_mesh.distance(ordered[i], ordered[(i + 1) % n])
            for i in range(n)]


def modeled_ring_allreduce_gbps(ordered: Sequence[Coord], slice_mesh: Mesh,
                                generation: str) -> float:
    """Hop-count-weighted ring all-reduce bandwidth model: each of the
    2(n-1) ring steps moves payload/n bytes over that step's hop count
    serially, so algo bandwidth = link * n / (2(n-1) * mean_hop).
    Deterministic — the bench A/B's contiguous-vs-fragmented delta is a
    pure function of the two coordinate sets."""
    hops = ring_hops(ordered, slice_mesh)
    if not hops:
        return 0.0
    n = len(ordered)
    mean_hop = sum(hops) / len(hops)
    link = ICI_LINK_GBPS.get(generation, 50.0)
    return link * n / (2.0 * (n - 1) * mean_hop)


def slice_mesh_for(dims: Tuple[int, int, int], generation: str) -> Mesh:
    """The full-slice Mesh for declared dims: torus closure on every dim
    a torus generation meaningfully spans (same rule as mesh.for_slice,
    but from declared dims rather than a chip count)."""
    torus = generation in TORUS_GENERATIONS
    return Mesh(dims=dims, wrap=tuple(torus and d > 2 for d in dims))


# ---------------------------------------------------------------------------
# MeshPlan: the validated, ordered allocation → mesh handoff
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class MeshPlan:
    """One claim-set's allocation, ordered and costed for mesh
    construction. ``coords``/``chip_keys`` are in RANK order (snake);
    ``order[r]`` is the arrival-order index of rank r, so a caller
    holding per-chip resources in arrival order permutes them with it.
    Frozen: plans are snapshots, never mutated."""

    generation: str
    slice_dims: Tuple[int, int, int]
    coords: Tuple[Coord, ...]
    chip_keys: Tuple[Tuple[int, int], ...]   # (worker_index, chip_index)
    order: Tuple[int, ...]
    contiguous: bool
    hops: Tuple[int, ...]
    hop_mean: float
    hop_max: int
    modeled_ici_gbps: float
    n_workers: int = 1

    @property
    def n_devices(self) -> int:
        return len(self.coords)


def plan_from_coords(coords_by_key: Dict[Tuple[int, int], Coord],
                     slice_dims: Optional[Tuple[int, int, int]],
                     generation: str,
                     n_workers: int = 1) -> MeshPlan:
    """Validate + order one allocation into a MeshPlan.

    `coords_by_key` maps (worker_index, chip_index) -> global slice
    coordinate. Refuses duplicate coordinates (two chips cannot share a
    fabric position), coordinates outside the declared slice topology,
    and empty allocations. Without declared dims the bounding box
    serves (a fabric the inventory never declared can still be laid
    out, just never validated against a larger slice)."""
    FAULTS.check("mesh.build")
    if not coords_by_key:
        MESH_BUILDS.inc(labels={"outcome": "refused"})
        raise MeshBuildError("empty allocation: no chips to lay out")
    arrival = sorted(coords_by_key.items())
    seen: Dict[Coord, Tuple[int, int]] = {}
    for key, c in arrival:
        if any(v < 0 for v in c):
            MESH_BUILDS.inc(labels={"outcome": "refused"})
            raise MeshBuildError(f"negative coordinate {c} for chip {key}")
        if c in seen:
            MESH_BUILDS.inc(labels={"outcome": "refused"})
            raise MeshBuildError(
                f"chips {seen[c]} and {key} share coordinate {c}")
        seen[c] = key
    if slice_dims is None:
        lo = tuple(min(c[i] for c in seen) for i in range(3))
        hi = tuple(max(c[i] for c in seen) for i in range(3))
        if lo != (0, 0, 0):
            # Normalize an undeclared fabric to its own origin so the
            # hop model sees the same block wherever it sits — the
            # arrival list shifts WITH it, so rank indices keep naming
            # the same chips.
            arrival = [(k, (c[0] - lo[0], c[1] - lo[1], c[2] - lo[2]))
                       for k, c in arrival]
            seen = {c: k for k, c in arrival}
            hi = tuple(hi[i] - lo[i] for i in range(3))
        slice_dims = (hi[0] + 1, hi[1] + 1, hi[2] + 1)
    else:
        for c in seen:
            if any(c[i] >= slice_dims[i] for i in range(3)):
                MESH_BUILDS.inc(labels={"outcome": "refused"})
                raise MeshBuildError(
                    f"coordinate {c} outside declared slice topology "
                    f"{format_topology(slice_dims)}")
    mesh = slice_mesh_for(slice_dims, generation)
    ordered = snake_order(seen)
    index_of = {c: i for i, (_k, c) in enumerate(arrival)}
    order = tuple(index_of[c] for c in ordered)
    chip_keys = tuple(arrival[i][0] for i in order)
    hops = tuple(ring_hops(ordered, mesh))
    contiguous = is_contiguous_block(ordered, mesh)
    plan = MeshPlan(
        generation=generation,
        slice_dims=slice_dims,
        coords=tuple(ordered),
        chip_keys=chip_keys,
        order=order,
        contiguous=contiguous,
        hops=hops,
        hop_mean=(sum(hops) / len(hops)) if hops else 0.0,
        hop_max=max(hops) if hops else 0,
        modeled_ici_gbps=modeled_ring_allreduce_gbps(ordered, mesh,
                                                     generation),
        n_workers=n_workers,
    )
    MESH_BUILDS.inc(labels={
        "outcome": "ok" if contiguous else "fragmented"})
    return plan


def _env_chip_coords(env: Dict[str, str], worker: int
                     ) -> Dict[Tuple[int, int], Coord]:
    """Validated {(worker, chip_index): coord} from one claim env: the
    refusal contract shared by the single- and multi-worker plan paths.
    Refuses a missing coordinate export (coordinate-less node) and a
    visible chip with no exported coordinate — each is a rank/topology
    mismatch, not a chip to guess about."""
    coords = parse_chip_coords(env.get(ENV_CHIP_COORDS, ""))
    if not coords:
        raise MeshBuildError(
            f"worker {worker} claim env exports no {ENV_CHIP_COORDS}: "
            "the inventory published no topology (coordinate-less node)")
    visible = []
    for tok in (t.strip() for t in
                env.get("TPU_VISIBLE_CHIPS", "").split(",") if t.strip()):
        if not tok.isdigit():
            # A torn env var must not silently drop chips: a filtered
            # token would build a mesh over a subset of the allocation.
            raise MeshBuildError(
                f"worker {worker} has a malformed TPU_VISIBLE_CHIPS "
                f"entry {tok!r}")
        visible.append(int(tok))
    missing = [i for i in visible if i not in coords]
    if missing:
        raise MeshBuildError(
            f"worker {worker} visible chips {missing} have no exported "
            "coordinate (claim env topology mismatch)")
    return {(worker, i): coords[i] for i in (visible or sorted(coords))}


def plan_from_env(env: Dict[str, str]) -> MeshPlan:
    """MeshPlan from ONE worker's claim CDI env (the workload
    container's view): TPU_VISIBLE_CHIPS selects the chips,
    TPU_CHIP_COORDS places them, TPU_SLICE_TOPOLOGY declares the
    fabric. Refusals per _env_chip_coords.

    Closes the claim's trace loop (SURVEY §19): when the env carries
    TPU_DRA_TRACEPARENT (exported by the prepare pipeline next to the
    coordinates), the build lands as a ``mesh.build`` span on the same
    trace the scheduler started — status error on refusal."""
    span = TRACER.begin("mesh.build", root=True,
                        traceparent=env.get(ENV_TRACEPARENT))
    ok = False
    try:
        worker = int(env.get(ENV_WORKER_INDEX, "0") or 0)
        dims = parse_topology(env.get(ENV_SLICE_TOPOLOGY, ""))
        generation = env.get(ENV_GENERATION, "")
        plan = plan_from_coords(_env_chip_coords(env, worker), dims,
                                generation)
        span.set(n_devices=plan.n_devices, contiguous=plan.contiguous)
        ok = True
        return plan
    finally:
        if ok:
            span.end()
        else:
            span.abandon("mesh build refused")


def plan_from_worker_envs(envs: Sequence[Dict[str, str]]) -> MeshPlan:
    """MeshPlan across a multi-process worker set: each env is one
    worker's claim CDI env (chip coords are GLOBAL slice coordinates)
    merged with its cddaemon identity (TPU_WORKER_ID,
    TPU_WORKER_HOSTNAMES). Refuses non-contiguous worker ids, a peer
    list whose size disagrees with the env count, conflicting slice
    topologies, and overlapping coordinates — each is a symptom of
    workers holding different allocation results, and a mesh built from
    disagreeing views deadlocks or corrupts at first collective."""
    if not envs:
        raise MeshBuildError("no worker envs")
    ids = []
    for env in envs:
        try:
            ids.append(int(env["TPU_WORKER_ID"]))
        except (KeyError, ValueError) as e:
            raise MeshBuildError(
                "worker env missing a parseable TPU_WORKER_ID") from e
    if sorted(ids) != list(range(len(envs))):
        raise MeshBuildError(
            f"worker ids {sorted(ids)} are not the contiguous range "
            f"0..{len(envs) - 1} (rank mismatch)")
    hostnames = {env.get("TPU_WORKER_HOSTNAMES", "") for env in envs}
    hostnames.discard("")
    if len(hostnames) > 1:
        raise MeshBuildError(
            f"workers disagree on the peer list: {sorted(hostnames)}")
    if hostnames:
        n_hosts = len(next(iter(hostnames)).split(","))
        if n_hosts != len(envs):
            raise MeshBuildError(
                f"peer list names {n_hosts} hosts but {len(envs)} "
                "worker envs were provided (rank/topology mismatch)")
    dims_seen = {env.get(ENV_SLICE_TOPOLOGY, "") for env in envs}
    dims_seen.discard("")
    if len(dims_seen) > 1:
        raise MeshBuildError(
            f"workers declare conflicting slice topologies "
            f"{sorted(dims_seen)}")
    dims = parse_topology(next(iter(dims_seen))) if dims_seen else None
    gens_seen = {env.get(ENV_GENERATION, "") for env in envs}
    gens_seen.discard("")
    if len(gens_seen) > 1:
        # One physical slice cannot span generations — disagreement
        # means divergent allocation views, and picking one would also
        # pick the wrong ICI_LINK_GBPS for the modeled numbers.
        raise MeshBuildError(
            f"workers declare conflicting generations {sorted(gens_seen)}")
    generation = next(iter(gens_seen)) if gens_seen else ""
    # Multi-worker builds continue worker 0's claim trace (every worker
    # of one gang computes the identical plan; one span per build call
    # keeps the tree a tree).
    span = TRACER.begin(
        "mesh.build", root=True,
        traceparent=next((e.get(ENV_TRACEPARENT) for e in envs
                          if e.get(ENV_TRACEPARENT)), None),
        attributes={"n_workers": len(envs)})
    ok = False
    try:
        merged: Dict[Tuple[int, int], Coord] = {}
        for env in envs:
            merged.update(_env_chip_coords(env,
                                           int(env["TPU_WORKER_ID"])))
        plan = plan_from_coords(merged, dims, generation,
                                n_workers=len(envs))
        ok = True
        return plan
    finally:
        if ok:
            span.end()
        else:
            span.abandon("mesh build refused")


def plan_from_allocation(claim: Dict, slices: List[Dict]) -> MeshPlan:
    """Control-plane adapter: MeshPlan straight from cluster truth (an
    allocated ResourceClaim + the node's published ResourceSlices),
    bypassing the CDI env — what the chaos walk and controllers use to
    ask 'what mesh would this allocation yield?' without a prepare."""
    from tpu_dra.topology.placement import node_topology_from_slices

    results = (((claim.get("status") or {}).get("allocation") or {})
               .get("devices") or {}).get("results") or []
    if not results:
        raise MeshBuildError("claim has no allocation results")
    pools = {r.get("pool", "") for r in results}
    by_node: Dict[str, List[Dict]] = {}
    for sl in slices:
        node = (sl.get("spec") or {}).get("nodeName")
        if node in pools:
            by_node.setdefault(node, []).append(sl)
    coords: Dict[Tuple[int, int], Coord] = {}
    generation = ""
    dims: Optional[Tuple[int, int, int]] = None
    for w, pool in enumerate(sorted(pools, key=_natural_name_key)):
        topo = node_topology_from_slices(by_node.get(pool, []))
        if topo is None:
            raise MeshBuildError(
                f"node {pool} publishes no usable topology")
        devices = [r.get("device", "") for r in results
                   if r.get("pool", "") == pool]
        for i, dev in enumerate(sorted(devices, key=_natural_name_key)):
            if dev not in topo.coord_of:
                raise MeshBuildError(
                    f"allocated device {dev} carries no coordinate on "
                    f"{pool}")
            # The real chip index where the name carries one (chip-10
            # sorts AND keys as 10, matching the arrival-order contract
            # ordered_devices documents), positional otherwise.
            _head, _sep, tail = dev.rpartition("-")
            key = (w, int(tail) if tail.isdigit() else i)
            if key in coords:
                raise MeshBuildError(
                    f"devices on {pool} collide on chip index "
                    f"{key[1]} ({dev} vs an earlier device)")
            coords[key] = topo.coord_of[dev]
        if dims is None:
            dims = topo.mesh.dims
        gen = next(((_attr_str(d, "generation") or "")
                    for sl in by_node.get(pool, [])
                    for d in (sl.get("spec") or {}).get("devices") or []),
                   "")
        generation = generation or gen
    return plan_from_coords(coords, dims, generation,
                            n_workers=len(pools))


def _attr_str(dev: Dict, name: str) -> Optional[str]:
    a = (dev.get("attributes") or {}).get(name) or {}
    return a.get("string")


def _natural_name_key(name: str):
    """Order names with a trailing integer numerically (chip-10 after
    chip-2, mesh-10 after mesh-2) — lexicographic order would scramble
    ranks on any node with 10+ chips."""
    head, sep, tail = name.rpartition("-")
    if sep and tail.isdigit():
        return (head, int(tail))
    return (name, -1)


def admit_launch(workload: str) -> None:
    """Launch-admission seam consulted before a workload runs on a built
    mesh (``workloads.meshbuild.launch_workload`` and any future
    launcher). Exists so the ``workload.launch`` failure mode — the
    launch layer erroring after the mesh is up — is drivable from chaos
    without importing JAX."""
    FAULTS.check("workload.launch", workload=workload)
