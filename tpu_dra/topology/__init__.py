"""ICI topology subsystem: fabric model + placement scoring.

The blueprint's core TPU-native claim is that ICI-connected slice
provisioning replaces IMEX/MNNVL — which means the driver, not the
workload, owns the fabric model (the composable-driver argument of
arxiv 2506.23628). This package models the ICI mesh/torus per TPU
generation and gives every placement decision a topology to consume:

- ``mesh``       — the :class:`Mesh` model (dims, wraparound, neighbor /
  distance functions), canonical per-generation slice shapes, and
  publish-time coordinate validation.
- ``placement``  — the slice-shape library (cuboid sub-shapes for a chip
  count), the free-set scanner with fragmentation-aware scoring, the
  contiguity verifier, and node-set ranking by inter-node ICI adjacency
  (``sliceId``/``workerIndex``).

Ownership rules (SURVEY §11): the topology layer holds NO allocation
state of its own. The scheduler's ``AllocationIndex`` stays the single
source of truth for taken devices; this package derives a free
coordinate set from it per decision and scores placements over that.
"""

from tpu_dra.topology.mesh import (  # noqa: F401
    Mesh, TopologyError, format_topology, parse_topology, topology_dims,
    validate_chips,
)
from tpu_dra.topology.placement import (  # noqa: F401
    NodeTopology, allocation_violations, best_placement, domain_topology,
    enumerate_placements, enumerate_shapes, is_contiguous_block,
    max_free_cuboid, node_topology_from_slices, rank_candidate_nodes,
)
