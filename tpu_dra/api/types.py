"""API group ``resource.tpu.dev/v1beta1``: opaque config kinds + ComputeDomain.

TPU-native re-design of api/nvidia.com/resource/v1beta1 (reference):

- ``GpuConfig``        -> ``TpuConfig``        (gpuconfig.go:29-89)
- ``MigDeviceConfig``  -> ``SubsliceConfig``   (migconfig.go:28-77) — a TPU
  chip exposes TensorCore subslices instead of MIG GPU instances.
- ``VfioDeviceConfig`` -> ``PassthroughConfig`` (vfiodeviceconfig.go:28-54)
- ``ComputeDomainChannelConfig`` / ``ComputeDomainDaemonConfig``
  (computedomainconfig.go:30-105) — unchanged shape: they carry the domain
  UID (and allocation mode) from the controller-stamped ResourceClaimTemplate
  into the node-side prepare path.
- Sharing (sharing.go:28-273): ``TimeSlicing`` is kept (libtpu programs are
  time-multiplexed per-chip by the accel driver); ``MPS`` becomes
  ``Multiprocess`` — concurrent libtpu processes on one chip with per-process
  HBM limits and a TensorCore percentage, the TPU analog of MPS
  active-thread-percentage / pinned-device-memory limits.
- ``ComputeDomain`` CRD (computedomain.go:37-139): same spec/status machine;
  the per-node ``cliqueID`` (NVLink partition id) becomes ``sliceID`` (the
  ICI-slice identity: hosts with equal sliceID are ICI-reachable; hosts with
  different sliceIDs coexist in one domain and talk over DCN — the
  heterogeneous-CD analog).

All types implement ``normalize()`` and ``validate()`` (api.go:40-46
``Interface``), are (de)serialized via ``from_dict(strict=...)`` /
``to_dict()``, and are registered with the scheme in
``tpu_dra.api.scheme``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from tpu_dra.infra import featuregates
from tpu_dra.infra.quantity import Quantity

GROUP = "resource.tpu.dev"
VERSION = "v1beta1"
API_VERSION = f"{GROUP}/{VERSION}"

# DRA driver names (reference: gpu.nvidia.com / compute-domain.nvidia.com).
TPU_DRIVER_NAME = "tpu.dev"
COMPUTE_DOMAIN_DRIVER_NAME = "compute-domain.tpu.dev"

# ComputeDomain orchestration constants shared by controller, daemon and
# CD kubelet plugin (reference: resource.nvidia.com/computeDomain node label,
# cd-controller computedomain.go finalizer, deviceclass templates).
COMPUTE_DOMAIN_LABEL_KEY = "resource.tpu.dev/computeDomain"
COMPUTE_DOMAIN_FINALIZER = "resource.tpu.dev/computeDomain"
DEVICE_CLASS_DAEMON = "compute-domain-daemon.tpu.dev"
DEVICE_CLASS_CHANNEL = "compute-domain-default-channel.tpu.dev"

TPU_CONFIG_KIND = "TpuConfig"
SUBSLICE_CONFIG_KIND = "SubsliceConfig"
PASSTHROUGH_CONFIG_KIND = "PassthroughConfig"
COMPUTE_DOMAIN_CHANNEL_CONFIG_KIND = "ComputeDomainChannelConfig"
COMPUTE_DOMAIN_DAEMON_CONFIG_KIND = "ComputeDomainDaemonConfig"
COMPUTE_DOMAIN_KIND = "ComputeDomain"

COMPUTE_DOMAIN_STATUS_READY = "Ready"
COMPUTE_DOMAIN_STATUS_NOT_READY = "NotReady"
# Failure-domain state (SURVEY §18): a CD that WAS Ready and lost a
# member (node death, daemon crash) — workloads already running on it
# learn they are degraded (with status.statusReason naming why) instead
# of the domain silently reading as a never-started NotReady. Recovery
# (the member set converging ready again) republishes Ready cleanly.
COMPUTE_DOMAIN_STATUS_DEGRADED = "Degraded"
ALLOCATION_MODE_SINGLE = "Single"
ALLOCATION_MODE_ALL = "All"

# Sharing strategies (sharing.go TimeSlicingStrategy / MpsStrategy analogs).
TimeSlicingStrategy = "TimeSlicing"
MultiprocessStrategy = "Multiprocess"

# Time-slice intervals (sharing.go: Default/Short/Medium/Long). The value is
# the program-scheduler quantum in microseconds that the node-side manager
# programs into the accel driver (the `nvidia-smi compute-policy
# --set-timeslice` analog); "Default" (0) resets to the driver default.
# Single source of truth — the sharing manager indexes this same map.
TIME_SLICE_INTERVALS = {"Default": 0, "Short": 1000, "Medium": 5000,
                        "Long": 20000}
DEFAULT_TIME_SLICE = "Default"


class ValidationError(ValueError):
    pass


def _unknown_fields(data: Dict[str, Any], allowed: set, strict: bool, path: str):
    _require_type(data, dict, path)
    if not strict:
        return
    unknown = set(data) - allowed
    if unknown:
        raise ValidationError(
            f"strict decoding error: unknown field(s) {sorted(unknown)} in {path}")


def _require_type(val, typ, path: str):
    if not isinstance(val, typ):
        raise ValidationError(f"{path}: expected {typ.__name__}, got {type(val).__name__}")
    return val


# ---------------------------------------------------------------------------
# Sharing
# ---------------------------------------------------------------------------

@dataclass
class TimeSlicingConfig:
    """Per-chip program time-slice length (sharing.go:86-118 analog)."""
    interval: str = DEFAULT_TIME_SLICE

    @classmethod
    def from_dict(cls, data: Dict[str, Any], strict: bool, path: str = "timeSlicingConfig"):
        _unknown_fields(data, {"interval"}, strict, path)
        return cls(interval=data.get("interval", DEFAULT_TIME_SLICE))

    def to_dict(self) -> Dict[str, Any]:
        return {"interval": self.interval}

    def validate(self):
        if self.interval not in TIME_SLICE_INTERVALS:
            raise ValidationError(
                f"unknown time-slice interval: {self.interval!r} "
                f"(must be one of {sorted(TIME_SLICE_INTERVALS)})")

    def interval_us(self) -> int:
        return TIME_SLICE_INTERVALS[self.interval]


@dataclass
class MultiprocessPerDeviceHbmLimit:
    """Map of device selector -> HBM byte limit for one multiprocess tenant.

    Analog of MpsPerDevicePinnedMemoryLimit (sharing.go:176-273). Keys are
    chip UUIDs, chip indices (stringified ints), or ``"default"``; values are
    k8s quantities. ``normalize()`` resolves the map against the actual
    devices of a claim: explicit per-device entries win over ``default``.
    """
    limits: Dict[str, str] = field(default_factory=dict)

    @classmethod
    def from_dict(cls, data: Dict[str, Any], strict: bool, path: str):
        _require_type(data, dict, path)
        return cls(limits=dict(data))

    def to_dict(self) -> Dict[str, str]:
        return dict(self.limits)

    def validate(self):
        for key, raw in self.limits.items():
            try:
                Quantity(raw)
            except ValueError as e:
                raise ValidationError(f"perDeviceHbmLimit[{key}]: {e}") from e

    def normalize(self, uuids: List[str], indices: Dict[str, int],
                  default_limit: Optional[str]) -> Dict[str, int]:
        """Resolve to {uuid: bytes} for the given claim devices.

        Mirrors MpsPerDevicePinnedMemoryLimit.Normalize (sharing.go:217-273):
        index keys are translated to UUIDs, "default" (or the config-level
        default limit) fills every unlisted device.
        """
        resolved: Dict[str, int] = {}
        default = self.limits.get("default", default_limit)
        if default is not None:
            for uuid in uuids:
                resolved[uuid] = Quantity(default).value
        index_to_uuid = {str(i): u for u, i in indices.items()}
        for key, raw in self.limits.items():
            if key == "default":
                continue
            uuid = index_to_uuid.get(key, key)
            if uuid not in uuids:
                raise ValidationError(
                    f"perDeviceHbmLimit: device {key!r} is not part of this claim")
            resolved[uuid] = Quantity(raw).value
        return resolved


@dataclass
class MultiprocessConfig:
    """Concurrent libtpu processes on one chip (MpsConfig analog,
    sharing.go:120-174). ``activeCoresPercentage`` caps the share of
    TensorCores a tenant may occupy (active-thread-percentage analog);
    HBM limits become per-process premapped-HBM caps exported as
    ``TPU_HBM_LIMIT_BYTES`` by the multiprocess manager."""
    default_active_cores_percentage: Optional[int] = None
    default_hbm_limit: Optional[str] = None
    per_device_hbm_limit: Optional[MultiprocessPerDeviceHbmLimit] = None

    @classmethod
    def from_dict(cls, data: Dict[str, Any], strict: bool, path: str = "multiprocessConfig"):
        allowed = {"defaultActiveCoresPercentage", "defaultHbmLimit", "perDeviceHbmLimit"}
        _unknown_fields(data, allowed, strict, path)
        per_dev = None
        if "perDeviceHbmLimit" in data:
            per_dev = MultiprocessPerDeviceHbmLimit.from_dict(
                data["perDeviceHbmLimit"], strict, f"{path}.perDeviceHbmLimit")
        return cls(
            default_active_cores_percentage=data.get("defaultActiveCoresPercentage"),
            default_hbm_limit=data.get("defaultHbmLimit"),
            per_device_hbm_limit=per_dev,
        )

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        if self.default_active_cores_percentage is not None:
            out["defaultActiveCoresPercentage"] = self.default_active_cores_percentage
        if self.default_hbm_limit is not None:
            out["defaultHbmLimit"] = self.default_hbm_limit
        if self.per_device_hbm_limit is not None:
            out["perDeviceHbmLimit"] = self.per_device_hbm_limit.to_dict()
        return out

    def validate(self):
        pct = self.default_active_cores_percentage
        if pct is not None and not (0 < pct <= 100):
            raise ValidationError(
                f"defaultActiveCoresPercentage must be in (0, 100], got {pct}")
        if self.default_hbm_limit is not None:
            try:
                Quantity(self.default_hbm_limit)
            except ValueError as e:
                raise ValidationError(f"defaultHbmLimit: {e}") from e
        if self.per_device_hbm_limit is not None:
            self.per_device_hbm_limit.validate()


@dataclass
class TpuSharing:
    """Sharing strategy selector (GpuSharing analog, sharing.go:28-84)."""
    strategy: str = TimeSlicingStrategy
    time_slicing_config: Optional[TimeSlicingConfig] = None
    multiprocess_config: Optional[MultiprocessConfig] = None

    @classmethod
    def from_dict(cls, data: Dict[str, Any], strict: bool, path: str = "sharing"):
        allowed = {"strategy", "timeSlicingConfig", "multiprocessConfig"}
        _unknown_fields(data, allowed, strict, path)
        ts = mp = None
        if "timeSlicingConfig" in data and data["timeSlicingConfig"] is not None:
            ts = TimeSlicingConfig.from_dict(
                data["timeSlicingConfig"], strict, f"{path}.timeSlicingConfig")
        if "multiprocessConfig" in data and data["multiprocessConfig"] is not None:
            mp = MultiprocessConfig.from_dict(
                data["multiprocessConfig"], strict, f"{path}.multiprocessConfig")
        return cls(strategy=data.get("strategy", ""), time_slicing_config=ts,
                   multiprocess_config=mp)

    def to_dict(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"strategy": self.strategy}
        if self.time_slicing_config is not None:
            out["timeSlicingConfig"] = self.time_slicing_config.to_dict()
        if self.multiprocess_config is not None:
            out["multiprocessConfig"] = self.multiprocess_config.to_dict()
        return out

    def validate(self):
        """Gate-aware validation (validate.go:26-95): a strategy is only
        valid while its feature gate is enabled — a gated-off strategy is
        'unknown', exactly as the reference treats it."""
        if (self.strategy == TimeSlicingStrategy
                and featuregates.enabled(featuregates.TimeSlicingSettings)):
            if self.multiprocess_config is not None:
                raise ValidationError(
                    "multiprocessConfig set with TimeSlicing strategy")
            if self.time_slicing_config is not None:
                self.time_slicing_config.validate()
        elif (self.strategy == MultiprocessStrategy
                and featuregates.enabled(featuregates.MultiprocessSupport)):
            if self.time_slicing_config is not None:
                raise ValidationError(
                    "timeSlicingConfig set with Multiprocess strategy")
            if self.multiprocess_config is not None:
                self.multiprocess_config.validate()
        else:
            raise ValidationError(
                f"unknown TPU sharing strategy: {self.strategy!r} "
                "(is its feature gate enabled?)")

    def is_time_slicing(self) -> bool:
        return self.strategy == TimeSlicingStrategy

    def is_multiprocess(self) -> bool:
        return self.strategy == MultiprocessStrategy


# ---------------------------------------------------------------------------
# Opaque config kinds
# ---------------------------------------------------------------------------

class _ConfigBase:
    KIND = ""

    def type_meta(self) -> Dict[str, str]:
        return {"apiVersion": API_VERSION, "kind": self.KIND}


@dataclass
class _SharingConfigBase(_ConfigBase):
    """Shared machinery for the two sharing-carrying config kinds; the
    reference duplicates this between GpuConfig and MigDeviceConfig
    (gpuconfig.go:52-77 / migconfig.go:52-70)."""
    sharing: Optional[TpuSharing] = None

    @classmethod
    def from_dict(cls, data: Dict[str, Any], strict: bool = True):
        _unknown_fields(data, {"apiVersion", "kind", "sharing"}, strict, self_path(cls))
        sharing = None
        if data.get("sharing") is not None:
            sharing = TpuSharing.from_dict(data["sharing"], strict, "sharing")
        return cls(sharing=sharing)

    def to_dict(self) -> Dict[str, Any]:
        out = self.type_meta()
        if self.sharing is not None:
            out["sharing"] = self.sharing.to_dict()
        return out

    def normalize(self):
        """Fill implied defaults (gpuconfig.go Normalize :52-77)."""
        if self.sharing is None:
            if not featuregates.enabled(featuregates.TimeSlicingSettings):
                return
            self.sharing = TpuSharing(strategy=TimeSlicingStrategy)
        if featuregates.enabled(featuregates.TimeSlicingSettings):
            if (self.sharing.strategy == TimeSlicingStrategy
                    and self.sharing.time_slicing_config is None):
                self.sharing.time_slicing_config = TimeSlicingConfig(DEFAULT_TIME_SLICE)
        if featuregates.enabled(featuregates.MultiprocessSupport):
            if (self.sharing.strategy == MultiprocessStrategy
                    and self.sharing.multiprocess_config is None):
                self.sharing.multiprocess_config = MultiprocessConfig()

    def validate(self):
        if self.sharing is not None:
            self.sharing.validate()


@dataclass
class TpuConfig(_SharingConfigBase):
    """Per-claim config for a whole TPU chip (GpuConfig analog,
    gpuconfig.go:29-89)."""
    KIND = TPU_CONFIG_KIND

    @classmethod
    def default(cls) -> "TpuConfig":
        cfg = cls()
        if featuregates.enabled(featuregates.TimeSlicingSettings):
            cfg.sharing = TpuSharing(
                strategy=TimeSlicingStrategy,
                time_slicing_config=TimeSlicingConfig(interval=DEFAULT_TIME_SLICE))
        return cfg


@dataclass
class SubsliceConfig(_SharingConfigBase):
    """Per-claim config for a TensorCore subslice of a chip (MigDeviceConfig
    analog, migconfig.go:28-77). The subslice *shape* is chosen by the
    scheduler via device selection (subslice devices are advertised like MIG
    profiles); this config only carries sharing settings for it."""
    KIND = SUBSLICE_CONFIG_KIND


@dataclass
class PassthroughConfig(_ConfigBase):
    """Whole-device VM passthrough marker (VfioDeviceConfig analog,
    vfiodeviceconfig.go:28-54): no fields; selecting it routes prepare
    through the vfio bind path. Feature-gated by PassthroughSupport."""
    KIND = PASSTHROUGH_CONFIG_KIND

    @classmethod
    def from_dict(cls, data: Dict[str, Any], strict: bool = True):
        _unknown_fields(data, {"apiVersion", "kind"}, strict, self_path(cls))
        return cls()

    def to_dict(self) -> Dict[str, Any]:
        return self.type_meta()

    def normalize(self):
        pass

    def validate(self):
        if not featuregates.enabled(featuregates.PassthroughSupport):
            raise ValidationError(
                "PassthroughConfig requires the PassthroughSupport feature gate")


@dataclass
class ComputeDomainChannelConfig(_ConfigBase):
    """Carried by the workload ResourceClaimTemplate the controller stamps
    per ComputeDomain (computedomainconfig.go:30-66)."""
    KIND = COMPUTE_DOMAIN_CHANNEL_CONFIG_KIND
    domain_id: str = ""
    allocation_mode: str = ALLOCATION_MODE_SINGLE

    @classmethod
    def from_dict(cls, data: Dict[str, Any], strict: bool = True):
        _unknown_fields(data, {"apiVersion", "kind", "domainID", "allocationMode"},
                        strict, self_path(cls))
        return cls(domain_id=data.get("domainID", ""),
                   allocation_mode=data.get("allocationMode", ALLOCATION_MODE_SINGLE))

    def to_dict(self) -> Dict[str, Any]:
        out = self.type_meta()
        out["domainID"] = self.domain_id
        out["allocationMode"] = self.allocation_mode
        return out

    def normalize(self):
        if not self.allocation_mode:
            self.allocation_mode = ALLOCATION_MODE_SINGLE

    def validate(self):
        if not self.domain_id:
            raise ValidationError("domainID must be set")
        if self.allocation_mode not in (ALLOCATION_MODE_SINGLE, ALLOCATION_MODE_ALL):
            raise ValidationError(
                f"allocationMode must be Single or All, got {self.allocation_mode!r}")


@dataclass
class ComputeDomainDaemonConfig(_ConfigBase):
    """Carried by the daemon ResourceClaimTemplate (computedomainconfig.go:68-105)."""
    KIND = COMPUTE_DOMAIN_DAEMON_CONFIG_KIND
    domain_id: str = ""

    @classmethod
    def from_dict(cls, data: Dict[str, Any], strict: bool = True):
        _unknown_fields(data, {"apiVersion", "kind", "domainID"}, strict, self_path(cls))
        return cls(domain_id=data.get("domainID", ""))

    def to_dict(self) -> Dict[str, Any]:
        out = self.type_meta()
        out["domainID"] = self.domain_id
        return out

    def normalize(self):
        pass

    def validate(self):
        if not self.domain_id:
            raise ValidationError("domainID must be set")


# ---------------------------------------------------------------------------
# ComputeDomain CRD
# ---------------------------------------------------------------------------

@dataclass
class ComputeDomainResourceClaimTemplate:
    name: str = ""

    @classmethod
    def from_dict(cls, data: Dict[str, Any], strict: bool, path: str):
        _unknown_fields(data, {"name"}, strict, path)
        return cls(name=data.get("name", ""))

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name}


@dataclass
class ComputeDomainChannelSpec:
    resource_claim_template: ComputeDomainResourceClaimTemplate = field(
        default_factory=ComputeDomainResourceClaimTemplate)
    allocation_mode: str = ALLOCATION_MODE_SINGLE

    @classmethod
    def from_dict(cls, data: Dict[str, Any], strict: bool, path: str = "spec.channel"):
        _unknown_fields(data, {"resourceClaimTemplate", "allocationMode"}, strict, path)
        rct = ComputeDomainResourceClaimTemplate.from_dict(
            data.get("resourceClaimTemplate", {}), strict, f"{path}.resourceClaimTemplate")
        return cls(resource_claim_template=rct,
                   allocation_mode=data.get("allocationMode", ALLOCATION_MODE_SINGLE))

    def to_dict(self) -> Dict[str, Any]:
        return {"resourceClaimTemplate": self.resource_claim_template.to_dict(),
                "allocationMode": self.allocation_mode}


@dataclass
class ComputeDomainSpec:
    """Spec is immutable after creation (CEL ``self == oldSelf``,
    computedomain.go:59; enforced by the CRD manifest in tpu_dra.api.crd).

    ``numNodes`` keeps the reference's deprecated semantics
    (computedomain.go:63-88): with SliceDaemonsWithDNSNames (default) it only
    drives the global Ready status; daemons start eagerly and workload pods
    release as soon as their local daemon is ready."""
    num_nodes: int = 0
    channel: Optional[ComputeDomainChannelSpec] = None

    @classmethod
    def from_dict(cls, data: Dict[str, Any], strict: bool, path: str = "spec"):
        _unknown_fields(data, {"numNodes", "channel"}, strict, path)
        channel = None
        if data.get("channel") is not None:
            channel = ComputeDomainChannelSpec.from_dict(data["channel"], strict)
        return cls(num_nodes=data.get("numNodes", 0), channel=channel)

    def to_dict(self) -> Dict[str, Any]:
        return {"numNodes": self.num_nodes,
                "channel": self.channel.to_dict() if self.channel else None}


@dataclass
class ComputeDomainNode:
    """One node registered into the domain (computedomain.go:117-139).

    ``slice_id`` replaces cliqueID: it identifies the ICI slice (NVLink
    clique analog) this host belongs to. (slice_id, index) is unique; the
    index pins the host's stable DNS name within its slice. An empty
    slice_id marks a DCN-only participant (heterogeneous domain,
    cd-daemon main.go:205-213)."""
    name: str = ""
    ip_address: str = ""
    slice_id: str = ""
    index: int = 0
    status: str = COMPUTE_DOMAIN_STATUS_NOT_READY

    @classmethod
    def from_dict(cls, data: Dict[str, Any], strict: bool, path: str):
        _unknown_fields(data, {"name", "ipAddress", "sliceID", "index", "status"},
                        strict, path)
        return cls(name=data.get("name", ""), ip_address=data.get("ipAddress", ""),
                   slice_id=data.get("sliceID", ""), index=data.get("index", 0),
                   status=data.get("status", COMPUTE_DOMAIN_STATUS_NOT_READY))

    def to_dict(self) -> Dict[str, Any]:
        return {"name": self.name, "ipAddress": self.ip_address,
                "sliceID": self.slice_id, "index": self.index, "status": self.status}


@dataclass
class ComputeDomainStatus:
    status: str = COMPUTE_DOMAIN_STATUS_NOT_READY
    nodes: List[ComputeDomainNode] = field(default_factory=list)

    @classmethod
    def from_dict(cls, data: Dict[str, Any], strict: bool, path: str = "status"):
        _unknown_fields(data, {"status", "nodes"}, strict, path)
        raw_nodes = data.get("nodes") or []
        _require_type(raw_nodes, list, f"{path}.nodes")
        nodes = [ComputeDomainNode.from_dict(n, strict, f"{path}.nodes[{i}]")
                 for i, n in enumerate(raw_nodes)]
        return cls(status=data.get("status", COMPUTE_DOMAIN_STATUS_NOT_READY), nodes=nodes)

    def to_dict(self) -> Dict[str, Any]:
        return {"status": self.status, "nodes": [n.to_dict() for n in self.nodes]}


@dataclass
class ComputeDomain(_ConfigBase):
    """The ComputeDomain CR (computedomain.go:37-56): prepares a set of nodes
    to run a multi-node workload over ICI/DCN."""
    KIND = COMPUTE_DOMAIN_KIND
    metadata: Dict[str, Any] = field(default_factory=dict)
    spec: ComputeDomainSpec = field(default_factory=ComputeDomainSpec)
    status: ComputeDomainStatus = field(default_factory=ComputeDomainStatus)

    @classmethod
    def from_dict(cls, data: Dict[str, Any], strict: bool = True):
        _unknown_fields(data, {"apiVersion", "kind", "metadata", "spec", "status"},
                        strict, self_path(cls))
        metadata = data.get("metadata") or {}
        _require_type(metadata, dict, "metadata")
        spec = ComputeDomainSpec.from_dict(data.get("spec") or {}, strict)
        status = ComputeDomainStatus.from_dict(data.get("status") or {}, strict)
        return cls(metadata=dict(metadata), spec=spec, status=status)

    def to_dict(self) -> Dict[str, Any]:
        out = self.type_meta()
        out["metadata"] = self.metadata
        out["spec"] = self.spec.to_dict()
        out["status"] = self.status.to_dict()
        return out

    def normalize(self):
        if self.spec.channel is not None and not self.spec.channel.allocation_mode:
            self.spec.channel.allocation_mode = ALLOCATION_MODE_SINGLE

    def validate(self):
        if self.spec.num_nodes < 0:
            raise ValidationError("spec.numNodes must be >= 0")
        if self.spec.channel is None:
            raise ValidationError("spec.channel must be set")
        if not self.spec.channel.resource_claim_template.name:
            raise ValidationError("spec.channel.resourceClaimTemplate.name must be set")
        if self.spec.channel.allocation_mode not in (
                ALLOCATION_MODE_SINGLE, ALLOCATION_MODE_ALL):
            raise ValidationError(
                "spec.channel.allocationMode must be Single or All")

    @property
    def uid(self) -> str:
        return self.metadata.get("uid", "")

    @property
    def name(self) -> str:
        return self.metadata.get("name", "")

    @property
    def namespace(self) -> str:
        return self.metadata.get("namespace", "")


def self_path(cls) -> str:
    return getattr(cls, "KIND", cls.__name__)
