"""L6 API group ``resource.tpu.dev/v1beta1``.

Reference: api/nvidia.com/resource/v1beta1 — opaque per-claim config kinds,
sharing types, the ComputeDomain CRD, and strict/non-strict decoders.
"""

from tpu_dra.api.types import (  # noqa: F401
    GROUP, VERSION, API_VERSION,
    TPU_DRIVER_NAME, COMPUTE_DOMAIN_DRIVER_NAME,
    TpuConfig, SubsliceConfig, PassthroughConfig,
    ComputeDomainChannelConfig, ComputeDomainDaemonConfig,
    TpuSharing, TimeSlicingConfig, MultiprocessConfig,
    TimeSlicingStrategy, MultiprocessStrategy,
    MultiprocessPerDeviceHbmLimit,
    ComputeDomain, ComputeDomainSpec, ComputeDomainChannelSpec,
    ComputeDomainResourceClaimTemplate, ComputeDomainStatus, ComputeDomainNode,
    COMPUTE_DOMAIN_STATUS_READY, COMPUTE_DOMAIN_STATUS_NOT_READY,
    ALLOCATION_MODE_SINGLE, ALLOCATION_MODE_ALL,
)
from tpu_dra.api.scheme import (  # noqa: F401
    StrictDecoder, NonstrictDecoder, Scheme, DecodeError,
)
