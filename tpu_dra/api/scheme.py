"""Scheme + strict/non-strict decoders for the resource.tpu.dev group.

Reference: api/nvidia.com/resource/v1beta1/api.go:40-96. The StrictDecoder
rejects unknown fields and is used for user-supplied opaque configs (webhook
and NodePrepareResources); the NonstrictDecoder drops unknown fields and is
used for checkpoint round-trips so a downgraded driver can still read
checkpoints written by a newer version.
"""

from __future__ import annotations

import json
from typing import Any, Dict, Type

from tpu_dra.api import types as t


class DecodeError(ValueError):
    pass


class Scheme:
    """Registry of (apiVersion, kind) -> type, with decode helpers."""

    def __init__(self):
        self._kinds: Dict[tuple, Type] = {}

    def add_known_type(self, api_version: str, kind: str, cls: Type):
        self._kinds[(api_version, kind)] = cls

    def recognizes(self, api_version: str, kind: str) -> bool:
        return (api_version, kind) in self._kinds

    def decode(self, data, strict: bool):
        """Decode a JSON document (str/bytes/dict) into a registered type."""
        if isinstance(data, (str, bytes)):
            try:
                data = json.loads(data)
            except json.JSONDecodeError as e:
                raise DecodeError(f"invalid JSON: {e}") from e
        if not isinstance(data, dict):
            raise DecodeError(f"expected JSON object, got {type(data).__name__}")
        api_version = data.get("apiVersion", "")
        kind = data.get("kind", "")
        cls = self._kinds.get((api_version, kind))
        if cls is None:
            raise DecodeError(
                f"no kind {kind!r} registered for version {api_version!r}")
        try:
            return cls.from_dict(data, strict=strict)
        except t.ValidationError as e:
            raise DecodeError(str(e)) from e

    def encode(self, obj) -> str:
        return json.dumps(obj.to_dict(), separators=(",", ":"), sort_keys=True)


_scheme = Scheme()
for _cls in (t.TpuConfig, t.SubsliceConfig, t.PassthroughConfig,
             t.ComputeDomainChannelConfig, t.ComputeDomainDaemonConfig,
             t.ComputeDomain):
    _scheme.add_known_type(t.API_VERSION, _cls.KIND, _cls)


class _Decoder:
    def __init__(self, scheme: Scheme, strict: bool):
        self._scheme = scheme
        self._strict = strict

    def decode(self, data):
        return self._scheme.decode(data, strict=self._strict)


DefaultScheme = _scheme
StrictDecoder = _Decoder(_scheme, strict=True)
NonstrictDecoder = _Decoder(_scheme, strict=False)
