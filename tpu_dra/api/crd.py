"""ComputeDomain CRD manifest (reference: the CRD in
deployments/helm/nvidia-dra-driver-gpu/crds/, with the CEL spec-immutability
rule of computedomain.go:59 and the status subresource).

Generated as a dict so the deploy tool renders it to YAML and the fake
apiserver tier can introspect the schema.
"""

from __future__ import annotations

from typing import Dict

from tpu_dra.api.types import GROUP, VERSION


def compute_domain_crd() -> Dict:
    node_props = {
        "name": {"type": "string"},
        "ipAddress": {"type": "string"},
        "sliceID": {"type": "string"},
        "index": {"type": "integer"},
        "status": {"type": "string", "enum": ["Ready", "NotReady"]},
    }
    spec_schema = {
        "type": "object",
        # Spec is immutable after creation (computedomain.go:59).
        "x-kubernetes-validations": [{
            "rule": "self == oldSelf",
            "message": "ComputeDomain spec is immutable",
        }],
        "properties": {
            "numNodes": {
                "type": "integer",
                "minimum": 0,
                "description": "Deprecated: drives only the global Ready "
                               "status; daemons start eagerly and workloads "
                               "release on local readiness.",
            },
            "channel": {
                "type": "object",
                "required": ["resourceClaimTemplate"],
                "properties": {
                    "resourceClaimTemplate": {
                        "type": "object",
                        "required": ["name"],
                        "properties": {"name": {"type": "string",
                                                "minLength": 1}},
                    },
                    "allocationMode": {
                        "type": "string",
                        "enum": ["Single", "All"],
                        "default": "Single",
                    },
                },
            },
        },
        "required": ["channel"],
    }
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"computedomains.{GROUP}"},
        "spec": {
            "group": GROUP,
            "scope": "Namespaced",
            "names": {
                "plural": "computedomains",
                "singular": "computedomain",
                "kind": "ComputeDomain",
                "shortNames": ["cd"],
            },
            "versions": [{
                "name": VERSION,
                "served": True,
                "storage": True,
                "subresources": {"status": {}},
                "schema": {"openAPIV3Schema": {
                    "type": "object",
                    "properties": {
                        "spec": spec_schema,
                        "status": {
                            "type": "object",
                            "properties": {
                                "status": {"type": "string",
                                           "enum": ["Ready", "NotReady"]},
                                "nodes": {
                                    "type": "array",
                                    "items": {"type": "object",
                                              "properties": node_props},
                                },
                                # ICI placement summary the controller
                                # stamps on multi-node domains under the
                                # TopologyAwareScheduling gate (without
                                # it a structural schema would prune the
                                # field).
                                "topology": {
                                    "type": "object",
                                    "properties": {
                                        "slices": {"type": "integer"},
                                        "sliceAligned": {"type": "boolean"},
                                    },
                                },
                            },
                        },
                    },
                }},
                "additionalPrinterColumns": [
                    {"name": "Status", "type": "string",
                     "jsonPath": ".status.status"},
                    {"name": "Nodes", "type": "integer",
                     "jsonPath": ".spec.numNodes"},
                    {"name": "Age", "type": "date",
                     "jsonPath": ".metadata.creationTimestamp"},
                ],
            }],
        },
    }
