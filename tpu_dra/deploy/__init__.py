"""Deployment manifests (reference: deployments/helm/nvidia-dra-driver-gpu).

Manifest builders for everything a cluster operator installs: the CRD,
DeviceClasses with CEL selectors, the controller Deployment, the
kubelet-plugin DaemonSet, the webhook, a ValidatingAdmissionPolicy, and
RBAC. ``python -m tpu_dra.deploy.render`` writes them as YAML to
deployments/manifests/ (the chart-render analog; Helm itself is not
assumed).
"""
