"""Cluster manifests for the TPU DRA driver.

Reference mapping (deployments/helm/nvidia-dra-driver-gpu/templates/):
- deviceclass-gpu.yaml / -mig.yaml        -> tpu / tpu-subslice DeviceClass
- deviceclass-compute-domain-*.yaml       -> daemon / channel DeviceClass
- controller.yaml                         -> controller Deployment
- kubeletplugin.yaml                      -> plugin DaemonSet (2 plugins)
- webhook.yaml + validatingwebhook        -> webhook Deployment + config
- validatingadmissionpolicy.yaml          -> VAP with CEL opaque-cfg guard
- clusterrole(binding).yaml               -> RBAC
"""

from __future__ import annotations

from typing import Dict, List

from tpu_dra.api import types as apitypes
from tpu_dra.api.crd import compute_domain_crd

APP = "tpu-dra-driver"
DEFAULT_NAMESPACE = "tpu-dra-driver"
DEFAULT_IMAGE = "tpu-dra-driver:latest"
# Gates enabled in the rendered deployment so the shipped demo ladder
# (tpu-test3 time-slicing, tpu-test-multiprocess) works out of the box;
# operators can override.
DEFAULT_FEATURE_GATES = "MultiprocessSupport=true,TimeSlicingSettings=true"


def namespace(ns: str = DEFAULT_NAMESPACE) -> Dict:
    return {"apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": ns}}


# ---------------------------------------------------------------------------
# DeviceClasses (CEL selectors over published device attributes)
# ---------------------------------------------------------------------------

def _device_class(name: str, driver: str, device_type: str,
                  extended_resource: str = "") -> Dict:
    cel = (f'device.driver == "{driver}" && '
           f'device.attributes["{driver}"].type == "{device_type}"')
    spec: Dict = {"selectors": [{"cel": {"expression": cel}}]}
    if extended_resource:
        # v1-only field (the static manifests pin v1); chart parity:
        # templates/deviceclass-tpu.yaml.
        spec = {"extendedResourceName": extended_resource, **spec}
    return {
        "apiVersion": "resource.k8s.io/v1",
        "kind": "DeviceClass",
        "metadata": {"name": name},
        "spec": spec,
    }


def device_classes() -> List[Dict]:
    tpu = apitypes.TPU_DRIVER_NAME
    cd = apitypes.COMPUTE_DOMAIN_DRIVER_NAME
    return [
        _device_class("tpu.dev", tpu, "chip",
                      extended_resource="tpu.dev/tpu"),
        _device_class("tpu-subslice.tpu.dev", tpu, "subslice"),
        _device_class(apitypes.DEVICE_CLASS_DAEMON, cd, "daemon"),
        _device_class(apitypes.DEVICE_CLASS_CHANNEL, cd, "channel"),
    ]


# ---------------------------------------------------------------------------
# RBAC
# ---------------------------------------------------------------------------

def rbac(ns: str = DEFAULT_NAMESPACE) -> List[Dict]:
    rules = [
        {"apiGroups": [apitypes.GROUP],
         "resources": ["computedomains", "computedomains/status"],
         "verbs": ["get", "list", "watch", "create", "update", "patch",
                   "delete"]},
        {"apiGroups": ["resource.k8s.io"],
         "resources": ["resourceclaims", "resourceclaimtemplates",
                       "resourceslices", "deviceclasses"],
         "verbs": ["get", "list", "watch", "create", "update", "patch",
                   "delete"]},
        {"apiGroups": ["apps"], "resources": ["daemonsets", "deployments"],
         "verbs": ["get", "list", "watch", "create", "update", "patch",
                   "delete"]},
        {"apiGroups": [""], "resources": ["nodes", "pods"],
         "verbs": ["get", "list", "watch", "patch", "update"]},
        {"apiGroups": [""], "resources": ["events"],
         "verbs": ["create", "patch"]},
    ]
    return [
        {"apiVersion": "v1", "kind": "ServiceAccount",
         "metadata": {"name": APP, "namespace": ns}},
        {"apiVersion": "rbac.authorization.k8s.io/v1", "kind": "ClusterRole",
         "metadata": {"name": APP}, "rules": rules},
        {"apiVersion": "rbac.authorization.k8s.io/v1",
         "kind": "ClusterRoleBinding",
         "metadata": {"name": APP},
         "roleRef": {"apiGroup": "rbac.authorization.k8s.io",
                     "kind": "ClusterRole", "name": APP},
         "subjects": [{"kind": "ServiceAccount", "name": APP,
                       "namespace": ns}]},
    ]


# ---------------------------------------------------------------------------
# Controller Deployment
# ---------------------------------------------------------------------------

def controller_deployment(ns: str = DEFAULT_NAMESPACE,
                          image: str = DEFAULT_IMAGE) -> Dict:
    labels = {"app.kubernetes.io/name": f"{APP}-controller"}
    return {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": f"{APP}-controller", "namespace": ns,
                     "labels": labels},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": labels},
            "template": {
                "metadata": {"labels": labels},
                "spec": {
                    "serviceAccountName": APP,
                    "priorityClassName": "system-cluster-critical",
                    "containers": [{
                        "name": "controller",
                        "image": image,
                        "command": ["python", "-m",
                                    "tpu_dra.cdcontroller.main"],
                        "env": [
                            {"name": "NAMESPACE", "valueFrom": {"fieldRef": {
                                "fieldPath": "metadata.namespace"}}},
                            {"name": "DAEMON_IMAGE", "value": image},
                            {"name": "HTTP_ENDPOINT_PORT", "value": "8080"},
                        ],
                        "ports": [{"name": "metrics",
                                   "containerPort": 8080}],
                    }],
                },
            },
        },
    }


# ---------------------------------------------------------------------------
# Kubelet plugin DaemonSet (both plugins on every TPU node)
# ---------------------------------------------------------------------------

def kubelet_plugin_daemonset(ns: str = DEFAULT_NAMESPACE,
                             image: str = DEFAULT_IMAGE) -> Dict:
    labels = {"app.kubernetes.io/name": f"{APP}-kubelet-plugin"}
    host_mounts = [
        {"name": "plugins", "hostPath": {
            "path": "/var/lib/kubelet/plugins",
            "type": "DirectoryOrCreate"}},
        {"name": "plugins-registry", "hostPath": {
            "path": "/var/lib/kubelet/plugins_registry",
            "type": "DirectoryOrCreate"}},
        {"name": "cdi", "hostPath": {"path": "/var/run/cdi",
                                     "type": "DirectoryOrCreate"}},
        {"name": "dev", "hostPath": {"path": "/dev"}},
        {"name": "sys", "hostPath": {"path": "/sys"}},
    ]
    mounts = [
        {"name": "plugins", "mountPath": "/var/lib/kubelet/plugins"},
        {"name": "plugins-registry",
         "mountPath": "/var/lib/kubelet/plugins_registry"},
        {"name": "cdi", "mountPath": "/var/run/cdi"},
        {"name": "dev", "mountPath": "/dev"},
        {"name": "sys", "mountPath": "/sys", "readOnly": True},
    ]
    common_env = [
        {"name": "NODE_NAME", "valueFrom": {"fieldRef": {
            "fieldPath": "spec.nodeName"}}},
        {"name": "NAMESPACE", "valueFrom": {"fieldRef": {
            "fieldPath": "metadata.namespace"}}},
        {"name": "FEATURE_GATES", "value": DEFAULT_FEATURE_GATES},
    ]
    return {
        "apiVersion": "apps/v1", "kind": "DaemonSet",
        "metadata": {"name": f"{APP}-kubelet-plugin", "namespace": ns,
                     "labels": labels},
        "spec": {
            "selector": {"matchLabels": labels},
            "template": {
                "metadata": {"labels": labels},
                "spec": {
                    "serviceAccountName": APP,
                    "priorityClassName": "system-node-critical",
                    "nodeSelector": {"tpu.dev/present": "true"},
                    # Prestart validation (the reference's initContainer
                    # validating driver installation, main.go prestart).
                    "initContainers": [{
                        "name": "validate",
                        "image": image,
                        "command": ["python", "-c",
                                    "from tpu_dra.native.tpuinfo import "
                                    "get_backend; "
                                    "print(len(get_backend().chips()), "
                                    "'chips')"],
                        "volumeMounts": mounts,
                    }],
                    # Distinct healthcheck ports: both containers share the
                    # pod network namespace, so a shared HEALTHCHECK_PORT
                    # would make the second bind fail and crashloop.
                    "containers": [
                        {
                            "name": "tpu-plugin",
                            "image": image,
                            "command": ["python", "-m",
                                        "tpu_dra.tpuplugin.main"],
                            "securityContext": {"privileged": True},
                            "env": common_env + [
                                {"name": "COORDINATOR_IMAGE",
                                 "value": image},
                                {"name": "HEALTHCHECK_PORT",
                                 "value": "8081"}],
                            "livenessProbe": {
                                "httpGet": {"path": "/healthz",
                                            "port": 8081},
                                "periodSeconds": 10,
                                "failureThreshold": 3,
                            },
                            "volumeMounts": mounts,
                        },
                        {
                            "name": "cd-plugin",
                            "image": image,
                            "command": ["python", "-m",
                                        "tpu_dra.cdplugin.main"],
                            "securityContext": {"privileged": True},
                            "env": common_env + [
                                {"name": "HEALTHCHECK_PORT",
                                 "value": "8082"}],
                            "livenessProbe": {
                                "httpGet": {"path": "/healthz",
                                            "port": 8082},
                                "periodSeconds": 10,
                                "failureThreshold": 3,
                            },
                            "volumeMounts": mounts,
                        },
                    ],
                    "volumes": host_mounts,
                },
            },
        },
    }


# ---------------------------------------------------------------------------
# Webhook
# ---------------------------------------------------------------------------

def webhook_manifests(ns: str = DEFAULT_NAMESPACE,
                      image: str = DEFAULT_IMAGE,
                      ca_bundle: str = "") -> List[Dict]:
    labels = {"app.kubernetes.io/name": f"{APP}-webhook"}
    deployment = {
        "apiVersion": "apps/v1", "kind": "Deployment",
        "metadata": {"name": f"{APP}-webhook", "namespace": ns,
                     "labels": labels},
        "spec": {
            "replicas": 1,
            "selector": {"matchLabels": labels},
            "template": {
                "metadata": {"labels": labels},
                "spec": {"containers": [{
                    "name": "webhook",
                    "image": image,
                    "command": ["python", "-m", "tpu_dra.webhook.main"],
                    "env": [
                        {"name": "TLS_CERT_FILE",
                         "value": "/etc/webhook/tls/tls.crt"},
                        {"name": "TLS_KEY_FILE",
                         "value": "/etc/webhook/tls/tls.key"},
                        {"name": "FEATURE_GATES",
                         "value": DEFAULT_FEATURE_GATES},
                    ],
                    "ports": [{"containerPort": 8443}],
                    "readinessProbe": {"httpGet": {
                        "path": "/readyz", "port": 8443, "scheme": "HTTPS"}},
                    "volumeMounts": [{"name": "tls",
                                      "mountPath": "/etc/webhook/tls",
                                      "readOnly": True}],
                }],
                    "volumes": [{"name": "tls", "secret": {
                        "secretName": f"{APP}-webhook-tls"}}]},
            },
        },
    }
    service = {
        "apiVersion": "v1", "kind": "Service",
        "metadata": {"name": f"{APP}-webhook", "namespace": ns},
        "spec": {"selector": labels,
                 "ports": [{"port": 443, "targetPort": 8443}]},
    }
    config = {
        "apiVersion": "admissionregistration.k8s.io/v1",
        "kind": "ValidatingWebhookConfiguration",
        "metadata": {"name": f"{APP}-webhook"},
        "webhooks": [{
            "name": "resource-claim-parameters.tpu.dev",
            "admissionReviewVersions": ["v1"],
            "sideEffects": "None",
            "failurePolicy": "Ignore",
            "clientConfig": {
                "service": {"name": f"{APP}-webhook", "namespace": ns,
                            "path": "/validate-resource-claim-parameters"},
                **({"caBundle": ca_bundle} if ca_bundle else {}),
            },
            "rules": [{
                "apiGroups": ["resource.k8s.io"],
                "apiVersions": ["v1", "v1beta1", "v1beta2"],
                "operations": ["CREATE", "UPDATE"],
                "resources": ["resourceclaims", "resourceclaimtemplates"],
            }],
        }],
    }
    return [deployment, service, config]


def validating_admission_policy() -> List[Dict]:
    """Deploy-time CEL guard (validatingadmissionpolicy.yaml analog):
    rejects opaque configs owned by this driver whose apiVersion/kind are
    not among the known ones — a cheap structural gate that works even
    when the webhook is down (failurePolicy Ignore). Two policies, since
    claims ('spec') and templates ('spec.spec') nest the device spec
    differently."""
    known_kinds = [apitypes.TPU_CONFIG_KIND, apitypes.SUBSLICE_CONFIG_KIND,
                   apitypes.PASSTHROUGH_CONFIG_KIND,
                   apitypes.COMPUTE_DOMAIN_CHANNEL_CONFIG_KIND,
                   apitypes.COMPUTE_DOMAIN_DAEMON_CONFIG_KIND]
    kinds_cel = "[" + ", ".join(f"'{k}'" for k in known_kinds) + "]"
    drivers_cel = (f"['{apitypes.TPU_DRIVER_NAME}', "
                   f"'{apitypes.COMPUTE_DOMAIN_DRIVER_NAME}']")

    def _expr(spec_path: str) -> str:
        return (
            f"!has({spec_path}.devices) || "
            f"!has({spec_path}.devices.config) || "
            f"{spec_path}.devices.config.all(c, "
            "!has(c.opaque) || !(c.opaque.driver in " + drivers_cel + ") || "
            "(has(c.opaque.parameters.kind) && "
            "c.opaque.parameters.kind in " + kinds_cel + " && "
            "c.opaque.parameters.apiVersion == '"
            + apitypes.API_VERSION + "'))")

    out: List[Dict] = []
    for suffix, resource, spec_path in (
            ("claims", "resourceclaims", "object.spec"),
            ("templates", "resourceclaimtemplates", "object.spec.spec")):
        name = f"{APP}-opaque-config-{suffix}"
        out.append({
            "apiVersion": "admissionregistration.k8s.io/v1",
            "kind": "ValidatingAdmissionPolicy",
            "metadata": {"name": name},
            "spec": {
                "failurePolicy": "Fail",
                "matchConstraints": {"resourceRules": [{
                    "apiGroups": ["resource.k8s.io"],
                    "apiVersions": ["v1"],
                    "operations": ["CREATE", "UPDATE"],
                    "resources": [resource],
                }]},
                "validations": [{
                    "expression": _expr(spec_path),
                    "message": "opaque device config owned by tpu.dev has "
                               "an unknown kind or apiVersion",
                }],
            },
        })
        out.append({
            "apiVersion": "admissionregistration.k8s.io/v1",
            "kind": "ValidatingAdmissionPolicyBinding",
            "metadata": {"name": name},
            "spec": {"policyName": name, "validationActions": ["Deny"]},
        })
    return out


def all_manifests(ns: str = DEFAULT_NAMESPACE,
                  image: str = DEFAULT_IMAGE,
                  ca_bundle: str = "") -> List[Dict]:
    return ([namespace(ns), compute_domain_crd()]
            + device_classes()
            + rbac(ns)
            + [controller_deployment(ns, image),
               kubelet_plugin_daemonset(ns, image)]
            + webhook_manifests(ns, image, ca_bundle)
            + validating_admission_policy())
