"""Render deployment manifests + demo specs to YAML.

Run: ``python -m tpu_dra.deploy.render -o deployments/manifests``
(the `helm template` analog for this chart-less repo).
"""

from __future__ import annotations

import argparse
import os

import yaml

from tpu_dra.deploy import demos, manifests


def render_all(out_dir: str, ns: str, image: str,
               demo_dir: str = "demo/specs",
               ca_bundle: str = "") -> list:
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, "tpu-dra-driver.yaml")
    docs = manifests.all_manifests(ns, image, ca_bundle)
    with open(path, "w") as f:
        yaml.safe_dump_all(docs, f, sort_keys=False)
    written = [path]
    os.makedirs(demo_dir, exist_ok=True)
    for name, spec_docs in demos.all_demos().items():
        p = os.path.join(demo_dir, f"{name}.yaml")
        with open(p, "w") as f:
            yaml.safe_dump_all(spec_docs, f, sort_keys=False)
        written.append(p)
    return written


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tpu-dra-render")
    ap.add_argument("-o", "--out-dir", default="deployments/manifests")
    ap.add_argument("--demo-dir", default="demo/specs")
    ap.add_argument("--namespace", default=manifests.DEFAULT_NAMESPACE)
    ap.add_argument("--image", default=manifests.DEFAULT_IMAGE)
    ap.add_argument("--ca-bundle", default="",
                    help="base64 CA bundle for the webhook clientConfig "
                         "(pair with the tpu-dra-driver-webhook-tls Secret "
                         "an operator or cert-manager provides)")
    ns = ap.parse_args(argv)
    for path in render_all(ns.out_dir, ns.namespace, ns.image,
                           demo_dir=ns.demo_dir, ca_bundle=ns.ca_bundle):
        print(path)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
