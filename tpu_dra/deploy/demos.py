"""Demo / quickstart specs (reference: demo/specs/quickstart/v1).

The reference's gpu-test1..5 ladder translated to TPU claims, plus the
multi-node ComputeDomain benchmark job (the nvbandwidth/NCCL analog:
a 2-pod JAX psum allreduce over a driver-provisioned slice).
"""

from __future__ import annotations

from typing import Dict, List

from tpu_dra.api import types as apitypes

WORKLOAD_IMAGE = "tpu-dra-driver:latest"


def _ns(name: str) -> Dict:
    return {"apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": name}}


def _rct(name: str, ns: str, device_class: str, count: int = 1,
         config: Dict = None) -> Dict:
    spec: Dict = {"devices": {"requests": [{
        "name": "tpu",
        "exactly": {"deviceClassName": device_class,
                    **({"count": count} if count != 1 else {})},
    }]}}
    if config:
        spec["devices"]["config"] = [{
            "requests": ["tpu"],
            "opaque": {"driver": apitypes.TPU_DRIVER_NAME,
                       "parameters": config}}]
    return {"apiVersion": "resource.k8s.io/v1",
            "kind": "ResourceClaimTemplate",
            "metadata": {"name": name, "namespace": ns},
            "spec": {"spec": spec}}


def _pod(name: str, ns: str, claim_source: Dict,
         command: List[str] = None, containers: int = 1) -> Dict:
    ctrs = []
    for i in range(containers):
        ctrs.append({
            "name": f"ctr{i}" if containers > 1 else "ctr",
            "image": WORKLOAD_IMAGE,
            "command": command or [
                "python", "-c",
                "import os, jax; "
                "print('TPU_VISIBLE_CHIPS=', "
                "os.environ.get('TPU_VISIBLE_CHIPS')); "
                "print('devices:', jax.devices())"],
            "resources": {"claims": [{"name": "tpu"}]},
        })
    return {
        "apiVersion": "v1", "kind": "Pod",
        "metadata": {"name": name, "namespace": ns},
        "spec": {
            "restartPolicy": "Never",
            "containers": ctrs,
            "resourceClaims": [{"name": "tpu", **claim_source}],
        },
    }


# -- the quickstart ladder --------------------------------------------------

def test1_exclusive_per_pod() -> List[Dict]:
    """gpu-test1 analog: two pods, each with its own exclusive chip."""
    ns = "tpu-test1"
    return [_ns(ns), _rct("single-tpu", ns, "tpu.dev"),
            _pod("pod0", ns, {"resourceClaimTemplateName": "single-tpu"}),
            _pod("pod1", ns, {"resourceClaimTemplateName": "single-tpu"})]


def test2_shared_claim_two_containers() -> List[Dict]:
    """gpu-test2 analog: one claim shared by two containers of one pod."""
    ns = "tpu-test2"
    return [_ns(ns), _rct("shared-tpu", ns, "tpu.dev"),
            _pod("pod0", ns, {"resourceClaimTemplateName": "shared-tpu"},
                 containers=2)]


def test3_time_sliced_across_pods() -> List[Dict]:
    """gpu-test3 analog: one ResourceClaim (not template) time-shared by
    two pods."""
    ns = "tpu-test3"
    claim = {
        "apiVersion": "resource.k8s.io/v1", "kind": "ResourceClaim",
        "metadata": {"name": "ts-tpu", "namespace": ns},
        "spec": {"devices": {
            "requests": [{"name": "tpu",
                          "exactly": {"deviceClassName": "tpu.dev"}}],
            "config": [{"requests": ["tpu"], "opaque": {
                "driver": apitypes.TPU_DRIVER_NAME,
                "parameters": {
                    "apiVersion": apitypes.API_VERSION, "kind": "TpuConfig",
                    "sharing": {"strategy": "TimeSlicing",
                                "timeSlicingConfig": {"interval": "Long"}},
                }}}],
        }},
    }
    return [_ns(ns), claim,
            _pod("pod0", ns, {"resourceClaimName": "ts-tpu"}),
            _pod("pod1", ns, {"resourceClaimName": "ts-tpu"})]


def test4_multi_chip() -> List[Dict]:
    """gpu-test4 analog: one pod claiming 4 chips on one host."""
    ns = "tpu-test4"
    return [_ns(ns), _rct("quad-tpu", ns, "tpu.dev", count=4),
            _pod("pod0", ns, {"resourceClaimTemplateName": "quad-tpu"})]


def test5_subslice() -> List[Dict]:
    """gpu-test5/MIG analog: two pods each claiming a TensorCore subslice
    of (potentially) the same chip."""
    ns = "tpu-test5"
    return [_ns(ns), _rct("subslice", ns, "tpu-subslice.tpu.dev"),
            _pod("pod0", ns, {"resourceClaimTemplateName": "subslice"}),
            _pod("pod1", ns, {"resourceClaimTemplateName": "subslice"})]


def test_multiprocess_shared_chip() -> List[Dict]:
    """gpu-test-mps analog (demo/specs/quickstart/v1/gpu-test-mps.yaml):
    one pod, two containers sharing a chip through the
    tpu-multiprocess-coordinator. Each tenant registers a lease on the
    coordinator's socket (the CUDA_MPS_PIPE_DIRECTORY analog) and prints
    the published limits it must honor."""
    ns = "tpu-test-multiprocess"
    config = {
        "apiVersion": apitypes.API_VERSION, "kind": "TpuConfig",
        "sharing": {"strategy": "Multiprocess",
                    "multiprocessConfig": {
                        "defaultActiveCoresPercentage": 50,
                        "defaultHbmLimit": "10Gi"}},
    }
    tenant = [
        "python", "-c",
        "import os, socket; "
        "s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM); "
        "s.connect(os.environ['TPU_MULTIPROCESS_PIPE'] + '/coordinator.sock'); "
        "s.sendall(('R %d\\n' % os.getpid()).encode()); "
        "print('lease:', s.recv(64).decode().strip()); "
        "print(open(os.environ['TPU_MULTIPROCESS_DIR'] + '/limits.env').read())",
    ]
    pod = _pod("pod0", ns, {"resourceClaimTemplateName": "shared-tpu"},
               command=tenant, containers=2)
    return [_ns(ns),
            _rct("shared-tpu", ns, "tpu.dev", config=config), pod]


# -- multi-node ComputeDomain benchmark -------------------------------------

def cd_allreduce_bench(num_nodes: int = 2) -> List[Dict]:
    """The nvbandwidth/NCCL-test analog (demo/specs/imex/
    nvbandwidth-test-job-1.yaml): a ComputeDomain + N pods that
    jax.distributed-initialize over the injected rendezvous env and run the
    psum bandwidth probe from tpu_dra.workloads."""
    ns = "tpu-bench"
    cd = {
        "apiVersion": apitypes.API_VERSION, "kind": "ComputeDomain",
        "metadata": {"name": "bench-cd", "namespace": ns},
        "spec": {"numNodes": num_nodes, "channel": {
            "resourceClaimTemplate": {"name": "bench-channel"},
            "allocationMode": "Single"}},
    }
    command = [
        "python", "-c",
        "import os, jax; "
        "jax.distributed.initialize("
        "os.environ['TPU_COORDINATOR_ADDRESS'], "
        "int(os.environ['TPU_PROCESS_COUNT']), "
        "int(os.environ['TPU_WORKER_ID'])); "
        "from tpu_dra.workloads.allreduce import allreduce_bandwidth; "
        "print('RESULT', allreduce_bandwidth())",
    ]
    pods = []
    for i in range(num_nodes):
        pod = _pod(f"bench-{i}", ns,
                   {"resourceClaimTemplateName": "bench-channel"}, command)
        # One pod per node: the CD channel device exists once per node.
        pod["spec"]["affinity"] = {"podAntiAffinity": {
            "requiredDuringSchedulingIgnoredDuringExecution": [{
                "labelSelector": {"matchLabels": {"app": "tpu-bench"}},
                "topologyKey": "kubernetes.io/hostname"}]}}
        pod["metadata"]["labels"] = {"app": "tpu-bench"}
        pods.append(pod)
    return [_ns(ns), cd] + pods


def all_demos() -> Dict[str, List[Dict]]:
    return {
        "tpu-test1": test1_exclusive_per_pod(),
        "tpu-test2": test2_shared_claim_two_containers(),
        "tpu-test3": test3_time_sliced_across_pods(),
        "tpu-test4": test4_multi_chip(),
        "tpu-test5": test5_subslice(),
        "tpu-test-multiprocess": test_multiprocess_shared_chip(),
        "cd-allreduce-bench": cd_allreduce_bench(),
    }
