"""helmlite: a Go-template-subset renderer for the Helm chart.

The dev/CI environment has no ``helm`` binary, but the chart must still be
renderable and validatable (`helm template | kubectl apply --dry-run=client`
is the reference's gate, Makefile + tests/bats). This module implements the
template subset the chart in deployments/helm/tpu-dra-driver uses:

- actions: ``{{ expr }}`` with ``{{-``/``-}}`` whitespace trimming
- blocks: if / else if / else, range (list and map, with ``$k, $v :=``),
  with, define/include
- pipelines: ``expr | fn arg | fn``
- terms: ``.a.b.c`` field chains, ``$`` root, ``$var`` (range/with vars),
  string literals, ints, bools, parenthesized expressions, function calls
- statements: ``$x := expr`` (declare) and ``$x = expr`` (reassign the
  nearest enclosing declaration, Go scoping — so list-building inside a
  range mutates the outer variable, the sprig append/join idiom)
- functions: quote, squote, default, toYaml, nindent, indent, printf
  (Go verbs %s %d %v %t %q %f, width), include, b64enc, eq, ne, not, and,
  or, empty, hasKey, trunc, trimSuffix, trimPrefix, lower, upper, replace,
  required, ternary, dict, list, len, contains, hasPrefix, hasSuffix,
  add, sub, mul, append, join, keys, toString, int, fail,
  genSelfSignedCert (real PEM pair via the cryptography package, with
  an ``openssl req -x509`` CLI fallback on hosts without it)

Truthiness follows Go templates: false, 0, "", nil, empty list/map are
falsy. Rendering is strict: unknown functions and malformed actions raise
``TemplateError`` (the ``helm template`` failure analog) rather than
emitting garbage YAML.
"""

from __future__ import annotations

import base64
import re
from typing import Any, Callable, Dict, List, Optional, Tuple

import yaml


class TemplateError(Exception):
    pass


# ---------------------------------------------------------------------------
# Lexing: split into literal text and {{ action }} nodes with trim markers
# ---------------------------------------------------------------------------

_ACTION_RE = re.compile(r"\{\{(-?)\s*(.*?)\s*(-?)\}\}", re.DOTALL)


def _lex(src: str) -> List[Tuple[str, str]]:
    """Returns [('text', s) | ('action', body)] with whitespace trimming
    already applied per the -/- markers."""
    nodes: List[Tuple[str, str]] = []
    pos = 0
    for m in _ACTION_RE.finditer(src):
        text = src[pos:m.start()]
        if m.group(1) == "-":
            text = text.rstrip(" \t\n\r")
        nodes.append(("text", text))
        nodes.append(("action", m.group(2)))
        pos = m.end()
        if m.group(3) == "-":
            rest = src[pos:]
            trimmed = rest.lstrip(" \t\n\r")
            pos += len(rest) - len(trimmed)
    nodes.append(("text", src[pos:]))
    return nodes


# ---------------------------------------------------------------------------
# Parsing: build a block tree
# ---------------------------------------------------------------------------

class _Node:
    pass


class _Text(_Node):
    def __init__(self, s: str):
        self.s = s


class _Expr(_Node):
    def __init__(self, src: str):
        self.src = src


class _If(_Node):
    def __init__(self):
        # list of (condition_src | None for else, body nodes)
        self.branches: List[Tuple[Optional[str], List[_Node]]] = []


class _Range(_Node):
    def __init__(self, var_k, var_v, src):
        self.var_k, self.var_v, self.src = var_k, var_v, src
        self.body: List[_Node] = []


class _With(_Node):
    def __init__(self, src):
        self.src = src
        self.body: List[_Node] = []


class _Define(_Node):
    def __init__(self, name):
        self.name = name
        self.body: List[_Node] = []


class _Assign(_Node):
    """``$x := expr`` (declare in current scope) or ``$x = expr``
    (reassign nearest enclosing declaration — Go semantics, so a
    ``$gates = append $gates ...`` inside range mutates the outer var)."""

    def __init__(self, name: str, declare: bool, src: str):
        self.name, self.declare, self.src = name, declare, src


_RANGE_RE = re.compile(
    r"^range(?:\s+(\$\w+)\s*(?:,\s*(\$\w+))?\s*:=)?\s+(.*)$", re.DOTALL)
_ASSIGN_RE = re.compile(r"^\$(\w+)\s*(:?=)\s*(.*)$", re.DOTALL)


def _parse(nodes: List[Tuple[str, str]]) -> Tuple[List[_Node], Dict[str, List[_Node]]]:
    defines: Dict[str, List[_Node]] = {}
    root: List[_Node] = []
    stack: List[Tuple[Any, List[_Node]]] = [(None, root)]

    def body() -> List[_Node]:
        return stack[-1][1]

    for kind, val in nodes:
        if kind == "text":
            if val:
                body().append(_Text(val))
            continue
        action = val.strip()
        if action.startswith("/*") or action.startswith("//"):
            continue  # comment
        if action.startswith("if "):
            node = _If()
            node.branches.append((action[3:].strip(), []))
            body().append(node)
            stack.append((node, node.branches[-1][1]))
        elif action.startswith("else"):
            owner = stack[-1][0]
            if not isinstance(owner, _If):
                raise TemplateError(f"'else' outside if: {action!r}")
            stack.pop()
            cond = action[4:].strip()
            if cond.startswith("if "):
                cond = cond[3:].strip()
            else:
                cond = None
            owner.branches.append((cond, []))
            stack.append((owner, owner.branches[-1][1]))
        elif action.startswith("range"):
            m = _RANGE_RE.match(action)
            if not m:
                raise TemplateError(f"bad range: {action!r}")
            node = _Range(m.group(1), m.group(2), m.group(3).strip())
            body().append(node)
            stack.append((node, node.body))
        elif action.startswith("with "):
            node = _With(action[5:].strip())
            body().append(node)
            stack.append((node, node.body))
        elif action.startswith("define "):
            m = re.match(r'define\s+"([^"]+)"', action)
            if not m:
                raise TemplateError(f"bad define: {action!r}")
            node = _Define(m.group(1))
            stack.append((node, node.body))
        elif action == "end":
            owner, _ = stack.pop()
            if owner is None:
                raise TemplateError("unbalanced 'end'")
            if isinstance(owner, _Define):
                defines[owner.name] = owner.body
        else:
            m = _ASSIGN_RE.match(action)
            if m:
                body().append(_Assign(m.group(1), m.group(2) == ":=",
                                      m.group(3).strip()))
            else:
                body().append(_Expr(action))
    if len(stack) != 1:
        raise TemplateError("unclosed block at EOF")
    return root, defines


# ---------------------------------------------------------------------------
# Expression evaluation
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    \s*(
        "(?:[^"\\]|\\.)*"        # double-quoted string
      | `[^`]*`                  # raw string
      | \$\w+(?:\.[\w.]+)?       # $var with optional attached .field chain
      | \$                       # bare $ (root)
      | \.[\w.]*                 # field chain .a.b / bare .
      | -?\d+(?:\.\d+)?          # number
      | \|                       # pipe
      | \(|\)
      | [A-Za-z_][\w]*           # ident (function, true/false)
    )""", re.VERBOSE)


def _tokenize(src: str) -> List[str]:
    out, pos = [], 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if not m:
            raise TemplateError(f"cannot tokenize: {src[pos:]!r}")
        out.append(m.group(1))
        pos = m.end()
    return out


def _truthy(v: Any) -> bool:
    if v is None or v is False:
        return False
    if isinstance(v, (int, float)) and v == 0:
        return False
    if isinstance(v, (str, list, dict, tuple)) and len(v) == 0:
        return False
    return True


class _Ctx:
    def __init__(self, root: Any, dot: Any, vars_: Dict[str, Any],
                 defines: Dict[str, List[_Node]], functions,
                 parent: Optional["_Ctx"] = None):
        self.root, self.dot, self.vars = root, dot, vars_
        self.defines, self.functions = defines, functions
        self.parent = parent

    def child(self, dot=None, extra_vars=None) -> "_Ctx":
        # Own-vars dict + parent link (not a flat copy) so that a Go-style
        # reassignment inside the child block mutates the declaring scope.
        return _Ctx(self.root, self.dot if dot is None else dot,
                    dict(extra_vars or {}), self.defines, self.functions,
                    parent=self)

    def lookup_var(self, name: str) -> Tuple[bool, Any]:
        c: Optional[_Ctx] = self
        while c is not None:
            if name in c.vars:
                return True, c.vars[name]
            c = c.parent
        return False, None

    def declare_var(self, name: str, value: Any) -> None:
        self.vars[name] = value

    def assign_var(self, name: str, value: Any) -> None:
        c: Optional[_Ctx] = self
        while c is not None:
            if name in c.vars:
                c.vars[name] = value
                return
            c = c.parent
        raise TemplateError(f"assignment to undeclared variable ${name}")


def _resolve_field(base: Any, chain: str) -> Any:
    cur = base
    for part in [p for p in chain.split(".") if p]:
        if isinstance(cur, dict):
            cur = cur.get(part)
        else:
            cur = getattr(cur, part, None)
        if cur is None:
            return None
    return cur


class _ExprEval:
    """Evaluates one pipeline: stages separated by '|'; each stage is a
    term or a function call whose last argument is the previous stage's
    output."""

    def __init__(self, ctx: _Ctx):
        self.ctx = ctx

    def eval(self, src: str) -> Any:
        tokens = _tokenize(src)
        stages: List[List[str]] = [[]]
        depth = 0
        for t in tokens:
            if t == "(":
                depth += 1
            elif t == ")":
                depth -= 1
            if t == "|" and depth == 0:
                stages.append([])
            else:
                stages[-1].append(t)
        value, first = None, True
        for stage in stages:
            if not stage:
                raise TemplateError(f"empty pipeline stage in {src!r}")
            value = self._eval_stage(stage, None if first else [value])
            first = False
        return value

    def _eval_stage(self, tokens: List[str], piped: Optional[List[Any]]) -> Any:
        pos = [0]

        def peek():
            return tokens[pos[0]] if pos[0] < len(tokens) else None

        def term() -> Any:
            t = peek()
            if t is None:
                raise TemplateError(f"unexpected end in {tokens!r}")
            pos[0] += 1
            if t == "(":
                # sub-pipeline until matching ')'
                depth, sub = 1, []
                while depth > 0:
                    nxt = peek()
                    if nxt is None:
                        raise TemplateError("unbalanced paren")
                    pos[0] += 1
                    if nxt == "(":
                        depth += 1
                    elif nxt == ")":
                        depth -= 1
                        if depth == 0:
                            break
                    sub.append(nxt)
                return _ExprEval(self.ctx).eval(" ".join(sub))
            if t.startswith('"'):
                return t[1:-1].replace('\\"', '"').replace("\\\\", "\\") \
                    .replace("\\n", "\n").replace("\\t", "\t")
            if t.startswith("`"):
                return t[1:-1]
            if t == "$":
                return self.ctx.root
            if t.startswith("$"):
                name, chain = t[1:], ""
                if "." in name:
                    name, chain = name.split(".", 1)
                found, base = self.ctx.lookup_var(name)
                if not found:
                    raise TemplateError(f"undefined variable ${name}")
                return _resolve_field(base, chain) if chain else base
            if t.startswith("."):
                return _resolve_field(self.ctx.dot, t)
            if re.fullmatch(r"-?\d+", t):
                return int(t)
            if re.fullmatch(r"-?\d+\.\d+", t):
                return float(t)
            if t == "true":
                return True
            if t == "false":
                return False
            if t == "nil":
                return None
            # function call: consume remaining tokens as args
            fn = self.ctx.functions.get(t)
            if fn is None:
                raise TemplateError(f"unknown function {t!r}")
            args = []
            while peek() is not None:
                args.append(term())
            if piped is not None:
                args.extend(piped)
            return fn(self.ctx, *args)

        first = term()
        # A bare term stage with piped input and leftovers is a call-less
        # stage (e.g. `.Values.x | quote` handled above); leftover tokens
        # after a non-function term is an error.
        if peek() is not None:
            raise TemplateError(f"trailing tokens in {tokens!r}")
        if piped is not None and not callable(first) and tokens and \
                not re.fullmatch(r"[A-Za-z_]\w*", tokens[0]):
            raise TemplateError(
                f"stage {tokens!r} cannot accept piped input")
        return first


# ---------------------------------------------------------------------------
# Rendering
# ---------------------------------------------------------------------------

def _render_nodes(nodes: List[_Node], ctx: _Ctx) -> str:
    out: List[str] = []
    for node in nodes:
        if isinstance(node, _Text):
            out.append(node.s)
        elif isinstance(node, _Expr):
            v = _ExprEval(ctx).eval(node.src)
            if v is None:
                continue
            out.append(v if isinstance(v, str) else _gostr(v))
        elif isinstance(node, _If):
            for cond, body in node.branches:
                if cond is None or _truthy(_ExprEval(ctx).eval(cond)):
                    out.append(_render_nodes(body, ctx))
                    break
        elif isinstance(node, _Range):
            coll = _ExprEval(ctx).eval(node.src)
            if isinstance(coll, dict):
                items = [(k, coll[k]) for k in sorted(coll)]
            elif coll:
                items = list(enumerate(coll))
            else:
                items = []
            for k, v in items:
                extra = {}
                if node.var_k and node.var_v:
                    extra = {node.var_k[1:]: k, node.var_v[1:]: v}
                elif node.var_k:
                    extra = {node.var_k[1:]: v}
                out.append(_render_nodes(
                    node.body, ctx.child(dot=v, extra_vars=extra)))
        elif isinstance(node, _With):
            v = _ExprEval(ctx).eval(node.src)
            if _truthy(v):
                out.append(_render_nodes(node.body, ctx.child(dot=v)))
        elif isinstance(node, _Assign):
            v = _ExprEval(ctx).eval(node.src)
            if node.declare:
                ctx.declare_var(node.name, v)
            else:
                ctx.assign_var(node.name, v)
        else:
            raise TemplateError(f"unhandled node {node!r}")
    return "".join(out)


def _gostr(v: Any) -> str:
    if v is True:
        return "true"
    if v is False:
        return "false"
    return str(v)


def _to_yaml(v: Any) -> str:
    return yaml.safe_dump(v, default_flow_style=False, sort_keys=False).rstrip("\n")


_VERB_RE = re.compile(r"%(0?\d*)([sdvtqf%])")


def _go_sprintf(fmt: str, args: Tuple[Any, ...]) -> str:
    """Go fmt verb subset: %s %d %v %t %q %f, optional zero-padded width
    (e.g. %04d), and %% escape. Errors on arg-count mismatch like Go's
    EXTRA/MISSING markers would surface — strict beats garbage YAML."""
    it = iter(args)

    def sub(m: re.Match) -> str:
        width, verb = m.group(1), m.group(2)
        if verb == "%":
            return "%"
        try:
            a = next(it)
        except StopIteration:
            raise TemplateError(f"printf {fmt!r}: missing argument")
        if verb == "t":
            s = "true" if _truthy(a) else "false"
        elif verb == "d":
            s = str(int(a))
        elif verb == "f":
            s = str(float(a))
        elif verb == "q":
            return '"' + _gostr(a).replace('"', '\\"') + '"'
        else:
            s = _gostr(a)
        if width:
            pad = "0" if width.startswith("0") else " "
            s = s.rjust(int(width), pad)
        return s

    out = _VERB_RE.sub(sub, fmt)
    if next(it, None) is not None:
        raise TemplateError(f"printf {fmt!r}: too many arguments")
    return out


def _gen_self_signed_cert_openssl(cn: str, ips: List[str],
                                  dns_names: List[str],
                                  days: int) -> Dict[str, str]:
    """`openssl req -x509` fallback for hosts without the cryptography
    package.  Same contract as the primary path: self-signed CA cert
    (BasicConstraints critical CA:TRUE, EKU serverAuth, SAN covering the
    CN plus extra DNS/IP entries) and an unencrypted RSA-2048 key, both
    PEM.  The key comes out PKCS#8 ("BEGIN PRIVATE KEY") rather than
    TraditionalOpenSSL, which every PEM consumer in the charts accepts."""
    import os
    import subprocess
    import tempfile

    sans = [f"DNS.1 = {cn}"]
    for d in dns_names or []:
        if d and d != cn:
            sans.append(f"DNS.{len(sans) + 1} = {d}")
    n_ip = 0
    for ip in ips or []:
        if ip:
            n_ip += 1
            sans.append(f"IP.{n_ip} = {ip}")
    conf = (
        "[req]\n"
        "distinguished_name = dn\n"
        "prompt = no\n"
        "[dn]\n"
        f"CN = {cn}\n"
        "[v3_ext]\n"
        "basicConstraints = critical,CA:TRUE\n"
        "extendedKeyUsage = serverAuth\n"
        "subjectAltName = @alt\n"
        "[alt]\n" + "\n".join(sans) + "\n")
    with tempfile.TemporaryDirectory(prefix="helmlite-cert-") as tmp:
        cfg = os.path.join(tmp, "req.cnf")
        crt = os.path.join(tmp, "tls.crt")
        key = os.path.join(tmp, "tls.key")
        with open(cfg, "w") as f:
            f.write(conf)
        proc = subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-sha256", "-days", str(int(days)), "-keyout", key,
             "-out", crt, "-config", cfg, "-extensions", "v3_ext"],
            capture_output=True, text=True)
        if proc.returncode != 0:
            raise TemplateError(
                f"genSelfSignedCert: openssl fallback failed: {proc.stderr}")
        with open(crt) as f:
            cert_pem = f.read()
        with open(key) as f:
            key_pem = f.read()
    return {"Cert": cert_pem, "Key": key_pem}


def _gen_self_signed_cert(cn: str, ips: List[str], dns_names: List[str],
                          days: int) -> Dict[str, str]:
    """helm/sprig genSelfSignedCert analog: returns {Cert, Key} PEM pair.
    The cert is its own CA (BasicConstraints CA=true) so charts can use
    Cert as both the server certificate and the webhook caBundle."""
    import datetime
    import ipaddress

    try:
        from cryptography import x509
        from cryptography.hazmat.primitives import hashes, serialization
        from cryptography.hazmat.primitives.asymmetric import rsa
        from cryptography.x509.oid import ExtendedKeyUsageOID, NameOID
    except ImportError:
        return _gen_self_signed_cert_openssl(cn, ips, dns_names, days)

    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name([x509.NameAttribute(NameOID.COMMON_NAME, cn)])
    sans: List[x509.GeneralName] = [x509.DNSName(cn)]
    for d in dns_names or []:
        if d and d != cn:
            sans.append(x509.DNSName(str(d)))
    for ip in ips or []:
        if ip:
            sans.append(x509.IPAddress(ipaddress.ip_address(str(ip))))
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (
        x509.CertificateBuilder()
        .subject_name(name).issuer_name(name)
        .public_key(key.public_key())
        .serial_number(x509.random_serial_number())
        .not_valid_before(now - datetime.timedelta(minutes=5))
        .not_valid_after(now + datetime.timedelta(days=int(days)))
        .add_extension(x509.BasicConstraints(ca=True, path_length=None),
                       critical=True)
        .add_extension(x509.SubjectAlternativeName(sans), critical=False)
        .add_extension(x509.ExtendedKeyUsage(
            [ExtendedKeyUsageOID.SERVER_AUTH]), critical=False)
        .sign(key, hashes.SHA256())
    )
    return {
        "Cert": cert.public_bytes(serialization.Encoding.PEM).decode(),
        "Key": key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption()).decode(),
    }


def _make_functions() -> Dict[str, Callable]:
    def quote(ctx, v):
        return '"' + _gostr("" if v is None else v).replace('"', '\\"') + '"'

    def squote(ctx, v):
        return "'" + _gostr("" if v is None else v) + "'"

    def default(ctx, dflt, v=None):
        return v if _truthy(v) else dflt

    def to_yaml(ctx, v):
        return _to_yaml(v)

    def nindent(ctx, n, s):
        pad = " " * int(n)
        return "\n" + "\n".join(
            pad + line if line else line for line in _gostr(s).split("\n"))

    def indent(ctx, n, s):
        pad = " " * int(n)
        return "\n".join(
            pad + line if line else line for line in _gostr(s).split("\n"))

    def include(ctx, name, dot):
        body = ctx.defines.get(name)
        if body is None:
            raise TemplateError(f"include of undefined template {name!r}")
        # Fresh variable scope (Go template-invocation semantics): the
        # callee sees only its argument, not the caller's $vars.
        return _render_nodes(body, _Ctx(ctx.root, dot, {}, ctx.defines,
                                        ctx.functions))

    def printf(ctx, fmt, *args):
        return _go_sprintf(fmt, args)

    def required(ctx, msg, v):
        if not _truthy(v):
            raise TemplateError(f"required value missing: {msg}")
        return v

    def ternary(ctx, if_true, if_false, cond):
        return if_true if _truthy(cond) else if_false

    return {
        "quote": quote,
        "squote": squote,
        "default": default,
        "toYaml": to_yaml,
        "nindent": nindent,
        "indent": indent,
        "include": include,
        "printf": printf,
        "b64enc": lambda ctx, s: base64.b64encode(
            _gostr(s).encode()).decode(),
        "eq": lambda ctx, a, b: a == b,
        "ne": lambda ctx, a, b: a != b,
        "not": lambda ctx, v: not _truthy(v),
        "and": lambda ctx, *vs: all(_truthy(v) for v in vs),
        "or": lambda ctx, *vs: next((v for v in vs if _truthy(v)),
                                    vs[-1] if vs else None),
        "empty": lambda ctx, v: not _truthy(v),
        "hasKey": lambda ctx, d, k: isinstance(d, dict) and k in d,
        "len": lambda ctx, v: len(v) if v is not None else 0,
        "trunc": lambda ctx, n, s: _gostr(s)[:int(n)],
        "trimSuffix": lambda ctx, suf, s: _gostr(s)[:-len(suf)]
        if _gostr(s).endswith(suf) else _gostr(s),
        "lower": lambda ctx, s: _gostr(s).lower(),
        "upper": lambda ctx, s: _gostr(s).upper(),
        "replace": lambda ctx, old, new, s: _gostr(s).replace(old, new),
        "required": required,
        "ternary": ternary,
        "dict": lambda ctx, *kv: {kv[i]: kv[i + 1]
                                  for i in range(0, len(kv), 2)},
        "list": lambda ctx, *vs: list(vs),
        "contains": lambda ctx, sub, s: sub in _gostr(s),
        "hasPrefix": lambda ctx, pre, s: _gostr(s).startswith(pre),
        "hasSuffix": lambda ctx, suf, s: _gostr(s).endswith(suf),
        "trimPrefix": lambda ctx, pre, s: _gostr(s)[len(pre):]
        if _gostr(s).startswith(pre) else _gostr(s),
        "add": lambda ctx, *vs: sum(int(v) for v in vs),
        "sub": lambda ctx, a, b: int(a) - int(b),
        "mul": lambda ctx, *vs: __import__("math").prod(int(v) for v in vs),
        "append": lambda ctx, lst, *items: list(lst or []) + list(items),
        "join": lambda ctx, sep, lst: sep.join(_gostr(v) for v in lst or []),
        "keys": lambda ctx, d: sorted((d or {}).keys()),
        "toString": lambda ctx, v: _gostr(v),
        "int": lambda ctx, v: int(v),
        "fail": _fail,
        "genSelfSignedCert": lambda ctx, cn, ips, dns, days:
            _gen_self_signed_cert(cn, ips, dns, days),
    }


def _fail(ctx, msg):
    raise TemplateError(f"fail: {_gostr(msg)}")


# ---------------------------------------------------------------------------
# Chart driver
# ---------------------------------------------------------------------------

def _deep_merge(base: Dict, override: Dict) -> Dict:
    out = dict(base)
    for k, v in (override or {}).items():
        if v is None:
            # Helm semantics: an explicit null in an override DELETES the
            # default key (how overlays drop a default nodeSelector entry,
            # e.g. demo/clusters/gke/values-gke.yaml).
            out.pop(k, None)
        elif isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


def render_chart(chart_dir: str, values_override: Optional[Dict] = None,
                 release_name: str = "tpu-dra-driver",
                 namespace: str = "tpu-dra-driver") -> List[Dict]:
    """The `helm template` analog: renders every templates/*.yaml plus
    crds/*.yaml and returns the parsed document list. Raises TemplateError
    or yaml.YAMLError on malformed output — the validation gate."""
    import os

    with open(os.path.join(chart_dir, "Chart.yaml")) as f:
        chart_meta = yaml.safe_load(f)
    with open(os.path.join(chart_dir, "values.yaml")) as f:
        values = yaml.safe_load(f) or {}
    values = _deep_merge(values, values_override or {})

    root = {
        "Values": values,
        "Release": {"Name": release_name, "Namespace": namespace,
                    "Service": "Helm"},
        "Chart": {"Name": chart_meta.get("name", ""),
                  "Version": chart_meta.get("version", ""),
                  "AppVersion": chart_meta.get("appVersion", "")},
    }

    tdir = os.path.join(chart_dir, "templates")
    sources = {}
    for fn in sorted(os.listdir(tdir)):
        if fn.endswith((".yaml", ".tpl")):
            with open(os.path.join(tdir, fn)) as f:
                sources[fn] = f.read()

    # First pass: collect defines from every file (helm shares them).
    defines: Dict[str, List[_Node]] = {}
    parsed = {}
    for fn, src in sources.items():
        tree, defs = _parse(_lex(src))
        defines.update(defs)
        parsed[fn] = tree

    functions = _make_functions()
    docs: List[Dict] = []
    for fn, tree in parsed.items():
        if fn.endswith(".tpl"):
            continue
        ctx = _Ctx(root, root, {}, defines, functions)
        text = _render_nodes(tree, ctx)
        for doc in yaml.safe_load_all(text):
            if doc:
                docs.append(doc)

    cdir = os.path.join(chart_dir, "crds")
    if os.path.isdir(cdir):
        for fn in sorted(os.listdir(cdir)):
            with open(os.path.join(cdir, fn)) as f:
                for doc in yaml.safe_load_all(f.read()):
                    if doc:
                        docs.append(doc)
    return docs
