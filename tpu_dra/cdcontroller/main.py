"""ComputeDomain controller entrypoint.

Reference: cmd/compute-domain-controller/main.go:48-127, 243-290 — flags
(incl. --max-nodes-per-slice-domain, the GB200 maxNodesPerIMEXDomain
analog sized for TPU slice host counts), metrics endpoint, run loop.

Run: ``python -m tpu_dra.cdcontroller.main [flags]``
"""

from __future__ import annotations

import signal
import threading

from tpu_dra.cdcontroller.controller import Controller
from tpu_dra.infra import debug
from tpu_dra.infra.flags import (
    Flag, FlagSet, apply_feature_gates, feature_gate_flag, logging_flags,
    setup_logging,
)
from tpu_dra.infra.featuregates import Features
from tpu_dra.infra.metrics import MetricsServer
from tpu_dra.k8s.client import HttpApiClient, RetryingApiClient


def flags() -> FlagSet:
    return FlagSet("tpu-cd-controller", [
        Flag("namespace", "NAMESPACE", default="tpu-dra-driver",
             help="driver namespace (DaemonSets + daemon RCTs land here)"),
        Flag("image", "DAEMON_IMAGE", default="tpu-dra-driver:latest",
             help="image for the per-CD slice-daemon DaemonSet"),
        Flag("daemon-service-account", "DAEMON_SERVICE_ACCOUNT", default="",
             help="serviceAccountName for stamped daemon pods "
                  "(empty = namespace default SA)"),
        Flag("max-nodes-per-slice-domain", "MAX_NODES_PER_SLICE_DOMAIN",
             default=64, type=int,
             help="upper bound on hosts per ICI slice domain "
                  "(e.g. 64 hosts = v5e-256)"),
        Flag("kube-api-url", "KUBE_API_URL", default=None,
             help="API server URL (default: in-cluster config)"),
        Flag("http-endpoint-port", "HTTP_ENDPOINT_PORT", default=0, type=int,
             help="metrics/pprof HTTP port (0 = disabled)"),
        Flag("gc-interval-seconds", "GC_INTERVAL_SECONDS", default=600,
             type=int, help="stale-object GC period"),
        feature_gate_flag(),
        *logging_flags(),
    ])


def main(argv=None) -> int:
    fs = flags()
    ns = fs.parse(argv)
    logger = setup_logging(ns.v, ns.log_json)
    apply_feature_gates(ns)
    fs.dump_config(ns, logger)
    debug.start_debug_signal_handlers()

    # Transient API-server failures (rolling upgrade, LB blips)
    # retry with jittered backoff instead of crash-looping the pod.
    client = RetryingApiClient(HttpApiClient(base_url=ns.kube_api_url))
    controller = Controller(
        client, namespace=ns.namespace, image=ns.image,
        log_verbosity=ns.v, feature_gates=Features.as_string(),
        max_nodes_per_slice_domain=ns.max_nodes_per_slice_domain,
        gc_interval=ns.gc_interval_seconds,
        daemon_service_account=ns.daemon_service_account)

    metrics_srv = None
    if ns.http_endpoint_port:
        metrics_srv = MetricsServer(addr="0.0.0.0",  # noqa: S104
                                    port=ns.http_endpoint_port)
        metrics_srv.start()

    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())

    controller.start()
    logger.info("compute-domain controller running (namespace %s)",
                ns.namespace)
    stop.wait()
    controller.stop()
    if metrics_srv:
        metrics_srv.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
