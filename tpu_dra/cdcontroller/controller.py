"""ComputeDomain reconciliation (reference: cmd/compute-domain-controller).

One `Controller` wires five informers (ComputeDomains, DaemonSets, RCTs,
daemon Pods, Nodes) into a rate-limited work queue. Reconcile semantics
follow computedomain.go:57-289:

- add/update: add finalizer, stamp daemon RCT + DaemonSet (driver
  namespace) and the user-facing workload RCT (CD namespace); flip CD
  status from the per-node readiness the cd-daemons maintain in
  cd.status.nodes (_update_readiness — the daemonset.go:362-389 analog,
  with the DaemonSet's desiredNumberScheduled as the open-ended lower
  bound).
- delete: ordered teardown — delete stamped objects, strip node labels,
  assert removal, then remove the finalizer (:237-271).
- daemon pod deletion: drop that node from CD status by pod IP, flip
  NotReady below numNodes (daemonsetpods.go:134-173).
- stale sweeps: CleanupManager GC + node-label sweeps (cleanup.go, node.go).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional

from tpu_dra.api import types as apitypes
from tpu_dra.cdcontroller import templates
from tpu_dra.cdcontroller.cleanup import CleanupManager
from tpu_dra.infra import featuregates
from tpu_dra.infra.faults import FAULTS
from tpu_dra.infra.metrics import DefaultRegistry
from tpu_dra.topology import domain_topology
from tpu_dra.infra.workqueue import WorkQueue, default_controller_rate_limiter
from tpu_dra.k8s import (
    ApiClient, COMPUTEDOMAINS, DAEMONSETS, NODES, PODS, RESOURCECLAIMTEMPLATES,
)
from tpu_dra.k8s.client import AlreadyExistsError, ConflictError, NotFoundError
from tpu_dra.k8s.informer import Informer, label_index, uid_index

log = logging.getLogger("tpu_dra.cdcontroller")

reconciles_total = DefaultRegistry.counter(
    "tpu_dra_cd_reconciles_total", "ComputeDomain reconcile passes")
teardowns_total = DefaultRegistry.counter(
    "tpu_dra_cd_teardowns_total", "ComputeDomain teardown completions")
degraded_total = DefaultRegistry.counter(
    "tpu_dra_cd_degraded_total",
    "Ready -> Degraded transitions: a previously-Ready ComputeDomain "
    "lost a member (node death, daemon crash) and says so via "
    "status.statusReason instead of reading as a never-started NotReady")

UID_INDEX = "uid"
CD_LABEL_INDEX = "cd-uid"

# Annotation recording the hash of the template a stamped DaemonSet was
# last written from (kubectl last-applied analog): comparing hashes detects
# every template change — including removed fields — without being fooled
# by server-side defaulting of fields the template never set.
TEMPLATE_HASH_ANNOTATION = "resource.tpu.dev/template-hash"


def _template_hash(spec: Dict) -> str:
    import hashlib
    import json
    return hashlib.sha256(
        json.dumps(spec, sort_keys=True).encode()).hexdigest()[:16]


class RetryableError(Exception):
    """Raised to push the reconcile back onto the rate-limited queue."""


class Controller:
    def __init__(self, client: ApiClient, *, namespace: str,
                 image: str = "tpu-dra-driver:latest",
                 log_verbosity: int = 0, feature_gates: str = "",
                 max_nodes_per_slice_domain: int = 64,
                 gc_interval: float = 600.0,
                 daemon_service_account: str = "",
                 open_ready_settle_s: float = 1.0):
        self._client = client
        self._namespace = namespace  # driver namespace (DS + daemon RCT home)
        self._image = image
        self._log_verbosity = log_verbosity
        self._feature_gates = feature_gates
        self._max_nodes = max_nodes_per_slice_domain
        self._daemon_sa = daemon_service_account
        self._queue = WorkQueue(default_controller_rate_limiter(),
                                log=lambda m: log.debug("%s", m))
        self._stop = threading.Event()
        # Open-ended (numNodes==0) readiness settle: uid -> (node-name
        # set, monotonic time of its last change). Expected membership of
        # an open CD lags label-driven daemon summoning, so Ready only
        # flips once the member set has been stable for
        # open_ready_settle_s (late joiners re-arm the window).
        self._open_settle_s = open_ready_settle_s
        self._open_membership: dict = {}

        self.cd_informer = Informer(client, COMPUTEDOMAINS)
        self.cd_informer.add_indexer(UID_INDEX, uid_index)
        self.ds_informer = Informer(
            client, DAEMONSETS, namespace=namespace,
            label_selector=apitypes.COMPUTE_DOMAIN_LABEL_KEY)
        self.ds_informer.add_indexer(
            CD_LABEL_INDEX, label_index(apitypes.COMPUTE_DOMAIN_LABEL_KEY))
        self.rct_informer = Informer(
            client, RESOURCECLAIMTEMPLATES,
            label_selector=apitypes.COMPUTE_DOMAIN_LABEL_KEY)
        self.rct_informer.add_indexer(
            CD_LABEL_INDEX, label_index(apitypes.COMPUTE_DOMAIN_LABEL_KEY))
        self.pod_informer = Informer(
            client, PODS, namespace=namespace,
            label_selector=apitypes.COMPUTE_DOMAIN_LABEL_KEY)
        self.node_informer = Informer(client, NODES)

        self.cd_informer.on_add(lambda obj: self._enqueue_cd_obj(obj))
        self.cd_informer.on_update(lambda _old, new: self._enqueue_cd_obj(new))
        self.cd_informer.on_delete(self._on_cd_deleted)
        self.ds_informer.on_update(self._on_ds_update)
        self.pod_informer.on_delete(self._on_pod_deleted)

        self._cleanup = CleanupManager(
            client=client,
            cd_exists=lambda uid: self._get_cd_by_uid(uid) is not None,
            targets=[
                (DAEMONSETS, namespace),
                (RESOURCECLAIMTEMPLATES, None),
            ],
            interval=gc_interval,
            extra_sweeps=[self._sweep_stale_node_labels])

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> None:
        for inf in (self.cd_informer, self.ds_informer, self.rct_informer,
                    self.pod_informer, self.node_informer):
            inf.start()
        for inf in (self.cd_informer, self.ds_informer, self.rct_informer,
                    self.pod_informer, self.node_informer):
            inf.wait_for_sync()
        self._queue.run_in_thread()
        self._cleanup.start()

    def stop(self) -> None:
        self._stop.set()
        self._cleanup.stop()
        self._queue.shutdown()
        for inf in (self.cd_informer, self.ds_informer, self.rct_informer,
                    self.pod_informer, self.node_informer):
            inf.stop()

    # -- event handlers (fast, enqueue only) --------------------------------

    def _enqueue_cd_obj(self, cd: Dict) -> None:
        uid = cd["metadata"].get("uid", "")
        if uid:
            self.enqueue(uid)

    def enqueue(self, uid: str) -> None:
        self._queue.enqueue(uid, self._reconcile, key=f"cd/{uid}")

    def _on_cd_deleted(self, cd: Dict) -> None:
        # CD fully gone from the API server: sweep anything left behind.
        uid = cd["metadata"].get("uid", "")
        if uid:
            self._queue.enqueue(uid, self._sweep_after_delete,
                                key=f"gc/{uid}")

    def _on_ds_update(self, _old: Dict, new: Dict) -> None:
        uid = (new["metadata"].get("labels") or {}).get(
            apitypes.COMPUTE_DOMAIN_LABEL_KEY)
        if uid:
            self.enqueue(uid)

    def _on_pod_deleted(self, pod: Dict) -> None:
        uid = (pod["metadata"].get("labels") or {}).get(
            apitypes.COMPUTE_DOMAIN_LABEL_KEY)
        if uid:
            self._queue.enqueue((uid, pod), self._handle_pod_deleted,
                                key=f"pod-del/{uid}/{pod['metadata']['name']}")

    # -- helpers ------------------------------------------------------------

    def _get_cd_by_uid(self, uid: str) -> Optional[Dict]:
        hits = self.cd_informer.get_by_index(UID_INDEX, uid)
        return hits[0] if hits else None

    def _fresh_cd(self, uid: str) -> Optional[Dict]:
        cached = self._get_cd_by_uid(uid)
        if cached is None:
            return None
        meta = cached["metadata"]
        try:
            obj = self._client.get(COMPUTEDOMAINS, meta["name"],
                                   meta.get("namespace"))
        except NotFoundError:
            return None
        return obj if obj["metadata"].get("uid") == uid else None

    # -- reconcile ----------------------------------------------------------

    def _reconcile(self, uid: str) -> None:
        reconciles_total.inc()
        cd = self._fresh_cd(uid)
        if cd is None:
            self._sweep_after_delete(uid)
            return
        if cd["metadata"].get("deletionTimestamp"):
            self._teardown(cd)
            return
        self._ensure_finalizer(cd)
        self._ensure_stamped_objects(cd)
        self._update_readiness(cd)

    def _ensure_finalizer(self, cd: Dict) -> None:
        fins = cd["metadata"].setdefault("finalizers", [])
        if apitypes.COMPUTE_DOMAIN_FINALIZER in fins:
            return
        fins.append(apitypes.COMPUTE_DOMAIN_FINALIZER)
        try:
            updated = self._client.update(COMPUTEDOMAINS, cd)
        except ConflictError as e:
            raise RetryableError(f"finalizer add conflict: {e}") from e
        cd["metadata"] = updated["metadata"]
        self.cd_informer.update_cache(updated)

    def _ensure_stamped_objects(self, cd: Dict) -> None:
        ns = self._namespace
        for build, gvr, obj_ns in (
            (lambda: templates.daemon_claim_template(cd, namespace=ns),
             RESOURCECLAIMTEMPLATES, ns),
            (lambda: templates.daemon_daemonset(
                cd, namespace=ns, image=self._image,
                daemon_claim_template=templates.daemon_object_name(cd),
                log_verbosity=self._log_verbosity,
                feature_gates=self._feature_gates,
                max_nodes_per_slice_domain=self._max_nodes,
                service_account=self._daemon_sa),
             DAEMONSETS, ns),
            (lambda: templates.workload_claim_template(cd),
             RESOURCECLAIMTEMPLATES,
             cd["metadata"].get("namespace", "default")),
        ):
            obj = build()
            if gvr is DAEMONSETS:
                obj["metadata"].setdefault("annotations", {})[
                    TEMPLATE_HASH_ANNOTATION] = _template_hash(obj["spec"])
            if not obj["metadata"].get("name"):
                # spec.channel.resourceClaimTemplate.name unset: without it
                # the create would 422 on every reconcile. The webhook is the
                # real gate; skip + log here so the CD can't wedge the queue.
                log.warning("computedomain %s: no workload RCT name in spec; "
                            "skipping workload template",
                            cd["metadata"].get("name"))
                continue
            try:
                created = self._client.create(gvr, obj, namespace=obj_ns)
            except AlreadyExistsError:
                # DaemonSets get the reference's explicit update path
                # (daemonset.go:340) so controller upgrades (new image,
                # gates, max-nodes) reach running CDs; RCT specs are
                # immutable upstream and stay create-only.
                if gvr is DAEMONSETS:
                    self._sync_stamped_daemonset(obj, obj_ns)
                continue
            # Mutation cache: see our own write before the watch lands.
            if gvr is DAEMONSETS:
                self.ds_informer.update_cache(created)
            else:
                self.rct_informer.update_cache(created)

    def _sync_stamped_daemonset(self, want: Dict, ns: str) -> None:
        """Converge an existing per-CD DaemonSet onto the freshly built
        template when the recorded template hash differs (a missing hash —
        pre-upgrade object — converges once and gains the annotation)."""
        name = want["metadata"]["name"]
        try:
            existing = self._client.get(DAEMONSETS, name, ns)
        except NotFoundError:
            raise RetryableError(
                f"daemonset {name} vanished between create-conflict and get")
        want_hash = want["metadata"]["annotations"][TEMPLATE_HASH_ANNOTATION]
        have_hash = (existing["metadata"].get("annotations") or {}).get(
            TEMPLATE_HASH_ANNOTATION)
        if have_hash == want_hash:
            return
        fresh = dict(existing)
        fresh["spec"] = want["spec"]
        fresh["metadata"] = dict(existing["metadata"])
        fresh["metadata"]["annotations"] = dict(
            existing["metadata"].get("annotations") or {},
            **{TEMPLATE_HASH_ANNOTATION: want_hash})
        try:
            updated = self._client.update(DAEMONSETS, fresh, namespace=ns)
        except ConflictError as e:
            raise RetryableError(f"daemonset {name} update conflict: {e}") \
                from e
        self.ds_informer.update_cache(updated)
        log.info("daemonset %s/%s converged onto current template", ns, name)

    def _update_readiness(self, cd: Dict) -> None:
        """daemonset.go:362-389 analog: global CD status vs numNodes. With
        numNodes==0 (deprecated-field semantics, SliceDaemonsWithDNSNames
        default) the CD is Ready once every registered daemon is ready and
        at least one is.

        Readiness is counted from cd.status.nodes — the per-node entries
        the cd-daemons themselves maintain — rather than the DaemonSet's
        kubelet-aggregated numberReady. Same convergence signal (each
        daemon's startup probe drives both), one fewer freshness
        dependency, and it is the SAME source the CD plugin's channel
        gate reads (assert_node_ready), so "domain Ready" and "my peers
        are all in the env snapshot" can never disagree. The DaemonSet
        existence check stays: Ready must not flip before the CD's
        infrastructure is stamped."""
        uid = cd["metadata"]["uid"]
        hits = self.ds_informer.get_by_index(CD_LABEL_INDEX, uid)
        if not hits:
            return
        nodes = (cd.get("status") or {}).get("nodes") or []
        ready = sum(1 for n in nodes
                    if n.get("status") == apitypes.COMPUTE_DOMAIN_STATUS_READY)
        num_nodes = (cd.get("spec") or {}).get("numNodes", 0)
        expected_members = num_nodes
        settling = False
        if num_nodes > 0:
            want = (apitypes.COMPUTE_DOMAIN_STATUS_READY
                    if ready >= num_nodes
                    else apitypes.COMPUTE_DOMAIN_STATUS_NOT_READY)
        else:
            # Open-ended CD: every expected daemon ready and at least one.
            # Expected = max(registered, DS desiredNumberScheduled): a
            # scheduled-but-unregistered daemon (pod still pulling) must
            # hold the domain NotReady, or an early channel prepare would
            # snapshot a partial peer env. Harnesses with no kubelet
            # maintaining DS status degrade to the registered count.
            desired = (hits[0].get("status") or {}).get(
                "desiredNumberScheduled", 0)
            expected = max(len(nodes), desired)
            expected_members = expected
            want = (apitypes.COMPUTE_DOMAIN_STATUS_READY
                    if ready > 0 and ready >= expected
                    else apitypes.COMPUTE_DOMAIN_STATUS_NOT_READY)
            if want == apitypes.COMPUTE_DOMAIN_STATUS_READY:
                # Residual race: expected lags label-driven daemon
                # summoning, so the first node's readiness could flip an
                # open-ended domain Ready before later participants have
                # labeled their nodes — the same flake class the strict
                # numNodes gate closes. Hold Ready until the member set
                # has been stable for the settle window; a new member
                # re-arms it (and its status update re-enqueues us).
                sig = frozenset(n.get("name", "") for n in nodes)
                now = time.monotonic()
                prev = self._open_membership.get(uid)
                if prev is None and (cd.get("status") or {}).get(
                        "status") == apitypes.COMPUTE_DOMAIN_STATUS_READY:
                    # Controller restart over an already-Ready domain:
                    # adopt the member set as settled — re-arming here
                    # would flap every stable open-ended CD to NotReady
                    # for a window whose membership never changed.
                    changed_at = now - self._open_settle_s
                    self._open_membership[uid] = (sig, changed_at)
                elif prev is None or prev[0] != sig:
                    self._open_membership[uid] = (sig, now)
                    changed_at = now
                else:
                    changed_at = prev[1]
                remaining = self._open_settle_s - (now - changed_at)
                if remaining > 0:
                    want = apitypes.COMPUTE_DOMAIN_STATUS_NOT_READY
                    settling = True
                    self._queue.enqueue(uid, self._reconcile,
                                        key=f"cd/{uid}", after=remaining)
        # Failure-domain transition (SURVEY §18): a domain that WAS
        # Ready and no longer meets its readiness bar has LOST something
        # — a member node died, a daemon crash-looped — and the
        # workloads gating on it need to know it is a regression, not a
        # domain that never came up. Ready -> Degraded, with the why in
        # status.statusReason; a Degraded domain stays Degraded until it
        # either recovers (Ready, reason cleared) or is torn down.
        # EXCEPT the settle hold: there every member IS ready — the
        # window exists to absorb growth (a joining member), which is
        # not a loss and must not read (or count) as one.
        reason = None
        if not settling and \
                want == apitypes.COMPUTE_DOMAIN_STATUS_NOT_READY:
            cur = (cd.get("status") or {}).get("status")
            if cur in (apitypes.COMPUTE_DOMAIN_STATUS_READY,
                       apitypes.COMPUTE_DOMAIN_STATUS_DEGRADED):
                want = apitypes.COMPUTE_DOMAIN_STATUS_DEGRADED
                # The pod-delete handler may already have recorded a
                # MORE specific reason (the lost member's name); the
                # periodic readiness pass must not launder it into the
                # generic count.
                reason = ((cd.get("status") or {}).get("statusReason")
                          if cur == apitypes.COMPUTE_DOMAIN_STATUS_DEGRADED
                          else None) or (
                    f"{ready}/{expected_members} members ready "
                    "(member lost or daemon not ready)")
        # ICI placement observability (gated): how many physical slices
        # the registered member set spans and whether it is slice-aligned
        # (one sliceID, contiguous worker indices). The daemons register
        # sliceID/index per node, so this is the controller's view of the
        # scheduler's topology-ranked node selection — a Ready domain
        # spanning slices means collectives will cross DCN.
        topo = None
        if (len(nodes) > 1
                and featuregates.enabled(
                    featuregates.TopologyAwareScheduling)):
            topo = domain_topology(nodes)
            if (want == apitypes.COMPUTE_DOMAIN_STATUS_READY
                    and not topo["sliceAligned"]):
                log.warning(
                    "computedomain %s is Ready but spans %d ICI slices "
                    "(members not slice-aligned): inter-node collectives "
                    "will traverse DCN", uid, topo["slices"])
        self._set_cd_status(uid, want, topo=topo, reason=reason)

    def _set_cd_status(self, uid: str, want: str,
                       topo: Optional[Dict] = None,
                       reason: Optional[str] = None) -> None:
        """topo=None means "no topology summary applies" (single-node
        membership, or the gate is off): a previously stamped
        status.topology is REMOVED rather than left stale — the field
        must describe the current member set or not exist. The same
        contract governs `reason` (status.statusReason): recovery to
        Ready republishes cleanly, with no stale degradation note."""
        cd = self._fresh_cd(uid)
        if cd is None:
            return
        status = cd.setdefault("status", {})
        if (status.get("status") == want
                and status.get("topology") == topo
                and status.get("statusReason") == reason):
            return
        newly_degraded = (
            want == apitypes.COMPUTE_DOMAIN_STATUS_DEGRADED
            and status.get("status")
            == apitypes.COMPUTE_DOMAIN_STATUS_READY)
        status["status"] = want
        if topo is not None:
            status["topology"] = topo
        else:
            status.pop("topology", None)
        if reason is not None:
            status["statusReason"] = reason
        else:
            status.pop("statusReason", None)
        status.setdefault("nodes", [])
        try:
            updated = self._client.update_status(COMPUTEDOMAINS, cd)
        except (ConflictError, NotFoundError) as e:
            raise RetryableError(f"status update: {e}") from e
        if newly_degraded:
            # Counted only once the write LANDED: a conflict retries
            # the whole item, and counting before the write would
            # record the same transition per attempt.
            degraded_total.inc()
        self.cd_informer.update_cache(updated)
        log.info("computedomain %s/%s status -> %s",
                 cd["metadata"].get("namespace"), cd["metadata"]["name"], want)

    # -- daemon pod deletions ----------------------------------------------

    def _handle_pod_deleted(self, item) -> None:
        uid, pod = item
        cd = self._fresh_cd(uid)
        if cd is None:
            return
        pod_ip = (pod.get("status") or {}).get("podIP", "")
        if not pod_ip:
            return
        # Stale-event guard: with hostNetwork the replacement daemon pod
        # reuses the node IP, and its registration must not be stripped by
        # the queued deletion of its predecessor.
        for live in self.pod_informer.lister.list():
            if (live["metadata"]["name"] != pod["metadata"]["name"]
                    and (live["metadata"].get("labels") or {}).get(
                        apitypes.COMPUTE_DOMAIN_LABEL_KEY) == uid
                    and (live.get("status") or {}).get("podIP") == pod_ip):
                return
        nodes = (cd.get("status") or {}).get("nodes") or []
        kept = [n for n in nodes if n.get("ipAddress") != pod_ip]
        if len(kept) == len(nodes):
            return
        # Injection site: the member-loss handling itself fails (status
        # write refused) — the keyed queue item must retry until the
        # loss is recorded; a CD must never sit Ready with a dead member
        # because the handler gave up.
        FAULTS.check("cd.member_loss", cd=uid, pod_ip=pod_ip)
        lost = sorted(n.get("name", "?") for n in nodes if n not in kept)
        cd.setdefault("status", {})["nodes"] = kept
        num_nodes = (cd.get("spec") or {}).get("numNodes", 0)
        short = ((num_nodes and len(kept) < num_nodes)
                 or (not num_nodes and not kept))
        newly_degraded = False
        if short:
            was = cd["status"].get("status")
            if was in (apitypes.COMPUTE_DOMAIN_STATUS_READY,
                       apitypes.COMPUTE_DOMAIN_STATUS_DEGRADED):
                # Ready -> Degraded with the member named: slice loss
                # mid-job reads as a regression with a reason, never a
                # wedged CD still claiming Ready (SURVEY §18).
                newly_degraded = \
                    was == apitypes.COMPUTE_DOMAIN_STATUS_READY
                cd["status"]["status"] = \
                    apitypes.COMPUTE_DOMAIN_STATUS_DEGRADED
            else:
                cd["status"]["status"] = \
                    apitypes.COMPUTE_DOMAIN_STATUS_NOT_READY
            cd["status"]["statusReason"] = (
                f"member node lost: {', '.join(lost)} "
                f"({len(kept)}/{num_nodes or len(nodes)} members remain)")
        try:
            updated = self._client.update_status(COMPUTEDOMAINS, cd)
        except (ConflictError, NotFoundError) as e:
            raise RetryableError(f"pod-delete status update: {e}") from e
        if newly_degraded:
            # After the write, not before: a conflict re-runs the keyed
            # item and would double-count the same transition.
            degraded_total.inc()
        self.cd_informer.update_cache(updated)
        if short:
            log.warning("computedomain %s degraded: %s", uid,
                        cd["status"]["statusReason"])

    # -- teardown -----------------------------------------------------------

    def _teardown(self, cd: Dict) -> None:
        """Ordered teardown (computedomain.go:237-271): stamped objects,
        node labels, assert removal, then the finalizer."""
        uid = cd["metadata"]["uid"]
        ns = self._namespace
        # Delete by CD-UID label, not by current spec names: a renamed
        # workload RCT would otherwise survive with the label and wedge the
        # leftover assertion forever (the reference also deletes by label
        # lookup, resourceclaimtemplate.go:195-213).
        selector = f"{apitypes.COMPUTE_DOMAIN_LABEL_KEY}={uid}"
        for gvr, gvr_ns in ((RESOURCECLAIMTEMPLATES, None), (DAEMONSETS, ns)):
            for obj in self._client.list(gvr, namespace=gvr_ns,
                                         label_selector=selector):
                self._client.delete(gvr, obj["metadata"]["name"],
                                    obj["metadata"].get("namespace"))
        self._remove_node_labels(uid)

        # Assert removal before dropping the finalizer.
        leftovers: List[str] = []
        for gvr, gvr_ns in ((DAEMONSETS, ns), (RESOURCECLAIMTEMPLATES, None)):
            for obj in self._client.list(gvr, namespace=gvr_ns,
                                         label_selector=selector):
                leftovers.append(f"{gvr.plural}/{obj['metadata']['name']}")
        if leftovers:
            raise RetryableError(f"teardown of {uid}: waiting on {leftovers}")

        fins = cd["metadata"].get("finalizers") or []
        if apitypes.COMPUTE_DOMAIN_FINALIZER in fins:
            fins.remove(apitypes.COMPUTE_DOMAIN_FINALIZER)
            cd["metadata"]["finalizers"] = fins
            try:
                self._client.update(COMPUTEDOMAINS, cd)
            except ConflictError as e:
                raise RetryableError(f"finalizer removal conflict: {e}") from e
            except NotFoundError:
                pass
        teardowns_total.inc()
        log.info("computedomain %s torn down", uid)

    # -- node labels --------------------------------------------------------

    def _remove_node_labels(self, uid: str) -> None:
        """node.go:110-146: strip resource.tpu.dev/computeDomain=<uid>."""
        for node in self.node_informer.lister.list():
            labels = node["metadata"].get("labels") or {}
            if labels.get(apitypes.COMPUTE_DOMAIN_LABEL_KEY) != uid:
                continue
            try:
                self._client.patch(
                    NODES, node["metadata"]["name"],
                    {"metadata": {"labels": {
                        apitypes.COMPUTE_DOMAIN_LABEL_KEY: None}}})
            except NotFoundError:
                pass

    def _sweep_stale_node_labels(self) -> None:
        """Periodic stale-label sweep (node.go:159): labels pointing at CDs
        that no longer exist are removed."""
        for node in self._client.list(NODES):
            labels = node["metadata"].get("labels") or {}
            uid = labels.get(apitypes.COMPUTE_DOMAIN_LABEL_KEY)
            if uid and self._get_cd_by_uid(uid) is None:
                try:
                    self._client.patch(
                        NODES, node["metadata"]["name"],
                        {"metadata": {"labels": {
                            apitypes.COMPUTE_DOMAIN_LABEL_KEY: None}}})
                except NotFoundError:
                    pass

    def _sweep_after_delete(self, uid: str) -> None:
        self._remove_node_labels(uid)
        self._cleanup.collect_uid(uid)
        self._open_membership.pop(uid, None)
