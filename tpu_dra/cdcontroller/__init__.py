"""ComputeDomain controller (reference: cmd/compute-domain-controller).

Cluster-scoped, single-replica control loop: watches ComputeDomain CRs and
materializes per-CD infrastructure — a per-CD DaemonSet of slice daemons
(landing only on nodes the CD kubelet plugin labels), the daemon + workload
ResourceClaimTemplates, Ready/NotReady status transitions, and garbage
collection of everything when the CD goes away.
"""

from tpu_dra.cdcontroller.controller import Controller  # noqa: F401
