"""Generic stale-object garbage collection.

Reference: cd-controller cleanup.go:30-160 `CleanupManager[T]` — periodic
(10 min) + on-demand GC: any object labeled with a ComputeDomain UID whose
CD no longer exists is deleted (finalizers stripped first if needed). A
size-1 dedup queue coalesces on-demand requests.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, List, Optional, Tuple

from tpu_dra.api import types as apitypes
from tpu_dra.k8s import ApiClient
from tpu_dra.k8s.client import GVR, NotFoundError

log = logging.getLogger("tpu_dra.cdcontroller.cleanup")


class CleanupManager:
    def __init__(self, *, client: ApiClient,
                 cd_exists: Callable[[str], bool],
                 targets: List[Tuple[GVR, Optional[str]]],
                 interval: float = 600.0,
                 extra_sweeps: Optional[List[Callable[[], None]]] = None):
        self._client = client
        self._cd_exists = cd_exists
        self._targets = targets
        self._interval = interval
        self._extra = extra_sweeps or []
        self._stop = threading.Event()
        self._kick = threading.Event()  # size-1 dedup: a set flag is "queued"
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="cd-cleanup")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._kick.set()
        if self._thread:
            self._thread.join(timeout=5)

    def request(self) -> None:
        """On-demand sweep; duplicate requests coalesce (cleanup.go:97-133)."""
        self._kick.set()

    def collect_uid(self, uid: str) -> None:
        """Immediate targeted GC for one departed CD."""
        self._collect(lambda u: u == uid)

    def _run(self) -> None:
        while not self._stop.is_set():
            self._kick.wait(timeout=self._interval)
            self._kick.clear()
            if self._stop.is_set():
                return
            try:
                self.sweep()
            except Exception:  # noqa: BLE001 — GC must not die
                log.exception("cleanup sweep failed")

    def sweep(self) -> None:
        self._collect(lambda uid: not self._cd_exists(uid))
        for fn in self._extra:
            fn()

    def _collect(self, is_stale: Callable[[str], bool]) -> None:
        for gvr, ns in self._targets:
            try:
                objs = self._client.list(
                    gvr, namespace=ns,
                    label_selector=apitypes.COMPUTE_DOMAIN_LABEL_KEY)
            except NotFoundError:
                continue
            for obj in objs:
                uid = (obj["metadata"].get("labels") or {}).get(
                    apitypes.COMPUTE_DOMAIN_LABEL_KEY, "")
                if not uid or not is_stale(uid):
                    continue
                meta = obj["metadata"]
                log.info("GC stale %s %s/%s (cd %s)", gvr.plural,
                         meta.get("namespace", ""), meta["name"], uid)
                if meta.get("finalizers"):
                    meta["finalizers"] = []
                    try:
                        self._client.update(gvr, obj,
                                            namespace=meta.get("namespace"))
                    except NotFoundError:
                        continue
                self._client.delete(gvr, meta["name"], meta.get("namespace"))
