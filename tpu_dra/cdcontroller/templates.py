"""Object builders for per-CD stamped resources.

The reference renders these from YAML templates
(templates/compute-domain-daemon.tmpl.yaml,
templates/compute-domain-daemon-claim-template.tmpl.yaml,
templates/compute-domain-workload-claim-template.tmpl.yaml, rendered by
cd-controller daemonset.go:201-246 / resourceclaimtemplate.go:281-400);
here they are dict builders with the same shape.
"""

from __future__ import annotations

from typing import Dict, Optional

from tpu_dra.api import types as apitypes
from tpu_dra.k8s.resources import new_object_meta, owner_reference

# Stable name prefix for per-CD objects; suffixed with the CD name.
DAEMON_PREFIX = "tpu-cd-daemon"


def cd_labels(cd_uid: str) -> Dict[str, str]:
    return {apitypes.COMPUTE_DOMAIN_LABEL_KEY: cd_uid}


def daemon_object_name(cd: Dict) -> str:
    return f"{DAEMON_PREFIX}-{cd['metadata']['name']}"


def daemon_daemonset(cd: Dict, *, namespace: str, image: str,
                     daemon_claim_template: str, log_verbosity: int = 0,
                     feature_gates: str = "",
                     max_nodes_per_slice_domain: int = 64,
                     service_account: str = "") -> Dict:
    """Per-CD DaemonSet. nodeSelector is the CD label, so daemon pods appear
    only as the CD kubelet plugin labels nodes (the workload-following
    behavior, daemonset.go:201-246)."""
    uid = cd["metadata"]["uid"]
    name = daemon_object_name(cd)
    labels = cd_labels(uid)
    pod_labels = dict(labels, **{"app.kubernetes.io/name": DAEMON_PREFIX})
    # The daemon updates CD status from inside its pod; when deployed via
    # the Helm chart it runs under the dedicated cd-daemon SA
    # (rbac-compute-domain-daemon.yaml) rather than the namespace default.
    sa_field = ({"serviceAccountName": service_account}
                if service_account else {})
    return {
        "apiVersion": "apps/v1",
        "kind": "DaemonSet",
        "metadata": new_object_meta(name, namespace, labels=labels,
                                    owner=None),
        "spec": {
            "selector": {"matchLabels": pod_labels},
            "template": {
                "metadata": {"labels": pod_labels},
                "spec": {
                    **sa_field,
                    "nodeSelector": cd_labels(uid),
                    "tolerations": [
                        {"key": "node-role.kubernetes.io/control-plane",
                         "operator": "Exists", "effect": "NoSchedule"},
                    ],
                    "hostNetwork": True,
                    "containers": [{
                        "name": "slice-daemon",
                        "image": image,
                        "command": ["python", "-m", "tpu_dra.cddaemon.main",
                                    "run"],
                        "env": [
                            {"name": "CD_UID", "value": uid},
                            {"name": "CD_NAME",
                             "value": cd["metadata"]["name"]},
                            {"name": "CD_NAMESPACE",
                             "value": cd["metadata"].get("namespace", "")},
                            {"name": "NODE_NAME", "valueFrom": {"fieldRef": {
                                "fieldPath": "spec.nodeName"}}},
                            {"name": "POD_NAME", "valueFrom": {"fieldRef": {
                                "fieldPath": "metadata.name"}}},
                            {"name": "POD_IP", "valueFrom": {"fieldRef": {
                                "fieldPath": "status.podIP"}}},
                            {"name": "LOG_VERBOSITY",
                             "value": str(log_verbosity)},
                            {"name": "FEATURE_GATES", "value": feature_gates},
                            {"name": "MAX_NODES_PER_SLICE_DOMAIN",
                             "value": str(max_nodes_per_slice_domain)},
                        ],
                        "startupProbe": {
                            "exec": {"command": [
                                "python", "-m", "tpu_dra.cddaemon.main",
                                "check"]},
                            "periodSeconds": 2,
                            "failureThreshold": 60,
                        },
                        "livenessProbe": {
                            "exec": {"command": [
                                "python", "-m", "tpu_dra.cddaemon.main",
                                "check"]},
                            "periodSeconds": 10,
                            "failureThreshold": 3,
                        },
                        "resources": {"claims": [{"name": "cd-daemon"}]},
                    }],
                    "resourceClaims": [{
                        "name": "cd-daemon",
                        "resourceClaimTemplateName": daemon_claim_template,
                    }],
                },
            },
        },
    }


def daemon_claim_template(cd: Dict, *, namespace: str) -> Dict:
    """RCT for the daemon pods' own claim (device class `compute-domain-
    daemon.tpu.dev`, opaque ComputeDomainDaemonConfig{domainID})."""
    uid = cd["metadata"]["uid"]
    cfg = apitypes.ComputeDomainDaemonConfig(domain_id=uid)
    return {
        "apiVersion": "resource.k8s.io/v1",
        "kind": "ResourceClaimTemplate",
        "metadata": new_object_meta(daemon_object_name(cd), namespace,
                                    labels=cd_labels(uid)),
        "spec": {"spec": {"devices": {
            "requests": [{
                "name": "daemon",
                "exactly": {"deviceClassName": apitypes.DEVICE_CLASS_DAEMON},
            }],
            "config": [{
                "requests": ["daemon"],
                "opaque": {
                    "driver": apitypes.COMPUTE_DOMAIN_DRIVER_NAME,
                    "parameters": cfg.to_dict(),
                },
            }],
        }}},
    }


def workload_claim_template(cd: Dict) -> Dict:
    """The user-facing RCT, created in the CD's namespace under the name the
    user chose in spec.channel.resourceClaimTemplate.name
    (resourceclaimtemplate.go:365-400). Owned by the CD so cascade deletion
    works even if the controller dies mid-teardown."""
    uid = cd["metadata"]["uid"]
    spec = cd.get("spec", {})
    channel = spec.get("channel") or {}
    name = (channel.get("resourceClaimTemplate") or {}).get("name", "")
    cfg = apitypes.ComputeDomainChannelConfig(
        domain_id=uid,
        allocation_mode=channel.get("allocationMode",
                                    apitypes.ALLOCATION_MODE_SINGLE))
    return {
        "apiVersion": "resource.k8s.io/v1",
        "kind": "ResourceClaimTemplate",
        "metadata": new_object_meta(
            name, cd["metadata"].get("namespace", "default"),
            labels=cd_labels(uid),
            owner=owner_reference({
                "apiVersion": apitypes.API_VERSION,
                "kind": apitypes.COMPUTE_DOMAIN_KIND,
                "metadata": cd["metadata"]})),
        "spec": {"spec": {"devices": {
            "requests": [{
                "name": "channel",
                "exactly": {"deviceClassName": apitypes.DEVICE_CLASS_CHANNEL},
            }],
            "config": [{
                "requests": ["channel"],
                "opaque": {
                    "driver": apitypes.COMPUTE_DOMAIN_DRIVER_NAME,
                    "parameters": cfg.to_dict(),
                },
            }],
        }}},
    }
