"""WorkloadController: DaemonSet/Deployment -> Pod stamping + status.

The kube-controller-manager analog the CD machinery needs: the CD
controller stamps per-CD DaemonSets whose nodeSelector is the CD label;
something must turn those into pods as nodes get labeled, keep the DS
status fresh (desiredNumberScheduled is the CD controller's lower bound
for open-ended readiness; per-node readiness itself comes from
cd.status.nodes — controller._update_readiness), and delete pods when
labels go away (the workload-following teardown).
"""

from __future__ import annotations

import hashlib
import json
import logging
import threading
from typing import Dict, List, Optional

from tpu_dra.k8s.client import ApiClient, ApiError, ConflictError, NotFoundError
from tpu_dra.k8s.resources import DAEMONSETS, DEPLOYMENTS, NODES, PODS

log = logging.getLogger("simcluster.workloads")


def _template_hash(owner: Dict) -> str:
    """Stable hash of a DS/Deployment pod template — the pod-template-hash
    analog that lets the sim roll pods on chart upgrades."""
    payload = json.dumps(owner["spec"]["template"], sort_keys=True)
    return hashlib.sha1(payload.encode()).hexdigest()[:10]


class WorkloadController:
    def __init__(self, client: ApiClient, interval: float = 0.2):
        self._client = client
        self._interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="sim-workloads")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.reconcile_once()
            except Exception:  # noqa: BLE001
                log.exception("workload reconcile failed")

    # ------------------------------------------------------------------

    def reconcile_once(self) -> None:
        nodes = self._client.list(NODES)
        pods = self._client.list(PODS)
        daemonsets = self._client.list(DAEMONSETS)
        deployments = self._client.list(DEPLOYMENTS)
        for ds in daemonsets:
            try:
                self._reconcile_daemonset(ds, nodes, pods)
            except ConflictError:
                continue
        for dep in deployments:
            try:
                self._reconcile_deployment(dep, pods)
            except ConflictError:
                continue
        # Orphan GC (the reference CleanupManager analog,
        # cd-controller cleanup.go:97-133): a stamped pod whose owning
        # DS/Deployment is gone would otherwise linger forever — e.g. a
        # per-CD daemon pod after its CD (and thus its DaemonSet) was
        # deleted mid-flight.
        owners = {(d["metadata"].get("namespace", "default"),
                   f"ds-{d['metadata']['name']}") for d in daemonsets}
        owners |= {(d["metadata"].get("namespace", "default"),
                    f"deploy-{d['metadata']['name']}") for d in deployments}
        for p in pods:
            tag = (p["metadata"].get("labels") or {}).get("sim/owner")
            ns = p["metadata"].get("namespace", "default")
            if tag and (ns, tag) not in owners:
                self._delete_pod(p["metadata"]["name"], ns)

    # -- DaemonSets -----------------------------------------------------

    def _reconcile_daemonset(self, ds: Dict, nodes: List[Dict],
                             pods: List[Dict]) -> None:
        ns = ds["metadata"].get("namespace", "default")
        name = ds["metadata"]["name"]
        selector = (ds["spec"]["template"]["spec"]
                    .get("nodeSelector") or {})
        want_nodes = {
            n["metadata"]["name"] for n in nodes
            if all((n["metadata"].get("labels") or {}).get(k) == v
                   for k, v in selector.items())}
        owned = {p["metadata"]["name"]: p for p in pods
                 if p["metadata"].get("namespace") == ns
                 and (p["metadata"].get("labels") or {}).get(
                     "sim/owner") == f"ds-{name}"}
        tmpl_hash = _template_hash(ds)
        for node in sorted(want_nodes):
            pod_name = f"{name}-{node}"
            if pod_name not in owned:
                self._create_pod(ds, pod_name, ns, f"ds-{name}",
                                 node_name=node)
        for pod_name, pod in owned.items():
            if pod["spec"].get("nodeName") not in want_nodes:
                # Node left the selector (label removed): workload-following
                # teardown.
                self._delete_pod(pod_name, ns)
            elif (pod["metadata"]["labels"].get("sim/template-hash")
                  != tmpl_hash):
                # Template changed (chart upgrade): roll the pod — delete
                # now, the next reconcile recreates it from the new
                # template (the DaemonSet RollingUpdate analog; the CD
                # controller's own template-hash convergence depends on
                # this, controller.py).
                self._delete_pod(pod_name, ns)
        ready = sum(1 for p in owned.values()
                    if self._pod_ready(p)
                    and p["spec"].get("nodeName") in want_nodes)
        status = {"desiredNumberScheduled": len(want_nodes),
                  "currentNumberScheduled": len(owned),
                  "numberReady": ready}
        if (ds.get("status") or {}) != status:
            ds["status"] = status
            try:
                self._client.update_status(DAEMONSETS, ds, ns)
            except ApiError:
                pass

    # -- Deployments ----------------------------------------------------

    def _reconcile_deployment(self, dep: Dict, pods: List[Dict]) -> None:
        ns = dep["metadata"].get("namespace", "default")
        name = dep["metadata"]["name"]
        replicas = int(dep["spec"].get("replicas", 1))
        owned = {p["metadata"]["name"]: p for p in pods
                 if p["metadata"].get("namespace") == ns
                 and (p["metadata"].get("labels") or {}).get(
                     "sim/owner") == f"deploy-{name}"}
        tmpl_hash = _template_hash(dep)
        for i in range(replicas):
            pod_name = f"{name}-{i}"
            if pod_name not in owned:
                self._create_pod(dep, pod_name, ns, f"deploy-{name}")
        for pod_name, pod in list(owned.items()):
            idx = pod_name.rsplit("-", 1)[-1]
            if idx.isdigit() and int(idx) >= replicas:
                self._delete_pod(pod_name, ns)
            elif (pod["metadata"]["labels"].get("sim/template-hash")
                  != tmpl_hash):
                self._delete_pod(pod_name, ns)  # roll on template change
        ready = sum(1 for p in owned.values() if self._pod_ready(p))
        status = {"replicas": len(owned), "readyReplicas": ready,
                  "availableReplicas": ready}
        if (dep.get("status") or {}) != status:
            dep["status"] = status
            try:
                self._client.update_status(DEPLOYMENTS, dep, ns)
            except ApiError:
                pass

    # -- shared ---------------------------------------------------------

    def _create_pod(self, owner: Dict, pod_name: str, ns: str,
                    owner_tag: str, node_name: Optional[str] = None) -> None:
        template = owner["spec"]["template"]
        labels = dict(template.get("metadata", {}).get("labels") or {})
        labels["sim/owner"] = owner_tag
        labels["sim/template-hash"] = _template_hash(owner)
        spec = dict(template["spec"])
        if node_name:
            spec = {**spec, "nodeName": node_name}
        pod = {
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": pod_name, "namespace": ns,
                         "labels": labels},
            "spec": spec,
        }
        try:
            self._client.create(PODS, pod, namespace=ns)
            log.info("stamped pod %s/%s (owner %s)", ns, pod_name, owner_tag)
        except ConflictError:
            pass

    def _delete_pod(self, name: str, ns: str) -> None:
        try:
            self._client.delete(PODS, name, ns)
            log.info("deleted pod %s/%s", ns, name)
        except NotFoundError:
            pass

    @staticmethod
    def _pod_ready(pod: Dict) -> bool:
        for cond in (pod.get("status") or {}).get("conditions") or []:
            if cond.get("type") == "Ready":
                return cond.get("status") == "True"
        return False
