"""simcluster: a cluster-in-processes for the e2e tier.

The dev/CI environment has no kind/kubectl/docker (SURVEY §4.2's
"simulated accel device directory" CI tier). This package stands in for
the cluster pieces the driver does NOT own, so the pieces it DOES own run
for real, as subprocesses, wired over real HTTP/gRPC:

- FakeApiServer        -> the API server (HTTP + watch)
- Scheduler            -> claims-from-templates + DRA allocation + binding
                          (upstream kube-scheduler's DRA plugin analog)
- WorkloadController   -> DaemonSet/Deployment -> Pod stamping + status
                          (kube-controller-manager analog)
- NodeSim              -> per-node kubelet: runs pod commands as real
                          subprocesses, drives the REAL driver plugins over
                          their dra.sock gRPC, applies REAL CDI spec edits
                          to container env, runs probes, reports status

The driver components themselves (kubelet plugins, CD controller, CD
daemon wrapping the C++ slice daemon, webhook, multiprocess coordinator)
are launched from the SAME manifests the Helm chart renders — nothing is
faked inside the driver path.

`python -m tpu_dra.simcluster` serves a cluster for hack/e2e-up.sh; the
kubectl shim (hack/kubectl_shim.py) talks to its URL.
"""

from tpu_dra.simcluster.cluster import SimCluster  # noqa: F401
