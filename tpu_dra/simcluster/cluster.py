"""SimCluster assembly + `python -m tpu_dra.simcluster` server mode."""

from __future__ import annotations

import json
import logging
import os
import signal
import sys
import threading
from typing import Dict, List, Optional

from tpu_dra.k8s.client import AlreadyExistsError, HttpApiClient
from tpu_dra.k8s.fakeserver import FakeApiServer
from tpu_dra.native.tpuinfo import default_fake_chips, make_fake_sysfs
from tpu_dra.simcluster.gvk import gvr_for_doc
from tpu_dra.simcluster.nodesim import NodeSim
from tpu_dra.simcluster.scheduler import Scheduler
from tpu_dra.simcluster.workloads import WorkloadController

log = logging.getLogger("simcluster")


class SimCluster:
    """N simulated TPU nodes around a FakeApiServer; see package docstring.

    Each node gets a hostfs with a make_fake_sysfs tree (the kind-node
    fake-accel-mount analog), so the kubelet plugins launched onto it
    enumerate chips through the REAL C++ libtpuinfo against that tree.
    """

    def __init__(self, workdir: str, *, num_nodes: int = 2,
                 chips_per_node: int = 4, slice_id: str = "slice-A",
                 slice_ids: Optional[List[str]] = None,
                 generation: str = "v5p"):
        """slice_ids: per-node ICI slice identity (topology/slice_id in the
        fake sysfs). Different ids across nodes make a ComputeDomain
        heterogeneous — the multislice/DCN (megascale) path.
        generation: fake chip generation; default v5p (2 TensorCores per
        chip) so the subslice (MIG-analog) inventory is non-empty —
        single-core generations like v5e have nothing to subdivide."""
        from tpu_dra.simcluster.admission import WebhookCaller

        self.workdir = workdir
        self.server = FakeApiServer()
        # Wire the admission chain: registered validating webhooks are
        # actually called on create/update, like the real apiserver.
        self.server.admission_hook = WebhookCaller(self.server.cluster)
        self.nodes: Dict[str, NodeSim] = {}
        self._num_nodes = num_nodes
        self._chips = chips_per_node
        self._generation = generation
        self._slice_ids = (list(slice_ids) if slice_ids
                           else [slice_id] * num_nodes)
        if len(self._slice_ids) != num_nodes:
            raise ValueError("slice_ids must have one entry per node")
        self.scheduler: Optional[Scheduler] = None
        self.workloads: Optional[WorkloadController] = None
        self.api: Optional[HttpApiClient] = None

    @property
    def url(self) -> str:
        return self.server.url

    def start(self) -> "SimCluster":
        self.server.start()
        self.api = HttpApiClient(base_url=self.server.url)
        from tpu_dra.k8s.resources import NODES
        for i in range(self._num_nodes):
            # Short names throughout: the kubelet registry socket path
            # must stay under the AF_UNIX 107-char limit
            # (<workdir>/<node>/fs/var/lib/kubelet/plugins_registry/
            # compute-domain.tpu.dev-reg.sock).
            name = f"n{i}"
            node_dir = os.path.join(self.workdir, name)
            hostfs = os.path.join(node_dir, "fs")
            chips = default_fake_chips(self._chips, self._generation,
                                       self._slice_ids[i], i)
            make_fake_sysfs(hostfs, chips)
            self.api.create(NODES, {
                "apiVersion": "v1", "kind": "Node",
                "metadata": {"name": name,
                             "labels": {"tpu.dev/present": "true"}},
            })
            sim = NodeSim(self.api, name, node_dir, api_url=self.server.url)
            sim.start()
            self.nodes[name] = sim
        self.scheduler = Scheduler(self.api)
        self.scheduler.start()
        self.workloads = WorkloadController(self.api)
        self.workloads.start()
        return self

    def stop(self) -> None:
        if self.workloads:
            self.workloads.stop()
        if self.scheduler:
            self.scheduler.stop()
        for sim in self.nodes.values():
            sim.stop()
        self.server.stop()

    # ------------------------------------------------------------------

    def install(self, docs: List[Dict]) -> int:
        """Apply manifests (the `kubectl apply -f` of the install step).
        Returns the number of objects created."""
        assert self.api is not None
        n = 0
        for doc in docs:
            if not doc:
                continue
            gvr = gvr_for_doc(doc)
            ns = doc["metadata"].get("namespace")
            try:
                self.api.create(gvr, doc, namespace=ns)
                n += 1
            except AlreadyExistsError:
                self.api.update(gvr, doc, ns)
        return n


def main(argv=None) -> int:
    """Serve a sim cluster until SIGTERM; used by hack/e2e-up.sh.

    Writes {url, workdir, pid} as JSON to --state-file once ready so the
    caller (and the kubectl shim via KUBECTL_SHIM_STATE) can find it.
    """
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--workdir", required=True)
    ap.add_argument("--nodes", type=int, default=2)
    ap.add_argument("--chips-per-node", type=int, default=4)
    from tpu_dra.native.tpuinfo import GEN_SPECS
    ap.add_argument("--generation", default="v5p",
                    choices=sorted(GEN_SPECS))
    ap.add_argument("--slice-ids", default="",
                    help="comma-separated per-node slice ids (different "
                         "ids = heterogeneous/multislice topology)")
    ap.add_argument("--state-file", default="")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)

    logging.basicConfig(
        level=logging.DEBUG if args.verbose else logging.INFO,
        format="%(asctime)s %(name)s %(levelname)s %(message)s")

    slice_ids = ([s.strip() for s in args.slice_ids.split(",") if s.strip()]
                 or None)
    cluster = SimCluster(args.workdir, num_nodes=args.nodes,
                         chips_per_node=args.chips_per_node,
                         slice_ids=slice_ids,
                         generation=args.generation).start()
    state = {"url": cluster.url, "workdir": args.workdir,
             "pid": os.getpid()}
    if args.state_file:
        with open(args.state_file, "w") as f:
            json.dump(state, f)
    print(json.dumps(state), flush=True)

    stop = threading.Event()
    signal.signal(signal.SIGTERM, lambda *_: stop.set())
    signal.signal(signal.SIGINT, lambda *_: stop.set())
    stop.wait()
    cluster.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
