"""CEL-subset evaluator for scheduler-side device selection.

The upstream kube-scheduler evaluates full CEL over published device
attributes when allocating DRA claims (SURVEY §1: DeviceClass selectors
plus per-request selectors). The sim implements the subset the demo
ladder and e2e tier actually use, so a wrong attribute name, a type
mismatch, or a non-matching value FAILS selection instead of silently
matching (VERDICT r4 missing #1; reference demo shape:
demo/specs/quickstart/v1/gpu-test6.yaml:26-35):

    device.driver == "tpu.dev"
    device.attributes['tpu.dev'].generation == 'v5p'
    device.attributes['tpu.dev'].coordX >= 1
    device.attributes['tpu.dev'].productName.lowerAscii().matches('v5p')
    a && b, a || b, !a, (a)

Evaluation context is one published resourceapi.Device: the slice's
driver name plus the device's typed attribute map
({"string": v} | {"int": v} | {"bool": v} | {"version": v}).

An unknown attribute, a driver-key mismatch in `device.attributes[...]`,
or a type error raises CelError — callers treat that as "device does not
match", which is the observable behavior of a CEL runtime error in the
real scheduler.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Tuple

_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<op>&&|\|\||==|!=|>=|<=|>|<|!|\(|\)|\[|\]|\.)
    | (?P<str>'(?:[^'\\]|\\.)*'|"(?:[^"\\]|\\.)*")
    | (?P<int>-?\d+)
    | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
    )""", re.VERBOSE)


class CelError(Exception):
    pass


def _tokenize(expr: str) -> List[Tuple[str, str]]:
    tokens = []
    pos = 0
    while pos < len(expr):
        m = _TOKEN_RE.match(expr, pos)
        if m is None or m.end() == pos:
            rest = expr[pos:].strip()
            if not rest:
                break
            raise CelError(f"cannot tokenize at {rest[:20]!r}")
        pos = m.end()
        for kind in ("op", "str", "int", "ident"):
            val = m.group(kind)
            if val is not None:
                tokens.append((kind, val))
                break
    return tokens


class _Parser:
    """Recursive descent over the token list; evaluates as it parses
    (short-circuit for && / ||)."""

    def __init__(self, tokens: List[Tuple[str, str]], driver: str,
                 attributes: Dict[str, Dict]):
        self._toks = tokens
        self._i = 0
        self._driver = driver
        self._attrs = attributes

    # -- token helpers --------------------------------------------------

    def _peek(self):
        return self._toks[self._i] if self._i < len(self._toks) else None

    def _next(self):
        tok = self._peek()
        if tok is None:
            raise CelError("unexpected end of expression")
        self._i += 1
        return tok

    def _accept(self, kind: str, value: str = None) -> bool:
        tok = self._peek()
        if tok and tok[0] == kind and (value is None or tok[1] == value):
            self._i += 1
            return True
        return False

    def _expect(self, kind: str, value: str = None):
        tok = self._next()
        if tok[0] != kind or (value is not None and tok[1] != value):
            raise CelError(f"expected {value or kind}, got {tok[1]!r}")
        return tok

    # -- grammar --------------------------------------------------------

    def parse(self) -> Any:
        v = self._or()
        if self._peek() is not None:
            raise CelError(f"trailing tokens at {self._peek()[1]!r}")
        return v

    def _or(self) -> Any:
        v = self._and()
        while self._accept("op", "||"):
            rhs = self._and()
            v = self._truthy(v) or self._truthy(rhs)
        return v

    def _and(self) -> Any:
        v = self._cmp()
        while self._accept("op", "&&"):
            rhs = self._cmp()
            v = self._truthy(v) and self._truthy(rhs)
        return v

    def _cmp(self) -> Any:
        lhs = self._unary()
        tok = self._peek()
        if tok and tok[0] == "op" and tok[1] in ("==", "!=", ">=",
                                                 "<=", ">", "<"):
            op = self._next()[1]
            rhs = self._unary()
            if type(lhs) is not type(rhs):
                raise CelError(
                    f"type mismatch: {type(lhs).__name__} {op} "
                    f"{type(rhs).__name__}")
            if op == "==":
                return lhs == rhs
            if op == "!=":
                return lhs != rhs
            if isinstance(lhs, bool):
                raise CelError(f"ordering comparison on bool ({op})")
            if op == ">=":
                return lhs >= rhs
            if op == "<=":
                return lhs <= rhs
            if op == ">":
                return lhs > rhs
            return lhs < rhs
        return lhs

    def _unary(self) -> Any:
        if self._accept("op", "!"):
            return not self._truthy(self._unary())
        return self._primary()

    def _primary(self) -> Any:
        if self._accept("op", "("):
            v = self._or()
            self._expect("op", ")")
            return self._methods(v)
        tok = self._next()
        if tok[0] == "str":
            return self._methods(_unquote(tok[1]))
        if tok[0] == "int":
            return int(tok[1])
        if tok[0] == "ident":
            if tok[1] in ("true", "false"):
                return tok[1] == "true"
            if tok[1] == "device":
                return self._methods(self._device_chain())
            raise CelError(f"unknown identifier {tok[1]!r}")
        raise CelError(f"unexpected token {tok[1]!r}")

    def _device_chain(self) -> Any:
        self._expect("op", ".")
        field = self._expect("ident")[1]
        if field == "driver":
            return self._driver
        if field != "attributes":
            raise CelError(f"unknown device field {field!r}")
        self._expect("op", "[")
        key = _unquote(self._expect("str")[1])
        self._expect("op", "]")
        if key != self._driver:
            # The real API nests attribute names under the driver's
            # domain; a wrong key must not match anything.
            raise CelError(
                f"attribute domain {key!r} does not match driver "
                f"{self._driver!r}")
        self._expect("op", ".")
        name = self._expect("ident")[1]
        if name not in self._attrs:
            raise CelError(f"unknown attribute {name!r}")
        typed = self._attrs[name]
        for typ in ("string", "int", "bool", "version"):
            if typ in typed:
                val = typed[typ]
                return int(val) if typ == "int" else val
        raise CelError(f"attribute {name!r} has no supported type")

    def _methods(self, value: Any) -> Any:
        """Postfix method calls on a value: .lowerAscii(), .matches(re)."""
        while True:
            save = self._i
            if not self._accept("op", "."):
                return value
            tok = self._peek()
            if tok is None or tok[0] != "ident" or tok[1] not in (
                    "lowerAscii", "matches"):
                self._i = save
                return value
            method = self._next()[1]
            self._expect("op", "(")
            if method == "lowerAscii":
                self._expect("op", ")")
                if not isinstance(value, str):
                    raise CelError("lowerAscii() on non-string")
                value = value.lower()
            else:
                pattern = _unquote(self._expect("str")[1])
                self._expect("op", ")")
                if not isinstance(value, str):
                    raise CelError("matches() on non-string")
                # CEL matches() is an unanchored RE2 search.
                value = re.search(pattern, value) is not None

    @staticmethod
    def _truthy(v: Any) -> bool:
        if not isinstance(v, bool):
            raise CelError(f"non-bool in boolean context: {v!r}")
        return v


def _unquote(raw: str) -> str:
    body = raw[1:-1]
    return re.sub(r"\\(.)", r"\1", body)


def evaluate(expr: str, *, driver: str, attributes: Dict[str, Dict]) -> bool:
    """True iff `expr` selects a device with the given driver/attributes.
    Raises CelError on unsupported syntax, unknown attributes, or type
    errors (callers treat that as no-match)."""
    result = _Parser(_tokenize(expr), driver, attributes).parse()
    if not isinstance(result, bool):
        raise CelError(f"expression is not boolean: {result!r}")
    return result


def device_matches(expr: str, device: Dict, driver: str) -> bool:
    """Evaluate against a published resourceapi.Device entry; a CEL error
    means the device is not selectable by this expression (the real
    scheduler's observable behavior for runtime errors)."""
    try:
        return evaluate(expr, driver=driver,
                        attributes=device.get("attributes") or {})
    except CelError:
        return False
