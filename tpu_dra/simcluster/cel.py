"""CEL-subset evaluator for scheduler-side device selection.

The upstream kube-scheduler evaluates full CEL over published device
attributes when allocating DRA claims (SURVEY §1: DeviceClass selectors
plus per-request selectors). The sim implements the subset the demo
ladder and e2e tier actually use, so a wrong attribute name, a type
mismatch, or a non-matching value FAILS selection instead of silently
matching (VERDICT r4 missing #1; reference demo shape:
demo/specs/quickstart/v1/gpu-test6.yaml:26-35):

    device.driver == "tpu.dev"
    device.attributes['tpu.dev'].generation == 'v5p'
    device.attributes['tpu.dev'].coordX >= 1
    device.attributes['tpu.dev'].productName.lowerAscii().matches('v5p')
    a && b, a || b, !a, (a)

Compilation and evaluation are SPLIT (SURVEY §10): an expression is
tokenized and parsed ONCE into an AST (`compile_expr`), cached in a
process-wide table keyed by the full source string, and the AST is then
evaluated against any number of devices. The real scheduler does exactly
this with cel-go programs; the poll-era evaluator here re-tokenized and
re-parsed per (expression, device) pair, which dominated allocation cost
at churn scale. Cache hits/misses/compiles are counted on
``tpu_dra.infra.metrics`` (CEL_CACHE_HITS / CEL_CACHE_MISSES /
CEL_COMPILES) so the perf tier can assert compiles <= distinct
expressions seen.

Evaluation context is one published resourceapi.Device: the slice's
driver name plus the device's typed attribute map
({"string": v} | {"int": v} | {"bool": v} | {"version": v}).

An unknown attribute, a driver-key mismatch in `device.attributes[...]`,
or a type error raises CelError — callers treat that as "device does not
match", which is the observable behavior of a CEL runtime error in the
real scheduler. Syntax errors (including bad regex literals) surface at
compile time and are negatively cached, so a broken DeviceClass selector
costs one parse, not one per candidate device.
"""

from __future__ import annotations

import re
import threading
from typing import Any, Dict, List, Optional, Tuple

from tpu_dra.infra.metrics import (
    CEL_CACHE_HITS, CEL_CACHE_MISSES, CEL_COMPILES,
)

_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<op>&&|\|\||==|!=|>=|<=|>|<|!|\(|\)|\[|\]|\.)
    | (?P<str>'(?:[^'\\]|\\.)*'|"(?:[^"\\]|\\.)*")
    | (?P<int>-?\d+)
    | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
    )""", re.VERBOSE)


class CelError(Exception):
    pass


def _tokenize(expr: str) -> List[Tuple[str, str]]:
    tokens = []
    pos = 0
    while pos < len(expr):
        m = _TOKEN_RE.match(expr, pos)
        if m is None or m.end() == pos:
            rest = expr[pos:].strip()
            if not rest:
                break
            raise CelError(f"cannot tokenize at {rest[:20]!r}")
        pos = m.end()
        for kind in ("op", "str", "int", "ident"):
            val = m.group(kind)
            if val is not None:
                tokens.append((kind, val))
                break
    return tokens


# ---------------------------------------------------------------------------
# AST nodes — compile once, evaluate per device
# ---------------------------------------------------------------------------

def _truthy(v: Any) -> bool:
    if not isinstance(v, bool):
        raise CelError(f"non-bool in boolean context: {v!r}")
    return v


class _Node:
    __slots__ = ()

    def eval(self, driver: str, attributes: Dict[str, Dict]) -> Any:
        raise NotImplementedError


class _Const(_Node):
    __slots__ = ("value",)

    def __init__(self, value: Any):
        self.value = value

    def eval(self, driver, attributes) -> Any:
        return self.value


class _Driver(_Node):
    __slots__ = ()

    def eval(self, driver, attributes) -> Any:
        return driver


class _Attr(_Node):
    """`device.attributes['<domain>'].<name>` — domain/driver match and
    attribute existence are per-device facts, so they stay eval-time."""

    __slots__ = ("domain", "name")

    def __init__(self, domain: str, name: str):
        self.domain = domain
        self.name = name

    def eval(self, driver, attributes) -> Any:
        if self.domain != driver:
            # The real API nests attribute names under the driver's
            # domain; a wrong key must not match anything.
            raise CelError(
                f"attribute domain {self.domain!r} does not match driver "
                f"{driver!r}")
        if self.name not in attributes:
            raise CelError(f"unknown attribute {self.name!r}")
        typed = attributes[self.name]
        for typ in ("string", "int", "bool", "version"):
            if typ in typed:
                val = typed[typ]
                return int(val) if typ == "int" else val
        raise CelError(f"attribute {self.name!r} has no supported type")


class _Not(_Node):
    __slots__ = ("inner",)

    def __init__(self, inner: _Node):
        self.inner = inner

    def eval(self, driver, attributes) -> Any:
        return not _truthy(self.inner.eval(driver, attributes))


class _And(_Node):
    __slots__ = ("lhs", "rhs")

    def __init__(self, lhs: _Node, rhs: _Node):
        self.lhs = lhs
        self.rhs = rhs

    def eval(self, driver, attributes) -> Any:
        # Short-circuit like CEL: the rhs is not evaluated (and cannot
        # raise) when the lhs already decides.
        if not _truthy(self.lhs.eval(driver, attributes)):
            return False
        return _truthy(self.rhs.eval(driver, attributes))


class _Or(_Node):
    __slots__ = ("lhs", "rhs")

    def __init__(self, lhs: _Node, rhs: _Node):
        self.lhs = lhs
        self.rhs = rhs

    def eval(self, driver, attributes) -> Any:
        if _truthy(self.lhs.eval(driver, attributes)):
            return True
        return _truthy(self.rhs.eval(driver, attributes))


class _Cmp(_Node):
    __slots__ = ("op", "lhs", "rhs")

    def __init__(self, op: str, lhs: _Node, rhs: _Node):
        self.op = op
        self.lhs = lhs
        self.rhs = rhs

    def eval(self, driver, attributes) -> Any:
        lhs = self.lhs.eval(driver, attributes)
        rhs = self.rhs.eval(driver, attributes)
        op = self.op
        if type(lhs) is not type(rhs):
            raise CelError(
                f"type mismatch: {type(lhs).__name__} {op} "
                f"{type(rhs).__name__}")
        if op == "==":
            return lhs == rhs
        if op == "!=":
            return lhs != rhs
        if isinstance(lhs, bool):
            raise CelError(f"ordering comparison on bool ({op})")
        if op == ">=":
            return lhs >= rhs
        if op == "<=":
            return lhs <= rhs
        if op == ">":
            return lhs > rhs
        return lhs < rhs


class _LowerAscii(_Node):
    __slots__ = ("inner",)

    def __init__(self, inner: _Node):
        self.inner = inner

    def eval(self, driver, attributes) -> Any:
        value = self.inner.eval(driver, attributes)
        if not isinstance(value, str):
            raise CelError("lowerAscii() on non-string")
        return value.lower()


class _Matches(_Node):
    """CEL matches() is an unanchored RE2 search; the pattern is a
    literal, so it is compiled once with the expression."""

    __slots__ = ("inner", "pattern")

    def __init__(self, inner: _Node, pattern: "re.Pattern"):
        self.inner = inner
        self.pattern = pattern

    def eval(self, driver, attributes) -> Any:
        value = self.inner.eval(driver, attributes)
        if not isinstance(value, str):
            raise CelError("matches() on non-string")
        return self.pattern.search(value) is not None


class Program:
    """A compiled CEL expression: evaluate against any device."""

    __slots__ = ("source", "_root")

    def __init__(self, source: str, root: _Node):
        self.source = source
        self._root = root

    def evaluate(self, *, driver: str, attributes: Dict[str, Dict]) -> bool:
        """True iff the expression selects a device with the given
        driver/attributes; CelError on runtime type/attribute errors."""
        result = self._root.eval(driver, attributes)
        if not isinstance(result, bool):
            raise CelError(f"expression is not boolean: {result!r}")
        return result

    def matches(self, device: Dict, driver: str) -> bool:
        """Evaluate against a published resourceapi.Device entry; a CEL
        runtime error means the device is not selectable."""
        try:
            return self.evaluate(driver=driver,
                                 attributes=device.get("attributes") or {})
        except CelError:
            return False


class _Parser:
    """Recursive descent over the token list, producing an AST (the
    compile half; short-circuit lives in the _And/_Or nodes)."""

    def __init__(self, tokens: List[Tuple[str, str]]):
        self._toks = tokens
        self._i = 0

    # -- token helpers --------------------------------------------------

    def _peek(self):
        return self._toks[self._i] if self._i < len(self._toks) else None

    def _next(self):
        tok = self._peek()
        if tok is None:
            raise CelError("unexpected end of expression")
        self._i += 1
        return tok

    def _accept(self, kind: str, value: str = None) -> bool:
        tok = self._peek()
        if tok and tok[0] == kind and (value is None or tok[1] == value):
            self._i += 1
            return True
        return False

    def _expect(self, kind: str, value: str = None):
        tok = self._next()
        if tok[0] != kind or (value is not None and tok[1] != value):
            raise CelError(f"expected {value or kind}, got {tok[1]!r}")
        return tok

    # -- grammar --------------------------------------------------------

    def parse(self) -> _Node:
        node = self._or()
        if self._peek() is not None:
            raise CelError(f"trailing tokens at {self._peek()[1]!r}")
        return node

    def _or(self) -> _Node:
        node = self._and()
        while self._accept("op", "||"):
            node = _Or(node, self._and())
        return node

    def _and(self) -> _Node:
        node = self._cmp()
        while self._accept("op", "&&"):
            node = _And(node, self._cmp())
        return node

    def _cmp(self) -> _Node:
        lhs = self._unary()
        tok = self._peek()
        if tok and tok[0] == "op" and tok[1] in ("==", "!=", ">=",
                                                 "<=", ">", "<"):
            op = self._next()[1]
            return _Cmp(op, lhs, self._unary())
        return lhs

    def _unary(self) -> _Node:
        if self._accept("op", "!"):
            return _Not(self._unary())
        return self._primary()

    def _primary(self) -> _Node:
        if self._accept("op", "("):
            node = self._or()
            self._expect("op", ")")
            return self._methods(node)
        tok = self._next()
        if tok[0] == "str":
            return self._methods(_Const(_unquote(tok[1])))
        if tok[0] == "int":
            return _Const(int(tok[1]))
        if tok[0] == "ident":
            if tok[1] in ("true", "false"):
                return _Const(tok[1] == "true")
            if tok[1] == "device":
                return self._methods(self._device_chain())
            raise CelError(f"unknown identifier {tok[1]!r}")
        raise CelError(f"unexpected token {tok[1]!r}")

    def _device_chain(self) -> _Node:
        self._expect("op", ".")
        field = self._expect("ident")[1]
        if field == "driver":
            return _Driver()
        if field != "attributes":
            raise CelError(f"unknown device field {field!r}")
        self._expect("op", "[")
        key = _unquote(self._expect("str")[1])
        self._expect("op", "]")
        self._expect("op", ".")
        name = self._expect("ident")[1]
        return _Attr(key, name)

    def _methods(self, node: _Node) -> _Node:
        """Postfix method calls on a value: .lowerAscii(), .matches(re)."""
        while True:
            save = self._i
            if not self._accept("op", "."):
                return node
            tok = self._peek()
            if tok is None or tok[0] != "ident" or tok[1] not in (
                    "lowerAscii", "matches"):
                self._i = save
                return node
            method = self._next()[1]
            self._expect("op", "(")
            if method == "lowerAscii":
                self._expect("op", ")")
                node = _LowerAscii(node)
            else:
                pattern = _unquote(self._expect("str")[1])
                self._expect("op", ")")
                try:
                    compiled = re.compile(pattern)
                except re.error as e:
                    raise CelError(f"bad matches() pattern "
                                   f"{pattern!r}: {e}") from e
                node = _Matches(node, compiled)


def _unquote(raw: str) -> str:
    body = raw[1:-1]
    return re.sub(r"\\(.)", r"\1", body)


# ---------------------------------------------------------------------------
# Compile cache
# ---------------------------------------------------------------------------

# source string -> Program | CelError (negative entries keep a broken
# selector from being re-parsed per candidate device). Keyed by the FULL
# source string so near-identical expressions ('v5p' vs 'v5e') never
# collide. Bounded as a leak guard: selector sources come from
# DeviceClasses and claim specs, so real populations are tiny; synthetic
# floods (a fuzzer minting unique expressions) clear and restart rather
# than growing without bound.
_CACHE_MAX = 4096
_cache: Dict[str, Any] = {}
_cache_lock = threading.Lock()


def compile_expr(source: str) -> Program:
    """Parse `source` into a Program, memoized process-wide. Raises
    CelError on syntax errors (also memoized)."""
    cached = _cache.get(source)  # lock-free fast path (GIL-atomic read)
    if cached is None:
        CEL_CACHE_MISSES.inc()
        with _cache_lock:
            cached = _cache.get(source)
            if cached is None:
                CEL_COMPILES.inc()
                if len(_cache) >= _CACHE_MAX:
                    _cache.clear()
                try:
                    cached = Program(source, _Parser(_tokenize(source)).parse())
                except CelError as e:
                    cached = e
                _cache[source] = cached
    else:
        CEL_CACHE_HITS.inc()
    if isinstance(cached, CelError):
        raise cached
    return cached


def cache_info() -> Dict[str, int]:
    """Introspection for tests/bench: cached entry count (compiled +
    negative) — counters live on tpu_dra.infra.metrics."""
    with _cache_lock:
        programs = sum(1 for v in _cache.values() if isinstance(v, Program))
        return {"entries": len(_cache), "programs": programs,
                "errors": len(_cache) - programs}


def clear_cache() -> None:
    """Test hook: drop all cached programs (counters are not reset)."""
    with _cache_lock:
        _cache.clear()


# ---------------------------------------------------------------------------
# Convenience entry points (compile-cache-backed)
# ---------------------------------------------------------------------------

def evaluate(expr: str, *, driver: str, attributes: Dict[str, Dict]) -> bool:
    """True iff `expr` selects a device with the given driver/attributes.
    Raises CelError on unsupported syntax, unknown attributes, or type
    errors (callers treat that as no-match)."""
    return compile_expr(expr).evaluate(driver=driver, attributes=attributes)


def device_matches(expr: str, device: Dict, driver: str) -> bool:
    """Evaluate against a published resourceapi.Device entry; a CEL error
    means the device is not selectable by this expression (the real
    scheduler's observable behavior for runtime errors)."""
    try:
        return evaluate(expr, driver=driver,
                        attributes=device.get("attributes") or {})
    except CelError:
        return False


def compile_many(sources: List[str]) -> Optional[List[Program]]:
    """Compile a selector conjunction; None when ANY source fails to
    compile — a broken selector selects nothing, not everything."""
    progs = []
    for s in sources:
        try:
            progs.append(compile_expr(s))
        except CelError:
            return None
    return progs
