"""NodeSim: the kubelet + containerd analog for one simulated node.

For every pod bound to this node it does what kubelet does, with the real
driver in the loop:

1. waits until every pod claim is allocated,
2. calls NodePrepareResources on the REAL plugin's dra.sock (gRPC) for
   each driver named in the allocation results,
3. resolves the returned CDI device ids against the REAL CDI spec files
   the plugin wrote under this node's CDI root and applies their env
   edits (containerd's CDI injection analog),
4. launches each container's command as a subprocess (image ignored —
   the sim's containers share the host interpreter, the documented
   containerization shim),
5. runs startup/readiness/liveness probes (exec + httpGet) and mirrors
   them into pod conditions,
6. on pod deletion: SIGTERM, NodeUnprepareResources, status cleanup.

Driver DaemonSet pods (the plugins themselves) are launched the same way
from the same manifests the chart renders — they are just pods whose
commands happen to be `python -m tpu_dra...`.
"""

from __future__ import annotations

import base64
import json
import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import urllib.request
from typing import Dict, List, Optional, Tuple

from tpu_dra.k8s.client import ApiClient, ApiError, NotFoundError
from tpu_dra.k8s.resources import PODS, RESOURCECLAIMS

log = logging.getLogger("simcluster.nodesim")

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class _RunningPod:
    def __init__(self, uid: str):
        self.uid = uid
        self.procs: List[subprocess.Popen] = []
        self.claim_refs: List[Tuple[str, str, str]] = []  # (uid, name, ns)
        self.prepared_drivers: List[str] = []
        self.ready = False
        self.next_probe = 0.0
        self.logs_dir = ""
        self.restart_at: Optional[float] = None
        self.links: List[str] = []  # short symlinks for CDI mounts


class NodeSim:
    def __init__(self, client: ApiClient, node_name: str, node_dir: str,
                 *, api_url: str, interval: float = 0.2):
        self._client = client
        self._node = node_name
        self._dir = node_dir          # <node_dir>/hostfs is the node's "/"
        self._api_url = api_url
        self._interval = interval
        self._running: Dict[str, _RunningPod] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    @property
    def hostfs(self) -> str:
        return os.path.join(self._dir, "fs")

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"nodesim-{self._node}")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)
        for rp in self._running.values():
            self._terminate(rp)

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.reconcile_once()
            except Exception:  # noqa: BLE001
                log.exception("nodesim %s reconcile failed", self._node)

    # ------------------------------------------------------------------

    def reconcile_once(self) -> None:
        pods = {p["metadata"]["uid"]: p for p in self._client.list(PODS)
                if p["spec"].get("nodeName") == self._node}
        # Reap pods whose object vanished or is terminating.
        for uid in list(self._running):
            pod = pods.get(uid)
            if pod is None or pod["metadata"].get("deletionTimestamp"):
                self._teardown(self._running.pop(uid), pod)
        for uid, pod in pods.items():
            if pod["metadata"].get("deletionTimestamp"):
                self._finalize_delete(pod)
                continue
            rp = self._running.get(uid)
            if rp is None:
                phase = (pod.get("status") or {}).get("phase", "Pending")
                if phase in ("", "Pending"):
                    self._maybe_start(pod)
            else:
                self._update_running(pod, rp)

    # -- startup --------------------------------------------------------

    def _maybe_start(self, pod: Dict) -> None:
        ns = pod["metadata"].get("namespace", "default")
        uid = pod["metadata"]["uid"]
        claims = self._resolve_claims(pod, ns)
        if claims is None:
            return  # not all allocated yet
        rp = _RunningPod(uid)
        rp.logs_dir = os.path.join(self._dir, "pods", uid, "logs")
        os.makedirs(rp.logs_dir, exist_ok=True)
        # Per-pod-claim CDI edits, applied per CONTAINER below by each
        # container's resources.claims — kubelet/containerd semantics: a
        # container only receives the CDI devices of the claims it
        # references, so two containers sharing a pod can see different
        # subslice env from the same chip (the gpu-test6 shape).
        edits: Dict[str, Tuple[Dict[str, str], List[Tuple[str, str]]]] = {}
        try:
            for entry_name, claim in claims:
                rp.claim_refs.append((claim["metadata"]["uid"],
                                      claim["metadata"]["name"], ns))
                ids = self._prepare_claim(claim, rp)
                env_part, mounts_part = self._cdi_edits(ids)
                linked: List[Tuple[str, str]] = []
                # Short symlinks for mount targets: a rewritten AF_UNIX
                # socket path (coordinator pipe) must stay <= 107 chars.
                for cpath, hpath in mounts_part:
                    link = f"/tmp/simm-{uid[:8]}-{len(rp.links)}"
                    if os.path.islink(link):
                        os.unlink(link)
                    os.symlink(hpath, link)
                    rp.links.append(link)
                    linked.append((cpath, link))
                edits[entry_name] = (env_part, linked)
        except Exception as e:  # noqa: BLE001
            # kubelet semantics: a failed prepare is retried on the next
            # sync, NOT unprepared — prepare is idempotent, and the CD
            # channel path deliberately fails-and-retries until the domain
            # reports Ready (cd device_state.go:456-504).
            log.warning("pod %s/%s prepare failed (will retry): %s", ns,
                        pod["metadata"]["name"], e)
            self._set_status(pod, phase="Pending", ready=False,
                             message=f"prepare failed: {e}")
            return
        try:
            for ctr in pod["spec"].get("containers") or []:
                names = [c.get("name") for c in
                         (ctr.get("resources") or {}).get("claims") or []]
                ctr_env: Dict[str, str] = {}
                ctr_mounts: List[Tuple[str, str]] = []
                for n in names:
                    env_part, mounts_part = edits.get(n, ({}, []))
                    ctr_env.update(env_part)
                    ctr_mounts.extend(mounts_part)
                rp.procs.append(self._launch(pod, ctr, ctr_env, rp,
                                             cdi_mounts=ctr_mounts))
        except Exception as e:  # noqa: BLE001
            log.warning("pod %s/%s launch failed: %s", ns,
                        pod["metadata"]["name"], e)
            self._terminate(rp)
            self._set_status(pod, phase="Failed", ready=False,
                             message=str(e))
            return
        self._running[uid] = rp
        self._set_status(pod, phase="Running", ready=False,
                         pids=self._pids(rp))
        self._publish_endpoints(pod, rp)

    def _publish_endpoints(self, pod: Dict, rp: _RunningPod) -> None:
        """Endpoints-controller analog: annotate Services selecting this
        pod with the pod's actual (port-remapped) endpoint so the sim's
        admission chain can dial registered webhooks."""
        from tpu_dra.k8s.resources import SERVICES
        from tpu_dra.simcluster.admission import ENDPOINT_ANNOTATION

        ns = pod["metadata"].get("namespace", "default")
        labels = pod["metadata"].get("labels") or {}
        try:
            services = self._client.list(SERVICES, namespace=ns)
        except ApiError:
            return
        for svc in services:
            selector = (svc.get("spec") or {}).get("selector") or {}
            if not selector or not all(labels.get(k) == v
                                       for k, v in selector.items()):
                continue
            ports = (svc["spec"].get("ports") or [{}])
            target = str(ports[0].get("targetPort", ports[0].get("port", "")))
            # Scheme and port must come from the SAME container — the one
            # actually serving the target port (a TLS webhook container
            # must not force https onto a sibling's plain-HTTP port).
            serving = None
            for proc in rp.procs:
                ctr = getattr(proc, "_ctr", {}) or {}
                ctr_ports = {str(p.get("containerPort", ""))
                             for p in ctr.get("ports") or []}
                if target in ctr_ports or \
                        target in getattr(proc, "_port_map", {}):
                    serving = proc
                    break
            serving = serving or (rp.procs[0] if rp.procs else None)
            if serving is None:
                continue
            env = getattr(serving, "_env", {}) or {}
            scheme = "https" if env.get("TLS_CERT_FILE") else "http"
            mapped = (getattr(serving, "_port_map", {}) or {}).get(
                target, target)
            endpoint = f"{scheme}://127.0.0.1:{mapped}"
            current = (svc["metadata"].get("annotations") or {}).get(
                ENDPOINT_ANNOTATION)
            if current == endpoint:
                continue  # already published: no RV churn
            try:
                self._client.patch(SERVICES, svc["metadata"]["name"],
                                   {"metadata": {"annotations": {
                                       ENDPOINT_ANNOTATION: endpoint}}},
                                   namespace=ns)
                log.info("service %s/%s -> %s", ns,
                         svc["metadata"]["name"], endpoint)
            except ApiError:
                pass

    def _resolve_claims(self, pod: Dict,
                        ns: str) -> Optional[List[Tuple[str, Dict]]]:
        """(pod-claim-entry name, claim) pairs — the entry name is what a
        container's resources.claims references."""
        statuses = {s["name"]: s["resourceClaimName"] for s in
                    ((pod.get("status") or {})
                     .get("resourceClaimStatuses") or [])}
        claims = []
        for entry in (pod["spec"].get("resourceClaims") or []):
            name = entry.get("resourceClaimName") or statuses.get(
                entry["name"])
            if not name:
                return None
            try:
                claim = self._client.get(RESOURCECLAIMS, name, ns)
            except NotFoundError:
                return None
            if not (claim.get("status") or {}).get("allocation"):
                return None
            claims.append((entry["name"], claim))
        return claims

    def _prepare_claim(self, claim: Dict, rp: _RunningPod) -> List[str]:
        """kubelet's NodePrepareResources over the plugin's unix socket."""
        from tpu_dra.kubeletplugin.gen import dra_v1_pb2 as dra
        from tpu_dra.kubeletplugin.server import kubelet_stubs

        alloc = claim["status"]["allocation"]
        drivers = sorted({r.get("driver", "") for r in
                          (alloc.get("devices") or {}).get("results") or []})
        cdi_ids: List[str] = []
        for driver in drivers:
            sock = os.path.join(self.hostfs, "var", "lib", "kubelet",
                                "plugins", driver, "dra.sock")
            if not os.path.exists(sock):
                raise RuntimeError(f"plugin socket missing: {sock}")
            channel, prepare, _ = kubelet_stubs(sock)
            try:
                req = dra.NodePrepareResourcesRequest()
                c = req.claims.add()
                c.uid = claim["metadata"]["uid"]
                c.name = claim["metadata"]["name"]
                c.namespace = claim["metadata"].get("namespace", "default")
                resp = prepare(req, timeout=60)
                result = resp.claims[c.uid]
                if result.error:
                    raise RuntimeError(
                        f"{driver} prepare: {result.error}")
                for dev in result.devices:
                    cdi_ids.extend(dev.cdi_device_ids)
                rp.prepared_drivers.append(driver)
            finally:
                channel.close()
        return cdi_ids

    def _cdi_edits(self, cdi_ids: List[str]
                   ) -> Tuple[Dict[str, str], List[Tuple[str, str]]]:
        """containerd's CDI resolution analog: map fully-qualified device
        ids to (env, mounts) edits from the spec files under this node's
        CDI root. Mounts come back as (containerPath, hostPath) pairs for
        the env-rewrite map — the sim cannot bind-mount, so paths that
        reference a mount are rewritten to the host location instead."""
        cdi_root = os.path.join(self.hostfs, "var", "run", "cdi")
        specs = []
        if os.path.isdir(cdi_root):
            for fn in sorted(os.listdir(cdi_root)):
                if fn.endswith(".json"):
                    with open(os.path.join(cdi_root, fn)) as f:
                        specs.append(json.load(f))
        env: Dict[str, str] = {}
        mounts: List[Tuple[str, str]] = []

        def apply(edits: Dict) -> None:
            for kv in (edits or {}).get("env") or []:
                k, _, v = kv.partition("=")
                env[k] = v
            for m in (edits or {}).get("mounts") or []:
                if m.get("containerPath") and m.get("hostPath"):
                    mounts.append((m["containerPath"], m["hostPath"]))

        for cdi_id in cdi_ids:
            kind, _, name = cdi_id.partition("=")
            for spec in specs:
                if spec.get("kind") != kind:
                    continue
                for dev in spec.get("devices") or []:
                    if dev.get("name") == name:
                        apply(spec.get("containerEdits") or {})
                        apply(dev.get("containerEdits") or {})
        return env, mounts

    # -- container launch ----------------------------------------------

    def _launch(self, pod: Dict, ctr: Dict, cdi_env: Dict[str, str],
                rp: _RunningPod,
                cdi_mounts: Optional[List[Tuple[str, str]]] = None
                ) -> subprocess.Popen:
        ns = pod["metadata"].get("namespace", "default")
        mounts = self._mount_map(pod, ctr, rp)
        mounts.extend(cdi_mounts or [])
        mounts.sort(key=lambda kv: -len(kv[0]))
        env = dict(os.environ)
        env.update({
            "PYTHONPATH": REPO,
            "KUBE_API_URL": self._api_url,   # in-cluster config analog
            "TPUINFO_SYSFS_ROOT": self.hostfs,
            "TPU_DRA_TPUINFO_BACKEND": "native",
            "PATH": os.pathsep.join([
                os.path.join(REPO, "native", "build"),
                env.get("PATH", "")]),
        })
        # The containerization shim: paths that are pod-local in a real
        # cluster must be disambiguated per pod/node here.
        env.setdefault("WORK_DIR",
                       os.path.join(self._dir, "pods", rp.uid, "work"))
        env.setdefault("HOSTS_FILE", os.path.join(self._dir, "hosts"))
        env.setdefault("SLICE_DAEMON_PORT", str(free_port()))
        env.setdefault("SLICE_DAEMON_BINARY",
                       os.path.join(REPO, "native", "build",
                                    "tpu-slice-daemon"))
        manifest_keys = set()
        for e in ctr.get("env") or []:
            value = e.get("value")
            if value is None and "valueFrom" in e:
                value = self._field_ref(pod, e["valueFrom"])
            if value is None:
                continue
            env[e["name"]] = self._rewrite_path(str(value), mounts)
            manifest_keys.add(e["name"])
        for k, v in cdi_env.items():
            env[k] = self._rewrite_path(v, mounts)
        # Sim containers share one network namespace (the host), so fixed
        # listen ports from the manifest must be remapped per pod; probes
        # consult the same map. JAX workloads run on the CPU backend unless
        # the manifest says otherwise — N concurrent sim pods cannot share
        # one real TPU's libtpu lock, and the launching shell's
        # JAX_PLATFORMS must not leak into "containers".
        if "JAX_PLATFORMS" not in manifest_keys:
            env["JAX_PLATFORMS"] = "cpu"
        port_map: Dict[str, str] = {}
        for key in ("HEALTHCHECK_PORT", "WEBHOOK_PORT",
                    "HTTP_ENDPOINT_PORT"):
            if env.get(key, "0") not in ("", "0"):
                port_map[env[key]] = str(free_port())
                env[key] = port_map[env[key]]
        cmd = [self._rewrite_path(c, mounts) for c in
               list(ctr.get("command") or []) + list(ctr.get("args") or [])]
        if not cmd:
            raise RuntimeError(
                f"container {ctr['name']} has no command (images are not "
                "runnable in the sim)")
        if cmd[0] == "python":
            cmd[0] = sys.executable
        out = open(os.path.join(rp.logs_dir, f"{ctr['name']}.log"), "ab")
        proc = subprocess.Popen(
            cmd, env=env, stdout=out, stderr=subprocess.STDOUT,
            cwd=os.path.join(self._dir, "pods", rp.uid))
        proc._ctr = ctr          # type: ignore[attr-defined]
        proc._logfile = out      # type: ignore[attr-defined]
        proc._env = env          # type: ignore[attr-defined]
        proc._port_map = port_map  # type: ignore[attr-defined]
        proc._mounts = mounts      # type: ignore[attr-defined]
        log.info("node %s: started %s/%s:%s (pid %d)", self._node, ns,
                 pod["metadata"]["name"], ctr["name"], proc.pid)
        return proc

    def _mount_map(self, pod: Dict, ctr: Dict,
                   rp: _RunningPod) -> List[Tuple[str, str]]:
        """containerPath -> hostPath mappings for env rewriting. hostPath
        volumes land under the node's hostfs; secret volumes are
        materialized from the Secret object."""
        vols = {v["name"]: v for v in pod["spec"].get("volumes") or []}
        out: List[Tuple[str, str]] = []
        for vm in ctr.get("volumeMounts") or []:
            vol = vols.get(vm["name"])
            if vol is None:
                continue
            if "hostPath" in vol:
                path = vol["hostPath"]["path"]
                # Objects created by components that already run inside the
                # sim (e.g. the plugin's coordinator Deployment) carry
                # hostPaths that are ALREADY sim-host-absolute; only
                # genuine in-cluster paths get the hostfs prefix.
                host = (path if os.path.exists(path) else
                        os.path.join(self.hostfs, path.lstrip("/")))
                os.makedirs(host, exist_ok=True)
                out.append((vm["mountPath"], host))
            elif "secret" in vol:
                host = os.path.join(self._dir, "pods", rp.uid, "secrets",
                                    vm["name"])
                os.makedirs(host, exist_ok=True)
                try:
                    sec = self._client.get(
                        self._secret_gvr(), vol["secret"]["secretName"],
                        pod["metadata"].get("namespace", "default"))
                    for k, v in (sec.get("data") or {}).items():
                        with open(os.path.join(host, k), "wb") as f:
                            f.write(base64.b64decode(v))
                except (NotFoundError, ApiError):
                    pass
                out.append((vm["mountPath"], host))
        # Longest prefix first so nested mounts resolve correctly.
        out.sort(key=lambda kv: -len(kv[0]))
        return out

    @staticmethod
    def _secret_gvr():
        from tpu_dra.simcluster.gvk import gvr_for_kind
        return gvr_for_kind("Secret")

    @staticmethod
    def _rewrite_path(value: str, mounts: List[Tuple[str, str]]) -> str:
        for cpath, hpath in mounts:
            if value == cpath or value.startswith(cpath.rstrip("/") + "/"):
                return hpath + value[len(cpath.rstrip("/")):]
        return value

    def _field_ref(self, pod: Dict, value_from: Dict) -> Optional[str]:
        path = (value_from.get("fieldRef") or {}).get("fieldPath", "")
        return {
            "metadata.name": pod["metadata"]["name"],
            "metadata.namespace": pod["metadata"].get("namespace",
                                                      "default"),
            "metadata.uid": pod["metadata"].get("uid", ""),
            "spec.nodeName": self._node,
            "spec.serviceAccountName":
                pod["spec"].get("serviceAccountName", "default"),
            "status.podIP": "127.0.0.1",
        }.get(path)

    # -- running-pod upkeep ---------------------------------------------

    def _update_running(self, pod: Dict, rp: _RunningPod) -> None:
        rcs = [p.poll() for p in rp.procs]
        if all(rc is not None for rc in rcs):
            restart = pod["spec"].get("restartPolicy", "Always")
            failed = any(rc != 0 for rc in rcs)
            if restart == "Always" or (restart == "OnFailure" and failed):
                if rp.restart_at is None:
                    rp.restart_at = time.monotonic() + 1.0
                if time.monotonic() >= rp.restart_at:
                    rp.restart_at = None
                    for i, p in enumerate(rp.procs):
                        np_ = subprocess.Popen(
                            p.args, env=p._env,  # type: ignore
                            stdout=p._logfile,   # type: ignore
                            stderr=subprocess.STDOUT)
                        # Carry ALL sim bookkeeping across the restart —
                        # losing _port_map/_mounts would break probe-port
                        # resolution and endpoint publishing afterwards.
                        for attr in ("_ctr", "_logfile", "_env",
                                     "_port_map", "_mounts"):
                            setattr(np_, attr, getattr(p, attr, None))
                        rp.procs[i] = np_
                return
            del self._running[rp.uid]
            self._unprepare_all(rp)
            self._set_status(pod, phase="Failed" if failed else "Succeeded",
                             ready=False)
            return
        if time.monotonic() >= rp.next_probe:
            rp.next_probe = time.monotonic() + 2.0
            ready = all(self._probe_ok(p) for p in rp.procs)
            if ready != rp.ready:
                rp.ready = ready
                self._set_status(pod, phase="Running", ready=ready,
                                 pids=self._pids(rp))
            # Re-publish endpoints each probe tick: a Service created
            # after its backing pod started must still get annotated.
            self._publish_endpoints(pod, rp)

    @staticmethod
    def _pids(rp: _RunningPod) -> Dict[str, int]:
        return {p._ctr["name"]: p.pid  # type: ignore[attr-defined]
                for p in rp.procs if p.poll() is None}

    def _probe_ok(self, proc: subprocess.Popen) -> bool:
        ctr = proc._ctr  # type: ignore[attr-defined]
        probe = (ctr.get("startupProbe") or ctr.get("readinessProbe")
                 or ctr.get("livenessProbe"))
        if probe is None:
            return True
        if "exec" in probe:
            mounts = getattr(proc, "_mounts", [])
            cmd = [self._rewrite_path(c, mounts)
                   for c in probe["exec"].get("command") or []]
            if cmd and cmd[0] == "python":
                cmd[0] = sys.executable
            try:
                return subprocess.run(
                    cmd, env=proc._env,  # type: ignore[attr-defined]
                    capture_output=True, timeout=10).returncode == 0
            except Exception:  # noqa: BLE001 # drflow: swallow-ok[probe failure IS the signal: returns not-ready]
                return False
        if "httpGet" in probe:
            hg = probe["httpGet"]
            port_map = getattr(proc, "_port_map", {})
            port = port_map.get(str(hg.get("port")), str(hg.get("port")))
            url = (f"{'https' if hg.get('scheme') == 'HTTPS' else 'http'}"
                   f"://127.0.0.1:{port}{hg.get('path', '/')}")
            try:
                import ssl
                ctx = ssl._create_unverified_context() \
                    if hg.get("scheme") == "HTTPS" else None
                urllib.request.urlopen(url, timeout=5, context=ctx)
                return True
            except Exception:  # noqa: BLE001 # drflow: swallow-ok[probe failure IS the signal: returns not-ready]
                return False
        return True

    # -- teardown -------------------------------------------------------

    def _terminate(self, rp: _RunningPod) -> None:
        for p in rp.procs:
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + 10
        for p in rp.procs:
            try:
                p.wait(timeout=max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                p.kill()

    def _teardown(self, rp: _RunningPod, pod: Optional[Dict]) -> None:
        self._terminate(rp)
        self._unprepare_all(rp)
        if pod is not None:
            self._finalize_delete(pod)

    def _finalize_delete(self, pod: Dict) -> None:
        # FakeCluster deletes synchronously (no kubelet grace period);
        # nothing to strip. Kept as a seam for finalizer support.
        return

    def _unprepare_all(self, rp: _RunningPod) -> None:
        for driver in rp.prepared_drivers:
            self._unprepare(rp, driver)
        rp.prepared_drivers = []
        for link in rp.links:
            try:
                os.unlink(link)
            except OSError:
                pass
        rp.links = []

    def _unprepare(self, rp: _RunningPod, driver: str) -> None:
        from tpu_dra.kubeletplugin.gen import dra_v1_pb2 as dra
        from tpu_dra.kubeletplugin.server import kubelet_stubs

        sock = os.path.join(self.hostfs, "var", "lib", "kubelet",
                            "plugins", driver, "dra.sock")
        if not os.path.exists(sock):
            return
        channel, _, unprepare = kubelet_stubs(sock)
        try:
            req = dra.NodeUnprepareResourcesRequest()
            for uid, name, ns in rp.claim_refs:
                c = req.claims.add()
                c.uid, c.name, c.namespace = uid, name, ns
            unprepare(req, timeout=30)
        except Exception as e:  # noqa: BLE001
            log.warning("unprepare via %s failed: %s", driver, e)
        finally:
            channel.close()

    def _set_status(self, pod: Dict, *, phase: str, ready: bool,
                    message: str = "",
                    pids: Optional[Dict[str, int]] = None) -> None:
        ns = pod["metadata"].get("namespace", "default")
        try:
            fresh = self._client.get(PODS, pod["metadata"]["name"], ns)
        except NotFoundError:
            return
        status = fresh.setdefault("status", {})
        status["phase"] = phase
        status["podIP"] = "127.0.0.1"
        status["conditions"] = [{
            "type": "Ready",
            "status": "True" if ready else "False",
            **({"message": message} if message else {}),
        }]
        # containerID carries the sim process pid (`sim://<pid>`) — the
        # containerd://<hash> analog. The e2e debug suite resolves it to
        # deliver signals the way `kubectl exec kill` would on a real
        # cluster (tests/e2e/test_debug.sh; reference
        # tests/bats/test_basics.bats:89-100).
        status["containerStatuses"] = [
            {"name": c["name"], "ready": ready,
             "state": {"running": {}} if phase == "Running" else {},
             **({"containerID": f"sim://{pids[c['name']]}"}
                if pids and c["name"] in pids else {})}
            for c in fresh["spec"].get("containers") or []]
        try:
            self._client.update_status(PODS, fresh, ns)
        except ApiError:
            pass  # conflict: next tick rewrites
