"""Admission chain for the simcluster: calls registered validating
webhooks the way the real apiserver does.

On create/update of a matching resource, builds an AdmissionReview, POSTs
it to the webhook Service's endpoint, and denies the request if the
response says so — honoring per-webhook failurePolicy (our chart ships
Ignore, so installs don't deadlock before the webhook pod is up).

Endpoint resolution: NodeSim acts as the endpoints controller — when a
pod backing a Service starts, it annotates the Service with
`sim/endpoint` (scheme + the pod's REMAPPED port). TLS uses the chart's
render-time self-signed cert; the caller pins the caBundle from the
webhook configuration when present, exactly like the apiserver.
"""

from __future__ import annotations

import base64
import json
import logging
import ssl
import tempfile
import urllib.request
from typing import Dict, Optional

from tpu_dra.k8s.client import GVR
from tpu_dra.k8s.fake import FakeCluster
from tpu_dra.k8s.resources import SERVICES, VALIDATINGWEBHOOKCONFIGURATIONS

log = logging.getLogger("simcluster.admission")

ENDPOINT_ANNOTATION = "sim/endpoint"


class WebhookCaller:
    """admission_hook callable for FakeApiServer."""

    def __init__(self, cluster: FakeCluster, timeout: float = 5.0):
        self._cluster = cluster
        self._timeout = timeout

    def __call__(self, gvr: GVR, obj: Dict,
                 operation: str) -> Optional[str]:
        for vwc in self._cluster.list(VALIDATINGWEBHOOKCONFIGURATIONS):
            for wh in vwc.get("webhooks") or []:
                if not self._rules_match(wh.get("rules") or [], gvr,
                                         operation):
                    continue
                outcome = self._call_webhook(wh, gvr, obj, operation)
                if outcome is None:
                    continue
                kind_, msg = outcome
                name = wh.get("name", "webhook")
                # Real apiserver message formats, so clients (and the e2e
                # suite) see identical text against kind or sim — and an
                # infra failure is NOT misreported as a policy denial.
                if kind_ == "deny":
                    return (f'admission webhook "{name}" denied the '
                            f'request: {msg}')
                return f'failed calling webhook "{name}": {msg}'
        return None

    @staticmethod
    def _rules_match(rules, gvr: GVR, operation: str) -> bool:
        for rule in rules:
            groups = rule.get("apiGroups") or []
            resources = rule.get("resources") or []
            ops = rule.get("operations") or []
            if (gvr.group in groups or "*" in groups) \
                    and (gvr.plural in resources or "*" in resources) \
                    and (operation in ops or "*" in ops):
                return True
        return False

    def _call_webhook(self, wh: Dict, gvr: GVR, obj: Dict,
                      operation: str):
        """Returns None (allowed), ('deny', msg) for a policy denial, or
        ('error', msg) for an infra failure under failurePolicy Fail."""
        fail_policy = wh.get("failurePolicy", "Fail")
        cc = wh.get("clientConfig") or {}
        endpoint = self._resolve_endpoint(cc)
        if endpoint is None:
            if fail_policy == "Ignore":
                return None
            return ("error", "webhook endpoint unavailable")
        # The reviewed version is the version the CLIENT submitted (the
        # real API server admits at request version, not storage
        # version): a v1beta1-shaped claim must reach the webhook as
        # v1beta1 so its conversion path runs (webhook resource.go:83-160
        # analog).
        obj_api = obj.get("apiVersion", "")
        version = obj_api.split("/", 1)[1] if "/" in obj_api \
            else gvr.version
        review = {
            "apiVersion": "admission.k8s.io/v1", "kind": "AdmissionReview",
            "request": {
                "uid": obj.get("metadata", {}).get("uid", "sim-admission"),
                "resource": {"group": gvr.group, "version": version,
                             "resource": gvr.plural},
                "kind": {"kind": obj.get("kind", "")},
                "operation": operation,
                "object": obj,
            },
        }
        # URL-based configs carry their own path; only service-based ones
        # append clientConfig.service.path to the resolved endpoint.
        if cc.get("url"):
            url = endpoint
        else:
            url = endpoint + (cc.get("service") or {}).get("path", "/")
        try:
            ctx = self._tls_context(cc)
            req = urllib.request.Request(
                url, json.dumps(review).encode(),
                {"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=self._timeout,
                                        context=ctx) as resp:
                out = json.loads(resp.read())
        except Exception as e:  # noqa: BLE001 — network/TLS failure
            log.warning("webhook call %s failed: %s", url, e)
            if fail_policy == "Ignore":
                return None
            return ("error", str(e))
        response = out.get("response") or {}
        if response.get("allowed"):
            return None
        return ("deny",
                (response.get("status") or {}).get("message", "denied"))

    def _resolve_endpoint(self, client_config: Dict) -> Optional[str]:
        if client_config.get("url"):
            return client_config["url"]  # full URL, path included
        svc = client_config.get("service") or {}
        try:
            service = self._cluster.get(SERVICES, svc.get("name", ""),
                                        svc.get("namespace"))
        except Exception as e:  # noqa: BLE001
            # An unreachable webhook silently skipped is a policy hole:
            # the failurePolicy decides the outcome, but the lookup
            # failure itself must be visible.
            log.warning("webhook service %s/%s lookup failed: %s",
                        svc.get("namespace"), svc.get("name"), e)
            return None
        return (service["metadata"].get("annotations") or {}).get(
            ENDPOINT_ANNOTATION)

    @staticmethod
    def _tls_context(client_config: Dict) -> ssl.SSLContext:
        ca = client_config.get("caBundle")
        if ca:
            # Pin the configured CA exactly like the apiserver; hostname
            # verification is off because the sim dials 127.0.0.1, not the
            # service DNS name the cert carries.
            ctx = ssl.create_default_context()
            ctx.check_hostname = False
            with tempfile.NamedTemporaryFile("w", suffix=".pem") as f:
                f.write(base64.b64decode(ca).decode())
                f.flush()
                ctx.load_verify_locations(f.name)
            return ctx
        ctx = ssl._create_unverified_context()  # noqa: S323
        return ctx
