"""Kind <-> GVR mapping for manifest handling (apply/get by kind name).

The fake API server stores objects by GVR; manifests and kubectl speak
kinds. One table serves the installer, the shim, and the sims.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

from tpu_dra.k8s.client import GVR

# kind -> (group, plural, namespaced)
_KINDS: Dict[str, Tuple[str, str, bool]] = {
    "Namespace": ("", "namespaces", False),
    "Node": ("", "nodes", False),
    "Pod": ("", "pods", True),
    "Secret": ("", "secrets", True),
    "Service": ("", "services", True),
    "ServiceAccount": ("", "serviceaccounts", True),
    "Event": ("", "events", True),
    "DaemonSet": ("apps", "daemonsets", True),
    "Deployment": ("apps", "deployments", True),
    "ResourceClaim": ("resource.k8s.io", "resourceclaims", True),
    "ResourceClaimTemplate": ("resource.k8s.io", "resourceclaimtemplates",
                              True),
    "ResourceSlice": ("resource.k8s.io", "resourceslices", False),
    "DeviceClass": ("resource.k8s.io", "deviceclasses", False),
    "ComputeDomain": ("resource.tpu.dev", "computedomains", True),
    "CustomResourceDefinition": ("apiextensions.k8s.io",
                                 "customresourcedefinitions", False),
    "ClusterRole": ("rbac.authorization.k8s.io", "clusterroles", False),
    "ClusterRoleBinding": ("rbac.authorization.k8s.io",
                           "clusterrolebindings", False),
    "NetworkPolicy": ("networking.k8s.io", "networkpolicies", True),
    "ValidatingWebhookConfiguration": (
        "admissionregistration.k8s.io", "validatingwebhookconfigurations",
        False),
    "ValidatingAdmissionPolicy": (
        "admissionregistration.k8s.io", "validatingadmissionpolicies",
        False),
    "ValidatingAdmissionPolicyBinding": (
        "admissionregistration.k8s.io",
        "validatingadmissionpolicybindings", False),
}

# kubectl-style aliases (lowercase) -> kind
ALIASES: Dict[str, str] = {}
for kind, (_, plural, _ns) in _KINDS.items():
    ALIASES[kind.lower()] = kind
    ALIASES[plural] = kind
    ALIASES[plural.rstrip("s")] = kind
ALIASES.update({
    "po": "Pod", "ds": "DaemonSet", "deploy": "Deployment",
    "ns": "Namespace", "no": "Node", "svc": "Service", "sa": "ServiceAccount",
    "cd": "ComputeDomain", "crd": "CustomResourceDefinition",
    "rc": "ResourceClaim", "rct": "ResourceClaimTemplate",
    "rs": "ResourceSlice", "dc": "DeviceClass",
})


def gvr_for_kind(kind: str) -> GVR:
    if kind not in _KINDS:
        raise KeyError(f"unknown kind {kind!r}")
    group, plural, namespaced = _KINDS[kind]
    # Version is irrelevant to the fake store (it keys on group/plural);
    # use the version the repo's resources.py declares where it matters.
    version = {"resource.tpu.dev": "v1beta1",
               "resource.k8s.io": "v1"}.get(group, "v1")
    return GVR(group, version, plural, namespaced=namespaced)


def gvr_for_doc(doc: Dict) -> GVR:
    return gvr_for_kind(doc.get("kind", ""))


def resolve_kind(name: str) -> Optional[str]:
    return ALIASES.get(name.lower())
