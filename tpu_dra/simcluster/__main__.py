from tpu_dra.simcluster.cluster import main

if __name__ == "__main__":
    raise SystemExit(main())
