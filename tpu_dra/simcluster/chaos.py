"""Chaos convergence harness: seeded randomized fault schedules against
full claim lifecycles, with invariants asserted after quiesce.

The production stack under test is real — ``TpuDriver`` + ``DeviceState``
+ ``CheckpointManager`` + ``CDIHandler`` over a ``RetryingApiClient``-
wrapped ``FakeCluster`` — only the kubelet gRPC hop is skipped (covered
by tests/test_e2e_prepare.py; this tier turns the crank thousands of
times and the wire adds nothing to the failure model). Faults enter
through the ``tpu_dra.infra.faults`` sites the production code itself
consults: API request errors, watch drops, CDI write failures,
checkpoint store failures and torn slots, plugin crashes (rebuild from
disk), and chip health events.

Each schedule is a seeded random walk over lifecycle operations
(prepare, retry, unprepare, crash-restart, health event, re-arm faults).
After the walk, faults are disarmed (quiesce) and the harness drives
every in-flight claim to its terminal state, then asserts the
invariants the ISSUE names:

1. every claim converged — prepared-and-ready or cleanly unallocated;
2. no orphaned CDI spec files (specs on disk == completed claims);
3. no leaked checkpoint entries (checkpoint == completed claims);
4. the published ResourceSlice matches the healthy-chip device set;
5. a final crash-restart recovers the same state (crash consistency);
6. full teardown leaves zero residue.

``python -m tpu_dra.simcluster.chaos --seeds 25`` runs the fixed seed
matrix (hack/chaos.sh); violations exit non-zero.
"""

from __future__ import annotations

import json
import logging
import os
import random
import shutil
import statistics
import tempfile
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from tpu_dra.api.types import API_VERSION, TPU_DRIVER_NAME
from tpu_dra.cdi.handler import CDIHandler
from tpu_dra.infra import featuregates, lockwitness
from tpu_dra.infra import trace
from tpu_dra.infra.faults import (
    FAULTS, EveryNth, OneShot, Probabilistic, Schedule,
)
from tpu_dra.k8s import (
    FakeCluster, PODS, RESOURCECLAIMS, RESOURCESLICES, RetryingApiClient,
)
from tpu_dra.k8s import informer as informer_mod
from tpu_dra.k8s.informer import Informer
from tpu_dra.kubeletplugin.server import Claim, PrepareResult
from tpu_dra.native.tpuinfo import FakeBackend, HealthEvent, default_fake_chips
from tpu_dra.tpuplugin.checkpoint import PREPARE_COMPLETED, CheckpointManager
from tpu_dra.tpuplugin.device_state import DeviceState
from tpu_dra.tpuplugin.driver import TpuDriver
from tpu_dra.tpuplugin.health import RECOVERED_KIND
from tpu_dra.tpuplugin.sharing import TimeSlicingManager

log = logging.getLogger("simcluster.chaos")

# Sites the random walk may arm. health.chip_event is injected directly
# (driver callback) for determinism; cddaemon.spawn belongs to the CD
# daemon stack, exercised by its own tests. The prepare.batch_* sites
# fire inside the batched prepare pipeline (driver fetch fan-out and
# DeviceState parallel apply), so the group-commit rollback machinery is
# chaos-tested on the exact production path; the prepare.journal_* sites
# break the append-only journal's append and bounded-lag compaction the
# same way (SURVEY §14). prepare.rpc_admit refuses RPCs at the async
# front-end's admission seam before any window slot or ordering gate
# exists (SURVEY §21): the walk must see a clean per-claim failure and
# retry, never a leaked gate wedging a successor RPC. health.flap
# breaks the quarantine ladder's graduation persistence (SURVEY §18):
# the chip must degrade to transient-unhealthy and re-graduate, never
# half-quarantine.
CHAOS_SITES = ("k8s.api.request", "cdi.claim_write", "checkpoint.store",
               "checkpoint.corrupt", "prepare.rpc_admit",
               "prepare.batch_fetch",
               "prepare.batch_apply", "prepare.journal_append",
               "prepare.journal_compact", "health.flap", "trace.emit")

TS_CONFIG = [{"source": "FromClaim", "requests": [], "opaque": {
    "driver": TPU_DRIVER_NAME, "parameters": {
        "apiVersion": API_VERSION, "kind": "TpuConfig",
        "sharing": {"strategy": "TimeSlicing",
                    "timeSlicingConfig": {"interval": "Short"}}}}}]

# Lock-hold outlier threshold for the witness invariant: generous
# against CI scheduling jitter, tight enough that real blocking work
# (a subprocess spawn, an API retry loop) under a data lock trips it.
LOCK_HOLD_OUTLIER_S = 5.0


@dataclass
class ChaosReport:
    seed: int
    events: int = 0
    prepares: int = 0
    unprepares: int = 0
    batches: int = 0                  # multi-claim prepare RPCs driven
    crashes: int = 0
    health_events: int = 0
    failed_attempts: int = 0          # operations a fault made fail
    injected: Dict[str, int] = field(default_factory=dict)
    violations: List[str] = field(default_factory=list)

    @property
    def ok(self) -> bool:
        return not self.violations

    def to_dict(self) -> Dict:
        return {"seed": self.seed, "events": self.events,
                "prepares": self.prepares, "unprepares": self.unprepares,
                "batches": self.batches,
                "crashes": self.crashes, "health_events": self.health_events,
                "failed_attempts": self.failed_attempts,
                "injected": dict(self.injected),
                "violations": list(self.violations)}


def _corrupt_one_slot(rng: random.Random):
    """Armed action for checkpoint.corrupt: tear ONE of the slots the
    store just wrote (a real torn write hits the slot in flight)."""
    def action(paths=()):
        if not paths:
            return
        path = rng.choice(list(paths))
        try:
            with open(path, "r+b") as f:
                f.seek(0)
                f.write(b'{"torn":')  # valid JSON prefix, broken envelope
        except OSError:
            pass
    return action


class ChaosHarness:
    """One seeded schedule: a real node-driver stack + the random walk."""

    MAX_QUIESCE_RETRIES = 30

    # Class-level defaults so close() is safe on a partially built
    # harness (an __init__ failure still releases the witness/gates/tmp).
    driver: Optional[TpuDriver] = None
    state: Optional[DeviceState] = None
    cdi: Optional[CDIHandler] = None
    tmp = ""
    _witnessed = False

    def __init__(self, seed: int, *, chips: int = 4,
                 generation: str = "v5p"):
        # Witness BEFORE any stack lock exists: every Lock/RLock the
        # driver stack creates below joins the acquisition-order graph,
        # and quiesce asserts it stayed acyclic (dralint's dynamic half).
        lockwitness.install()
        self._witnessed = True
        # Under a session-level install (TPU_DRA_LOCK_WITNESS=1) the
        # graph predates this harness: report only THIS walk's window.
        self._witness_snap = lockwitness.WITNESS.snapshot()
        # Open-span snapshot (SURVEY §19): quiesce asserts every span
        # THIS walk began was closed — a leaked sibling-test span must
        # not fail this harness, hence the window.
        self._trace_snap = trace.TRACER.open_ids()
        self.seed = seed
        self.rng = random.Random(seed)
        self.report = ChaosReport(seed=seed)
        # Gates for the whole harness lifetime: time-slicing configs are
        # part of the random claim mix; the health monitor THREAD is off
        # because the walk injects events synchronously at the driver
        # callback (deterministic, and no 0.5s monitor join per crash —
        # the monitor's own pipeline has dedicated tests).
        self._gates = featuregates.Features.overrides_snapshot()
        try:
            featuregates.Features.set_from_string(
                "TimeSlicingSettings=true,TPUDeviceHealthCheck=false")
            self.tmp = tempfile.mkdtemp(prefix=f"tpu-dra-chaos-{seed}-")
            self.cluster = FakeCluster()
            # Fast backoff: chaos turns the crank; wall-clock realism is
            # the schedule's job, not the sleep's.
            self.client = RetryingApiClient(
                self.cluster, max_attempts=4, base_delay=0.001,
                max_delay=0.01, rng=random.Random(seed ^ 0x5EED))
            self.backend = FakeBackend(
                default_fake_chips(chips, generation, slice_id="chaos"))
            self.n_chips = chips
            # uid -> claim object, by expected terminal state
            self.prepared: Dict[str, Dict] = {}  # last prepare succeeded
            self.pending: Dict[str, Dict] = {}   # attempted, not yet ready
            self._build_stack()
        except BaseException:
            # Partial init: close() tolerates missing stack pieces (class
            # defaults) and always releases gates/tmp/witness.
            self.close()
            raise

    # -- stack lifecycle ----------------------------------------------------

    def _build_stack(self) -> None:
        self.cdi = CDIHandler(os.path.join(self.tmp, "cdi"),
                              driver_root=os.path.join(self.tmp, "drv"))
        self.state = DeviceState(
            backend=self.backend, cdi=self.cdi,
            checkpoints=CheckpointManager(os.path.join(self.tmp, "plugin")),
            driver_name=TPU_DRIVER_NAME, node_name="chaos-node",
            ts_manager=TimeSlicingManager(self.backend),
            # The ladder engages under the walk's flap storms (window
            # far past any schedule's wall clock; threshold low enough
            # that _op_flap_storm deterministically graduates).
            quarantine_threshold=3, quarantine_window_s=300.0)
        self.driver = TpuDriver(
            state=self.state, client=self.client,
            driver_name=TPU_DRIVER_NAME, node_name="chaos-node",
            plugin_dir=os.path.join(self.tmp, "plugin"),
            registry_dir=os.path.join(self.tmp, "reg"))
        # publish_wait=0: under an armed API fault the initial publish
        # retries in the background; the walk must not block on it.
        self.driver.start(publish_wait=0)

    def _teardown_stack(self) -> None:
        """SIGKILL analog: stop threads/sockets and release fds, but do
        NOT unprepare or write any terminal state — recovery must come
        from what is on disk."""
        if self.driver is not None:
            self.driver.shutdown()
            self.driver = None
            self.state = None

    def crash_restart(self, max_attempts: int = 25) -> None:
        """Crash the plugin and bring it back up. Startup itself can hit
        armed faults (checkpoint load/store, CDI write) — a crash-looping
        pod retries until the fault clears, so does this. A schedule that
        fires on EVERY attempt (a hard outage) would crash-loop forever;
        after max_attempts the outage is declared over (faults disarmed,
        harvesting their counts) and the plugin comes up — what an
        operator fixing the node achieves."""
        self._teardown_stack()
        self.report.crashes += 1
        for _ in range(max_attempts):
            try:
                self._build_stack()
                return
            except Exception:  # noqa: BLE001 # drflow: swallow-ok[crash-looping restart under armed faults is the modeled outcome; report.crashes counts it]
                time.sleep(0.002)
        self._harvest_faults()
        FAULTS.reset()
        self._build_stack()

    def close(self) -> None:
        # Nested finally: a teardown failure must not skip the gate
        # restore, the tmpdir removal, or the witness uninstall.
        try:
            self._teardown_stack()
        finally:
            try:
                featuregates.Features.restore_overrides(self._gates)
            finally:
                if self.tmp:
                    shutil.rmtree(self.tmp, ignore_errors=True)
                if self._witnessed:
                    self._witnessed = False
                    lockwitness.uninstall()

    # -- claim plumbing -----------------------------------------------------

    def _used_chips(self) -> set:
        used = set()
        for obj in list(self.prepared.values()) + list(self.pending.values()):
            used.update(obj["_chaos_chips"])
        return used

    def make_claim(self, chip_indices: List[int],
                   devices: Optional[List[str]] = None,
                   configs: Optional[List[Dict]] = None) -> Dict:
        devices = devices or [f"chip-{i}" for i in chip_indices]
        obj = self.cluster.create(RESOURCECLAIMS, {
            "apiVersion": "resource.k8s.io/v1", "kind": "ResourceClaim",
            "metadata": {"name": f"chaos-{self.seed}-"
                                 f"{self.rng.randrange(16**8):08x}",
                         "namespace": "default"},
            "spec": {"devices": {"requests": [{"name": "tpu"}]}},
            "status": {"allocation": {"devices": {"results": [
                {"request": "tpu", "driver": TPU_DRIVER_NAME,
                 "pool": "chaos-node", "device": d} for d in devices],
                "config": configs or []}}},
        })
        obj["_chaos_chips"] = set(chip_indices)
        return obj

    def attempt_prepare(self, obj: Dict) -> Optional[str]:
        """One kubelet-style NodePrepareResources attempt; returns the
        error string (fault surfaced) or None (ready)."""
        claim = Claim(uid=obj["metadata"]["uid"],
                      name=obj["metadata"]["name"],
                      namespace=obj["metadata"]["namespace"])
        self.report.prepares += 1
        try:
            res = self.driver.prepare_claims([claim])[claim.uid]
        except Exception as e:  # noqa: BLE001 — fault escaped as exception
            return str(e)
        return res.error or None

    def attempt_unprepare(self, obj: Dict) -> Optional[str]:
        claim = Claim(uid=obj["metadata"]["uid"],
                      name=obj["metadata"]["name"],
                      namespace=obj["metadata"]["namespace"])
        self.report.unprepares += 1
        try:
            err = self.driver.unprepare_claims([claim])[claim.uid]
        except Exception as e:  # noqa: BLE001
            return str(e)
        return err or None

    # -- the random walk ----------------------------------------------------

    def _random_schedule(self) -> Schedule:
        kind = self.rng.choice(("nth", "prob", "oneshot"))
        if kind == "nth":
            return EveryNth(self.rng.randint(1, 4))
        if kind == "prob":
            return Probabilistic(self.rng.uniform(0.2, 0.7),
                                 random.Random(self.rng.randrange(1 << 30)))
        return OneShot(after=self.rng.randint(0, 3))

    def _harvest_faults(self) -> None:
        """Fold fired counters into the report (and zero them) before
        anything disarms or re-arms sites."""
        for site, fired in FAULTS.take_counts().items():
            self.report.injected[site] = (
                self.report.injected.get(site, 0) + fired)

    def _op_rearm(self) -> None:
        self._harvest_faults()
        site = self.rng.choice(CHAOS_SITES)
        if self.rng.random() < 0.3:
            FAULTS.disarm(site)
            return
        action = (_corrupt_one_slot(self.rng)
                  if site == "checkpoint.corrupt" else None)
        FAULTS.arm(site, self._random_schedule(), action=action)

    def _op_prepare_new(self) -> None:
        free = sorted(set(range(self.n_chips)) - self._used_chips())
        if not free:
            return
        n = self.rng.randint(1, min(2, len(free)))
        picked = self.rng.sample(free, n)
        devices = configs = None
        roll = self.rng.random()
        if roll < 0.2 and n == 1:
            # Subslice claim: any allocatable device backed by the chip.
            names = [name for name, d in self.state.allocatable.items()
                     if d.chip.index == picked[0]]
            devices = [self.rng.choice(names)]
        elif roll < 0.4:
            configs = TS_CONFIG
        obj = self.make_claim(picked, devices=devices, configs=configs)
        err = self.attempt_prepare(obj)
        uid = obj["metadata"]["uid"]
        if err is None:
            self.prepared[uid] = obj
        else:
            self.report.failed_attempts += 1
            self.pending[uid] = obj

    def _op_prepare_batch(self) -> None:
        """Kubelet-style multi-claim RPC: several single-chip claims
        through ONE driver.prepare_claims call — the group-commit path —
        with per-claim outcome tracking (a faulted member lands in
        pending while its batch siblings land in prepared)."""
        free = sorted(set(range(self.n_chips)) - self._used_chips())
        if len(free) < 2:
            return
        n = self.rng.randint(2, min(3, len(free)))
        objs = [self.make_claim([c]) for c in self.rng.sample(free, n)]
        claims = [Claim(uid=o["metadata"]["uid"],
                        name=o["metadata"]["name"],
                        namespace=o["metadata"]["namespace"])
                  for o in objs]
        self.report.prepares += len(objs)
        self.report.batches += 1
        try:
            res = self.driver.prepare_claims(claims)
        except Exception as e:  # noqa: BLE001 — fault escaped as exception
            res = {c.uid: PrepareResult(error=str(e)) for c in claims}
        for obj in objs:
            uid = obj["metadata"]["uid"]
            r = res.get(uid)
            if r is not None and not r.error:
                self.prepared[uid] = obj
            else:
                self.report.failed_attempts += 1
                self.pending[uid] = obj

    def _op_retry_pending(self) -> None:
        if not self.pending:
            return
        uid = self.rng.choice(sorted(self.pending))
        obj = self.pending[uid]
        if obj.get("_chaos_unprepare"):
            # Mid-unprepare claim: kubelet never re-prepares a claim it
            # decided to release; keep driving it toward unallocated.
            if self.attempt_unprepare(obj) is None:
                self.pending.pop(uid)
            else:
                self.report.failed_attempts += 1
            return
        err = self.attempt_prepare(obj)
        if err is None:
            self.prepared[uid] = self.pending.pop(uid)
        else:
            self.report.failed_attempts += 1

    def _op_unprepare(self) -> None:
        pool = sorted(self.prepared) + sorted(self.pending)
        if not pool:
            return
        uid = self.rng.choice(pool)
        obj = self.prepared.get(uid) or self.pending.get(uid)
        err = self.attempt_unprepare(obj)
        if err is None:
            self.prepared.pop(uid, None)
            self.pending.pop(uid, None)
        else:
            self.report.failed_attempts += 1
            # Not cleanly unallocated yet: it must converge at quiesce.
            self.pending.setdefault(uid, self.prepared.pop(uid, obj))
            obj["_chaos_unprepare"] = True

    def _op_health(self) -> None:
        self.report.health_events += 1
        chip = self.rng.randrange(self.n_chips)
        if self.rng.random() < 0.4:
            event = HealthEvent(chip_index=chip, code=0,
                                kind=RECOVERED_KIND)
        else:
            event = HealthEvent(chip_index=chip,
                                code=self.rng.randint(100, 120),
                                kind="hbm_fault")
        self.driver._on_unhealthy_event(event)

    def _op_flap_storm(self) -> None:
        """Drive one chip through the full quarantine ladder: threshold
        unhealthy/recovered flaps in a burst — with health.flap armed
        the graduation may be refused (degrading to transient-unhealthy
        and retrying on the next flap), which is exactly the path under
        test."""
        chip = self.rng.randrange(self.n_chips)
        for _ in range(3):
            self.driver._on_unhealthy_event(HealthEvent(
                chip_index=chip, code=self.rng.randint(100, 120),
                kind="hbm_fault"))
            self.driver._on_unhealthy_event(HealthEvent(
                chip_index=chip, code=0, kind=RECOVERED_KIND))
            self.report.health_events += 2

    def _op_clear_quarantine(self) -> None:
        """The operator's move: lift one random chip's quarantine."""
        q = self.state.quarantined_chips()
        if not q:
            return
        uuid = self.rng.choice(sorted(q))
        self.driver.clear_quarantine(q[uuid].get("chip_index"))

    def run(self, n_events: int = 40) -> ChaosReport:
        ops = [(self._op_prepare_new, 4), (self._op_prepare_batch, 2),
               (self._op_retry_pending, 3),
               (self._op_unprepare, 2), (self._op_rearm, 2),
               (self.crash_restart, 1), (self._op_health, 1),
               (self._op_flap_storm, 1), (self._op_clear_quarantine, 1)]
        weighted = [op for op, w in ops for _ in range(w)]
        try:
            for _ in range(n_events):
                self.report.events += 1
                self.rng.choice(weighted)()
            self.quiesce_and_verify()
        finally:
            self._harvest_faults()
            FAULTS.reset()
            self.close()
        return self.report

    # -- quiesce + invariants -----------------------------------------------

    def quiesce_and_verify(self) -> None:
        self._harvest_faults()
        FAULTS.reset()
        v = self.report.violations

        # 1. Convergence: drive every in-flight claim to its terminal
        # state — the retry loop kubelet would run, minus the waiting.
        for uid in sorted(self.pending):
            obj = self.pending.pop(uid)
            to_unallocated = obj.get("_chaos_unprepare", False)
            err = last = None
            for _ in range(self.MAX_QUIESCE_RETRIES):
                last = (self.attempt_unprepare(obj) if to_unallocated
                        else self.attempt_prepare(obj))
                if last is None:
                    break
            else:
                err = last
            if err is not None:
                v.append(f"claim {uid} did not converge to "
                         f"{'unallocated' if to_unallocated else 'ready'} "
                         f"after faults cleared: {err}")
            elif not to_unallocated:
                self.prepared[uid] = obj

        # 2. Crash consistency: the terminal state must survive an
        # unclean restart (load_or_init + orphan GC path) — INCLUDING
        # the quarantine ledger (SURVEY §18): a crash must not launder
        # a flapping chip back into the inventory.
        q_before = set(self.state.quarantined_chips())
        self.crash_restart()
        q_after = set(self.state.quarantined_chips())
        if q_before != q_after:
            v.append(f"quarantine did not survive restart: before "
                     f"{sorted(q_before)} after {sorted(q_after)}")

        snap = self.state.checkpoint_snapshot()
        want = set(self.prepared)

        # 3. No leaked checkpoint entries / lost claims.
        got = set(snap.claims)
        if got != want:
            v.append(f"checkpoint claims {sorted(got)} != expected "
                     f"prepared {sorted(want)}")
        for uid, pc in snap.claims.items():
            if pc.state != PREPARE_COMPLETED:
                v.append(f"claim {uid} left in state {pc.state} "
                         "after quiesce")

        # 4. No orphaned CDI spec files.
        specs = set(self.cdi.list_claim_uids())
        if specs != want:
            v.append(f"CDI claim specs {sorted(specs)} != expected "
                     f"{sorted(want)}")

        # 5. Idempotent re-prepare returns the same devices.
        for uid, obj in sorted(self.prepared.items()):
            err = self.attempt_prepare(obj)
            if err is not None:
                v.append(f"re-prepare of converged claim {uid} "
                         f"errored: {err}")

        # 6. ResourceSlice matches the healthy-chip device set.
        try:
            self.driver.publish_resources()
            slices = self.cluster.list(RESOURCESLICES)
            published = {d["name"] for s in slices
                         for d in s["spec"].get("devices", [])}
            healthy = {d["name"] for d in self.state.healthy_devices()}
            if published != healthy:
                v.append(f"ResourceSlice devices {sorted(published)} != "
                         f"healthy set {sorted(healthy)}")
        except Exception as e:  # noqa: BLE001
            v.append(f"publish after quiesce failed: {e}")

        # 7. Full teardown: everything unprepares, zero residue.
        for uid, obj in sorted(self.prepared.items()):
            err = self.attempt_unprepare(obj)
            if err is not None:
                v.append(f"final unprepare of {uid} failed: {err}")
        self.prepared.clear()
        if self.cdi.list_claim_uids():
            v.append("CDI specs left after full teardown: "
                     f"{self.cdi.list_claim_uids()}")
        if self.state.prepared_claim_uids():
            v.append("checkpoint entries left after full teardown: "
                     f"{self.state.prepared_claim_uids()}")

        # 8. Lock-order witness: the whole walk (prepare storms, crash
        # restarts, health events across watch/workqueue/gRPC threads)
        # must leave an ACYCLIC acquisition-order graph and no data lock
        # held across outlier-length work (SURVEY §12).
        v.extend(lockwitness.WITNESS.violations_since(
            self._witness_snap, max_hold_s=LOCK_HOLD_OUTLIER_S))

        # 9. Trace completeness (SURVEY §19): every span this walk began
        # — across prepare storms, crash restarts (the prepare_batch
        # finally abandons mid-crash spans), fault-aborted batches and
        # the trace.emit drop path — must be CLOSED at quiesce. An open
        # span here is a leaked attribution context: exactly the bug
        # class the span discipline (dralint R12) states lexically.
        v.extend(trace.open_span_violations(self._trace_snap))


def run_schedule(seed: int, n_events: int = 40, chips: int = 4) -> ChaosReport:
    """One seeded fault schedule to quiesce; the chaos tier's unit."""
    return ChaosHarness(seed, chips=chips).run(n_events)


def run_matrix(seeds: List[int], n_events: int = 40) -> Dict:
    reports = [run_schedule(seed, n_events) for seed in seeds]
    injected: Dict[str, int] = {}
    for r in reports:
        for site, n in r.injected.items():
            injected[site] = injected.get(site, 0) + n
    return {
        "schedules": len(reports),
        "events": sum(r.events for r in reports),
        "prepares": sum(r.prepares for r in reports),
        "batches": sum(r.batches for r in reports),
        "failed_attempts": sum(r.failed_attempts for r in reports),
        "crashes": sum(r.crashes for r in reports),
        "injected": injected,
        "violations": [f"seed {r.seed}: {msg}"
                       for r in reports for msg in r.violations],
    }


# ---------------------------------------------------------------------------
# Scheduler-churn walk (event-driven control plane under faults)
# ---------------------------------------------------------------------------

# Sites the scheduler walk may arm: API flakes and watch-stream drops hit
# the informer plane; sched.watch_event / sched.index_apply hit the
# scheduler's own event handling and incremental allocation index;
# sched.shard_apply dirties ONE shard of the sharded index (the
# shard-scoped resync path), and sched.snapshot_commit refuses
# optimistic commits (the multi-worker conflict/requeue path) — so the
# guarded resync fallback AND the parallel core's commit discipline are
# chaos-tested on the production path.
# sched.watch_shard_dispatch sheds deltas off the partitioned claims
# informer's shard FIFOs (the bounded-queue overflow path), and
# sched.informer_shard_relist faults the recovery hook itself — together
# they chaos-test the shard-dirty + resync pipeline that heals a shed
# delta, including its whole-index degradation.
SCHED_CHAOS_SITES = ("k8s.api.request", "k8s.watch.drop",
                     "sched.watch_event", "sched.index_apply",
                     "sched.shard_apply", "sched.snapshot_commit",
                     "sched.watch_shard_dispatch",
                     "sched.informer_shard_relist",
                     "trace.emit")


def chip_conflicts(claims: List[Dict]) -> List[str]:
    """Device double-allocations across allocated claims, with partition
    semantics: the same device twice, or a whole chip plus any of its
    subslices, in DIFFERENT claims. Public: the drmc model checker
    asserts it at every explored terminal state (analysis/drmc), the
    scheduler chaos walk at quiesce."""
    from tpu_dra.simcluster.scheduler import (
        _parent_of, claim_entries, claim_key,
    )

    holders: Dict[tuple, List[str]] = {}     # (driver,pool,device) -> keys
    chip_holders: Dict[tuple, List[tuple]] = {}  # (driver,pool,chip) ->
    #                                              [(key, is_whole)]
    out = []
    for claim in claims:
        key = claim_key(claim)
        for driver, pool, dev in claim_entries(claim):
            holders.setdefault((driver, pool, dev), []).append(key)
            chip = _parent_of(dev)  # the scheduler's own partition rule
            chip_holders.setdefault((driver, pool, chip), []).append(
                (key, chip == dev))
    for ent, keys in sorted(holders.items()):
        if len(set(keys)) > 1:
            out.append(f"device {ent} allocated to {sorted(set(keys))}")
    for ent, users in sorted(chip_holders.items()):
        whole = {k for k, is_whole in users if is_whole}
        subs = {k for k, is_whole in users if not is_whole}
        if whole and subs - whole:
            out.append(f"chip {ent} wholly allocated to {sorted(whole)} "
                       f"while subslices go to {sorted(subs)}")
    return out


class SchedulerChaosHarness:
    """One seeded schedule against the EVENT-DRIVEN scheduler: a random
    walk of pod churn (create / delete), fault re-arming across
    SCHED_CHAOS_SITES, and forced resyncs, against a real Scheduler over
    a RetryingApiClient-wrapped FakeCluster with a deliberately tiny
    watch-event log (dropped streams hit real 410 relists). After the
    walk, faults are disarmed and the harness waits for convergence,
    then asserts the ISSUE's invariants:

    1. every live pod is bound, its claims allocated on its node;
    2. no device double-allocation (partition semantics included);
    3. no claim left behind by a dead pod (no leak after pod death);
    4. the incremental allocation index matches cluster truth.
    """

    QUIESCE_TIMEOUT = 30.0

    # Sites the walk's re-arm op may pick; subclasses extend (the
    # topology walk adds the data-plane handoff sites).
    REARM_SITES = SCHED_CHAOS_SITES

    def __init__(self, seed: int, *, nodes: int = 4, chips_per_node: int = 2,
                 workers: int = 4):
        # Witness the scheduler's lock population (informer RLocks,
        # allocation-index lock, pending-set lock, rate-limiter locks):
        # quiesce asserts the acquisition-order graph stayed acyclic.
        lockwitness.install()
        self._witnessed = True
        self._witness_snap = lockwitness.WITNESS.snapshot()
        # Per-walk open-span window (invariant 9 / SURVEY §19).
        self._trace_snap = trace.TRACER.open_ids()
        # View shadow (SURVEY §20): every zero-copy view the scheduler
        # reads this walk is content-hashed at hand-out; quiesce
        # asserts none drifted (the runtime half of drflow R13).
        self._shadow_prev = informer_mod.SHADOW.enable()
        self._shadow_snap = informer_mod.SHADOW.snapshot()
        self.seed = seed
        self.rng = random.Random(seed ^ 0x5C4ED)
        self.report = ChaosReport(seed=seed)
        self.nodes = nodes
        self.chips = chips_per_node
        self.capacity = nodes * chips_per_node
        try:
            self.cluster = FakeCluster()
            self.cluster.EVENT_LOG_CAP = 48  # tight history: drops hit 410s
            self.client = RetryingApiClient(
                self.cluster, max_attempts=4, base_delay=0.001,
                max_delay=0.01, rng=random.Random(seed ^ 0xD15C))
            self._seed_inventory()
            # workers=4: the walk exercises the multi-worker pool — the
            # per-key serialization and optimistic snapshot-commit
            # disciplines are chaos invariants, not just bench wins.
            self._start_scheduler(workers)
            self.live: Dict[str, None] = {}
            self._pod_seq = 0
        except BaseException:
            # Anything after install() failing must release the witness
            # refcount, or threading.Lock stays patched process-wide
            # (and the view shadow must not stay enabled either).
            informer_mod.SHADOW.restore(self._shadow_prev)
            self._witnessed = False
            lockwitness.uninstall()
            raise

    def _seed_inventory(self) -> None:
        from tpu_dra.testing import seed_sched_inventory
        seed_sched_inventory(self.cluster, nodes=self.nodes,
                             chips_per_node=self.chips)

    def _start_scheduler(self, workers: int) -> None:
        """Seam the HA walk overrides to run a replicated pair behind
        leader election instead of one always-acting scheduler."""
        from tpu_dra.simcluster.scheduler import Scheduler
        self.sched = Scheduler(self.client, resync_interval=0.05,
                               gc_sweep_interval=0.2, workers=workers)
        self.sched.start()
        for inf in self.sched._informers.values():
            inf.RELIST_BACKOFF_BASE = 0.01  # keep the chaos tier fast

    # -- walk ops -----------------------------------------------------------

    def _random_schedule(self) -> Schedule:
        kind = self.rng.choice(("nth", "prob", "oneshot"))
        if kind == "nth":
            return EveryNth(self.rng.randint(1, 4))
        if kind == "prob":
            return Probabilistic(self.rng.uniform(0.1, 0.5),
                                 random.Random(self.rng.randrange(1 << 30)))
        return OneShot(after=self.rng.randint(0, 3))

    def _harvest_faults(self) -> None:
        for site, fired in FAULTS.take_counts().items():
            self.report.injected[site] = (
                self.report.injected.get(site, 0) + fired)

    def _op_rearm(self) -> None:
        self._harvest_faults()
        site = self.rng.choice(self.REARM_SITES)
        if self.rng.random() < 0.3:
            FAULTS.disarm(site)
            return
        FAULTS.arm(site, self._random_schedule())

    def _op_create_pod(self) -> None:
        if len(self.live) >= self.capacity:
            return  # keep the cluster satisfiable: quiesce expects binds
        from tpu_dra.testing import make_sched_pod
        name = f"cp-{self.seed}-{self._pod_seq}"
        self._pod_seq += 1
        make_sched_pod(self.cluster, name)
        self.live[name] = None
        self.report.prepares += 1  # pod lifecycles driven

    def _op_delete_pod(self) -> None:
        if not self.live:
            return
        name = self.rng.choice(sorted(self.live))
        self.cluster.delete(PODS, name, "default")
        self.live.pop(name, None)
        self.report.unprepares += 1

    def _op_force_resync(self) -> None:
        self.sched.request_resync("chaos op")

    # -- run + invariants ---------------------------------------------------

    def _ops(self):
        """(op, weight) pairs of the walk; subclasses extend."""
        return [(self._op_create_pod, 4), (self._op_delete_pod, 2),
                (self._op_rearm, 2), (self._op_force_resync, 1)]

    def run(self, n_events: int = 60) -> ChaosReport:
        weighted = [op for op, w in self._ops() for _ in range(w)]
        try:
            for _ in range(n_events):
                self.report.events += 1
                self.rng.choice(weighted)()
                # Let the control plane breathe between ops; the walk is
                # about interleaving, not about starving the scheduler.
                time.sleep(self.rng.uniform(0.0, 0.004))
            self.quiesce_and_verify()
        finally:
            self._harvest_faults()
            FAULTS.reset()
            self.close()
        return self.report

    def _converged(self) -> List[str]:
        """Empty when the control plane reached the expected steady
        state; otherwise what is still wrong (the quiesce loop polls
        this until the deadline, then records it as violations)."""
        problems = []
        pods = {p["metadata"]["name"]: p
                for p in self.cluster.list(PODS, namespace="default")}
        claims = self.cluster.list(RESOURCECLAIMS, namespace="default")
        for name in sorted(self.live):
            pod = pods.get(name)
            if pod is None:
                problems.append(f"live pod {name} missing from cluster")
                continue
            node = pod["spec"].get("nodeName")
            if not node:
                problems.append(f"live pod {name} not bound")
                continue
            claim = next((c for c in claims
                          if (c["metadata"].get("annotations") or {}).get(
                              "sim/owner-pod") == name), None)
            if claim is None:
                problems.append(f"live pod {name} has no claim")
                continue
            entries = [r.get("pool") for r in
                       ((claim.get("status") or {}).get("allocation") or {})
                       .get("devices", {}).get("results", [])]
            if not entries:
                problems.append(f"claim of live pod {name} unallocated")
            elif set(entries) != {node}:
                problems.append(f"pod {name} bound to {node} but claim "
                                f"allocated on {sorted(set(entries))}")
        alive = set(self.live)
        for claim in claims:
            owner = (claim["metadata"].get("annotations") or {}).get(
                "sim/owner-pod")
            if owner and owner not in alive:
                problems.append(f"claim {claim['metadata']['name']} leaked "
                                f"after pod {owner} death")
        # Index health is part of convergence: a resync enqueued by the
        # walk's final ops may still be queued — asserting one-shot
        # after cluster-truth convergence would flag that transient as
        # a violation.
        if self.sched._index.dirty:
            problems.append("index dirty (resync pending)")
        else:
            problems.extend(self.sched.verify_index())
        return problems

    def quiesce_and_verify(self) -> None:
        self._harvest_faults()
        FAULTS.reset()
        v = self.report.violations
        deadline = time.monotonic() + self.QUIESCE_TIMEOUT
        problems = self._converged()
        while problems and time.monotonic() < deadline:
            time.sleep(0.02)
            problems = self._converged()
        v.extend(problems)
        # Hard invariants, on cluster truth after convergence:
        claims = self.cluster.list(RESOURCECLAIMS, namespace="default")
        v.extend(chip_conflicts(claims))
        v.extend(self.sched.verify_index())
        # Lock-order witness over the event-driven control plane: the
        # walk's informer/workqueue/worker interleavings must leave an
        # acyclic lock graph and no outlier-length data-lock hold.
        v.extend(lockwitness.WITNESS.violations_since(
            self._witness_snap, max_hold_s=LOCK_HOLD_OUTLIER_S))
        # Trace completeness (SURVEY §19): every Allocated claim must
        # carry the traceparent annotation the scheduler stamped in the
        # allocation write, and that trace must be a complete span tree
        # — all spans closed, parents precede children (a trace that
        # lost a span to the trace.emit fault skips structure but still
        # owes zero open spans). Then the walk-wide open-span sweep.
        for claim in claims:
            if not (claim.get("status") or {}).get("allocation"):
                continue
            tp = (claim["metadata"].get("annotations") or {}).get(
                trace.TRACEPARENT_ANNOTATION)
            parsed = trace.parse_traceparent(tp)
            if parsed is None:
                v.append(f"allocated claim {claim['metadata']['name']} "
                         f"carries no valid traceparent annotation "
                         f"({tp!r})")
                continue
            v.extend(trace.verify_trace(parsed[0]))
        v.extend(trace.open_span_violations(self._trace_snap))
        # View-shadow sweep (SURVEY §20): any zero-copy view mutated in
        # place since hand-out is a violation — the runtime complement
        # of drflow R13, and the drift set the lint.sh observed⊆static
        # gate cross-validates.
        v.extend(informer_mod.SHADOW.violations_since(self._shadow_snap))

    def _stop_scheduler(self) -> None:
        """Seam paired with _start_scheduler (HA walk stops a pair)."""
        self.sched.stop()

    def close(self) -> None:
        try:
            self._stop_scheduler()
        finally:
            informer_mod.SHADOW.export()
            informer_mod.SHADOW.restore(self._shadow_prev)
            if self._witnessed:
                self._witnessed = False
                lockwitness.uninstall()


def run_sched_schedule(seed: int, n_events: int = 60) -> ChaosReport:
    """One seeded scheduler-churn walk to quiesce."""
    return SchedulerChaosHarness(seed).run(n_events)


# ---------------------------------------------------------------------------
# HA leader-kill walk (SURVEY §22)
# ---------------------------------------------------------------------------

# The election/takeover sites the leader-kill walk re-arms on top of the
# scheduler set: renew failures depose leaders mid-churn, takeover-resync
# faults force the promote degradation (queued re-resync, dirty shards
# refusing commits).
HA_CHAOS_SITES = ("sched.lease_renew", "sched.takeover_resync")


class LeaderKillChaosHarness(SchedulerChaosHarness):
    """The scheduler walk replicated (SURVEY §22): two Scheduler
    replicas behind LeaderElectors over one fenced Lease, plus walk ops
    that kill the acting leader cold (no lease release — the standby
    must wait out expiry, CAS the takeover, resync, resume) and kill/
    revive nodes so takeovers race pod churn AND eviction. Each kill
    refills the slot with a fresh standby under a NEW identity, so
    every kill is a genuine expiry-takeover, and the walk keeps a
    2-replica pool throughout. Invariants on top of the base set:
    at most one acting leader at quiesce, and — via the fencing
    reactor — no deposed leader's late commit landing (it would
    surface as double allocation / index divergence)."""

    REARM_SITES = SCHED_CHAOS_SITES + HA_CHAOS_SITES
    LEASE_DURATION_S = 0.3

    def _start_scheduler(self, workers: int) -> None:
        from tpu_dra.infra.leaderelect import install_fencing
        install_fencing(self.cluster)
        self._ha_workers = workers
        self._incarnation = 0
        self._replicas: List = [None, None]
        self._electors: List = [None, None]
        self.dead_nodes: Dict[str, Dict] = {}
        self.leader_kills = 0
        for slot in range(2):
            self._spawn_replica(slot)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            if any(s is not None and not s.is_standby
                   for s in self._replicas):
                return  # steady state: an acting leader exists
            time.sleep(0.005)
        raise RuntimeError("no replica became acting leader at startup")

    @property
    def sched(self):
        """The acting replica (the base walk's invariants read index
        state through this); mid-takeover, whichever replica exists."""
        for s in self._replicas:
            if s is not None and not s.is_standby:
                return s
        return next(s for s in self._replicas if s is not None)

    def _spawn_replica(self, slot: int) -> None:
        from tpu_dra.infra.leaderelect import LeaderElector
        from tpu_dra.simcluster.scheduler import Scheduler
        sched = Scheduler(self.client, resync_interval=0.05,
                          gc_sweep_interval=0.2, workers=self._ha_workers)
        sched.start(standby=True)
        for inf in sched._informers.values():
            inf.RELIST_BACKOFF_BASE = 0.01
        self._incarnation += 1
        ident = f"rep{slot}-{self._incarnation}"

        def on_started(gen, s=sched):
            s.set_lease_generation(gen)
            s.promote()

        elector = LeaderElector(
            self.client, ident,
            lease_duration_s=self.LEASE_DURATION_S,
            renew_interval_s=0.08,
            on_started_leading=on_started,
            seed=self.seed * 101 + self._incarnation)
        self._replicas[slot] = sched
        self._electors[slot] = elector
        elector.start()

    def _op_kill_leader(self) -> None:
        """Kill the acting leader cold, racing whatever churn/eviction
        is in flight, and refill the slot with a fresh standby."""
        idx = next((i for i, el in enumerate(self._electors)
                    if el is not None and el.is_leader), None)
        if idx is None:
            return  # mid-takeover: no acting leader to kill
        self._electors[idx].stop()
        self._replicas[idx].stop()
        self.report.crashes += 1
        self.leader_kills += 1
        self._spawn_replica(idx)

    def _op_kill_node(self) -> None:
        """Node death feeding the eviction scan (so takeovers race
        eviction, not just churn); at least half the fleet survives."""
        from tpu_dra.k8s import NODES, RESOURCESLICES
        alive = sorted(n["metadata"]["name"]
                       for n in self.cluster.list(NODES))
        if len(alive) <= max(1, self.nodes // 2):
            return
        name = self.rng.choice(alive)
        node_obj = next(n for n in self.cluster.list(NODES)
                        if n["metadata"]["name"] == name)
        slices = [sl for sl in self.cluster.list(RESOURCESLICES)
                  if (sl.get("spec") or {}).get("nodeName") == name]
        strip = NodeDeathChaosHarness._strip_meta
        self.dead_nodes[name] = {
            "node": strip(node_obj),
            "slices": [strip(sl) for sl in slices]}
        for sl in slices:
            self.cluster.delete(RESOURCESLICES, sl["metadata"]["name"],
                                None)
        self.cluster.delete(NODES, name, None)

    def _op_revive_node(self) -> None:
        from tpu_dra.k8s import NODES, RESOURCESLICES
        if not self.dead_nodes:
            return
        name = self.rng.choice(sorted(self.dead_nodes))
        saved = self.dead_nodes.pop(name)
        self.cluster.create(NODES, saved["node"])
        for sl in saved["slices"]:
            self.cluster.create(RESOURCESLICES, sl)

    def _ops(self):
        return super()._ops() + [(self._op_kill_leader, 2),
                                 (self._op_kill_node, 1),
                                 (self._op_revive_node, 1)]

    def quiesce_and_verify(self) -> None:
        # Revive the whole fleet first: quiesce owes every live pod a
        # bind, which needs the seeded capacity back (evicted claims
        # re-drive onto the restored nodes).
        from tpu_dra.k8s import NODES, RESOURCESLICES
        for name in sorted(self.dead_nodes):
            saved = self.dead_nodes[name]
            self.cluster.create(NODES, saved["node"])
            for sl in saved["slices"]:
                self.cluster.create(RESOURCESLICES, sl)
        self.dead_nodes.clear()
        super().quiesce_and_verify()
        acting = [el.identity for el in self._electors
                  if el is not None and el.is_leader]
        if len(acting) > 1:
            self.report.violations.append(
                f"two acting leaders at quiesce: {sorted(acting)}")

    def _stop_scheduler(self) -> None:
        for elector in self._electors:
            if elector is not None:
                elector.stop()
        for sched in self._replicas:
            if sched is not None:
                sched.stop()


def run_leaderkill_schedule(seed: int, n_events: int = 60) -> ChaosReport:
    """One seeded leader-kill walk to quiesce."""
    return LeaderKillChaosHarness(seed).run(n_events)


def run_leaderkill_matrix(seeds: List[int], n_events: int = 60) -> Dict:
    return _pod_matrix_summary(
        [run_leaderkill_schedule(seed, n_events) for seed in seeds])


# ---------------------------------------------------------------------------
# Topology walk (ICI-contiguous allocation under churn + faults)
# ---------------------------------------------------------------------------

# Claim sizes the topology walk mixes (weights mirror a realistic
# single-chip-heavy load with a multi-chip tail).
TOPO_CLAIM_SIZES = (1, 1, 2, 2, 4)

# Data-plane handoff sites (SURVEY §17) the topology walk additionally
# arms: mesh.build fires inside meshexport.plan_from_* (the allocation
# -> MeshPlan constructor the workload's mesh builder runs), and
# workload.launch inside the launch-admission seam. The walk's mesh
# probe keeps exercising both against live allocations, so the refusal
# paths are chaos-tested, and quiesce asserts that with faults disarmed
# every allocated multi-chip claim still yields a contiguous plan.
MESH_CHAOS_SITES = ("mesh.build", "workload.launch")


class TopologyChaosHarness(SchedulerChaosHarness):
    """The scheduler walk with the TopologyAwareScheduling gate ON over
    a coordinate-publishing inventory (2 ICI slices x 2 hosts x 16
    chips), churning a mix of 1/2/4-chip pods. On top of the base
    invariants, after quiesce:

    5. every allocated multi-chip claim is an ICI-contiguous cuboid;
    6. the topology free-set derived from the allocation index exactly
       matches the one derived from cluster truth
       (Scheduler.verify_topology).

    In-flight chip load is capped below capacity so quiesce stays
    satisfiable: the topology path deliberately REFUSES non-contiguous
    placements, and a walk pinned at 100% utilization could wedge a
    final multi-chip pod behind fragmentation no future free will clear
    (no deletes happen after the walk).

    7. (data-plane handoff, SURVEY §17) every allocated multi-chip claim
       on the coordinate-publishing inventory yields a MeshPlan —
       contiguous, with a positive modeled ICI bandwidth — once faults
       are disarmed; during the walk the probe op keeps building plans
       with mesh.build/workload.launch armed, so refusals surface as
       FaultInjected (counted), never as a wrong mesh."""

    REARM_SITES = SCHED_CHAOS_SITES + MESH_CHAOS_SITES

    def __init__(self, seed: int, *, nodes: int = 4,
                 chips_per_node: int = 16):
        self._topo_gates = featuregates.Features.overrides_snapshot()
        featuregates.Features.set_from_string("TopologyAwareScheduling=true")
        try:
            super().__init__(seed, nodes=nodes,
                             chips_per_node=chips_per_node)
        except Exception:
            featuregates.Features.restore_overrides(self._topo_gates)
            raise
        self.pod_chips: Dict[str, int] = {}
        self.chip_budget = (self.capacity * 3) // 4

    def _seed_inventory(self) -> None:
        from tpu_dra.testing import seed_sched_inventory
        seed_sched_inventory(self.cluster, nodes=self.nodes,
                             chips_per_node=self.chips,
                             generation="v5p", hosts_per_slice=2,
                             claim_counts=(2, 4))

    def _op_create_pod(self) -> None:
        n = self.rng.choice(TOPO_CLAIM_SIZES)
        if sum(self.pod_chips.values()) + n > self.chip_budget:
            return
        from tpu_dra.testing import make_sched_pod
        name = f"tp-{self.seed}-{self._pod_seq}"
        self._pod_seq += 1
        make_sched_pod(self.cluster, name,
                       template="tmpl" if n == 1 else f"tmpl{n}")
        self.live[name] = None
        self.pod_chips[name] = n
        self.report.prepares += 1

    def _op_delete_pod(self) -> None:
        if not self.live:
            return
        name = self.rng.choice(sorted(self.live))
        self.cluster.delete(PODS, name, "default")
        self.live.pop(name, None)
        self.pod_chips.pop(name, None)
        self.report.unprepares += 1

    def _prune_wedged(self) -> None:
        """Strict gate-on semantics can leave a pod PROVABLY
        unplaceable at quiesce: no node's remaining free coordinate set
        admits a contiguous cuboid of its size, and with the walk over
        no delete will ever free one (free sets only shrink). That is
        the documented wait-for-capacity behavior, not a scheduler bug
        — delete such pods (the operator's move) instead of letting the
        convergence deadline record a false violation. Pruning requires
        proof: every node rejects the pod's count against cluster-truth
        free sets. Called from the convergence poll, so a pod that
        becomes provably wedged only after a sibling allocates is still
        caught."""
        from tpu_dra import topology
        from tpu_dra.k8s import RESOURCESLICES
        from tpu_dra.simcluster.scheduler import _parent_of, claim_entries

        unbound = []
        pods = {p["metadata"]["name"]: p
                for p in self.cluster.list(PODS, namespace="default")}
        for name in sorted(self.live):
            pod = pods.get(name)
            if pod is not None and not pod["spec"].get("nodeName"):
                unbound.append(name)
        if not unbound:
            return
        claims = self.cluster.list(RESOURCECLAIMS, namespace="default")
        taken: Dict[str, set] = {}
        for claim in claims:
            owner = (claim["metadata"].get("annotations") or {}).get(
                "sim/owner-pod")
            if owner and owner not in pods:
                # Dead pod's claim: GC will free these chips — counting
                # them as taken could prune a pod the scheduler would
                # legitimately place once the drain completes, masking a
                # real wedge bug behind a premature prune.
                continue
            for _drv, pool, dev in claim_entries(claim):
                taken.setdefault(pool, set()).add(_parent_of(dev))
        frees = []
        for sl in self.cluster.list(RESOURCESLICES):
            node = (sl.get("spec") or {}).get("nodeName")
            topo = topology.node_topology_from_slices([sl])
            if node and topo is not None:
                frees.append((topo, {
                    c for dev, c in topo.coord_of.items()
                    if dev not in taken.get(node, set())}))
        for name in unbound:
            n = self.pod_chips.get(name, 1)
            if any(topology.best_placement(topo.mesh, free, n) is not None
                   for topo, free in frees):
                continue  # placeable: let the scheduler get there
            self.cluster.delete(PODS, name, "default")
            self.live.pop(name, None)
            self.pod_chips.pop(name, None)
            self.report.unprepares += 1
            log.info("topology chaos: pruned provably-unplaceable pod %s "
                     "(%d chips, fragmentation wedge)", name, n)

    def _ops(self):
        return super()._ops() + [(self._op_mesh_probe, 2)]

    def _allocated_multichip_claims(self) -> List[Dict]:
        return [c for c in self.cluster.list(RESOURCECLAIMS,
                                             namespace="default")
                if len((((c.get("status") or {}).get("allocation") or {})
                        .get("devices") or {}).get("results") or []) >= 2]

    def _op_mesh_probe(self) -> None:
        """Build a MeshPlan from one live allocation + admit a launch,
        with whatever faults the walk armed: the data-plane handoff's
        production guards (mesh.build, workload.launch) fire here, and
        an injected fault must surface as FaultInjected — a refusal the
        workload retries — never as a silently mis-ordered mesh."""
        from tpu_dra.infra.faults import FaultInjected
        from tpu_dra.topology import meshexport

        claims = self._allocated_multichip_claims()
        if not claims:
            return
        claim = self.rng.choice(sorted(
            claims, key=lambda c: c["metadata"]["name"]))
        slices = self.cluster.list(RESOURCESLICES)
        try:
            plan = meshexport.plan_from_allocation(claim, slices)
            meshexport.admit_launch("allreduce")
        except FaultInjected:
            return  # counted via FAULTS.take_counts at harvest
        except meshexport.MeshBuildError as e:
            # A racing deallocation can momentarily list a claim whose
            # slices moved; quiesce re-checks with the world stopped,
            # so only repeated failure there is a violation.
            log.info("topology chaos: mid-walk mesh probe refused: %s", e)
            return
        if not plan.contiguous:
            self.report.violations.append(
                f"mesh probe: claim {claim['metadata']['name']} built a "
                f"non-contiguous plan on the gate-on inventory "
                f"(coords {list(plan.coords)})")

    def _verify_mesh_handoff(self) -> List[str]:
        """Quiesce invariant 7: faults disarmed, every allocated
        multi-chip claim must yield a contiguous MeshPlan with positive
        modeled ICI bandwidth."""
        from tpu_dra.topology import meshexport

        out: List[str] = []
        slices = self.cluster.list(RESOURCESLICES)
        for claim in self._allocated_multichip_claims():
            name = claim["metadata"]["name"]
            try:
                plan = meshexport.plan_from_allocation(claim, slices)
            except Exception as e:  # noqa: BLE001 — any failure is a finding
                out.append(f"mesh handoff: claim {name} yields no plan: {e}")
                continue
            if not plan.contiguous:
                out.append(f"mesh handoff: claim {name} plan is not a "
                           f"contiguous cuboid (coords {list(plan.coords)})")
            if plan.modeled_ici_gbps <= 0:
                out.append(f"mesh handoff: claim {name} modeled ICI "
                           f"bandwidth is {plan.modeled_ici_gbps}")
        return out

    def _converged(self) -> List[str]:
        self._prune_wedged()
        return super()._converged()

    def quiesce_and_verify(self) -> None:
        super().quiesce_and_verify()
        self.report.violations.extend(self.sched.verify_topology())
        self.report.violations.extend(self._verify_mesh_handoff())

    def close(self) -> None:
        try:
            super().close()
        finally:
            featuregates.Features.restore_overrides(self._topo_gates)


def run_topo_schedule(seed: int, n_events: int = 60) -> ChaosReport:
    """One seeded topology walk to quiesce."""
    return TopologyChaosHarness(seed).run(n_events)


# ---------------------------------------------------------------------------
# Node-death walk (failure-domain recovery racing pod churn, SURVEY §18)
# ---------------------------------------------------------------------------

class NodeDeathChaosHarness(TopologyChaosHarness):
    """The topology walk plus the classic production failure: hardware
    dies mid-traffic. The walk kills nodes (Node + ResourceSlices gone),
    quarantines chips (the slice shrinks, the driver-republish analog),
    revives both, and arms ``sched.evict`` on top of the scheduler
    sites — while pods churn. The control plane must CONVERGE, not
    wedge; after quiesce:

    8. no claim is allocated to a dead node or an unpublished (dead /
       quarantined) device;
    9. every live pod is either bound with its claim Allocated on live
       published chips, or — when no placement exists on the surviving
       topology — Pending WITH a recorded PodScheduled=False reason
       (strict topology refusal, never a silent shrink or hang);
    10. a pod that IS placeable on the surviving capacity gets placed
       (eviction re-drives, the strict-refusal path does not leak pods).

    Pruning is OFF in this walk: provably-unplaceable pods are the
    invariant (Pending-with-reason), not noise to delete.
    """

    REARM_SITES = TopologyChaosHarness.REARM_SITES + ("sched.evict",)

    # Claim sizes: single-chip heavy so dead capacity rarely wedges
    # everything, with a multi-chip tail to exercise strict refusal.
    CLAIM_SIZES = (1, 1, 1, 2, 4)

    def __init__(self, seed: int, *, nodes: int = 4,
                 chips_per_node: int = 8):
        super().__init__(seed, nodes=nodes, chips_per_node=chips_per_node)
        # name -> saved {"node": obj, "slices": [objs]} for revival.
        self.dead_nodes: Dict[str, Dict] = {}
        # node -> {device name: saved device obj} (quarantined chips).
        self.dead_chips: Dict[str, Dict[str, Dict]] = {}

    # -- capacity bookkeeping ------------------------------------------------

    def _published(self) -> Dict[str, set]:
        from tpu_dra.k8s import RESOURCESLICES
        out: Dict[str, set] = {}
        for sl in self.cluster.list(RESOURCESLICES):
            node = (sl.get("spec") or {}).get("nodeName")
            if node:
                out.setdefault(node, set()).update(
                    d["name"] for d in sl["spec"].get("devices", []))
        return out

    def _nodes_alive(self) -> set:
        from tpu_dra.k8s import NODES
        return {n["metadata"]["name"] for n in self.cluster.list(NODES)}

    def _op_create_pod(self) -> None:
        # Budget against LIVE capacity, not the seeded total — a walk
        # that killed half the fleet must stop admitting at half rate.
        alive = self._nodes_alive()
        live = sum(len(devs) for node, devs in self._published().items()
                   if node in alive)
        self.chip_budget = (live * 3) // 4
        n = self.rng.choice(self.CLAIM_SIZES)
        if sum(self.pod_chips.values()) + n > self.chip_budget:
            return
        from tpu_dra.testing import make_sched_pod
        name = f"nd-{self.seed}-{self._pod_seq}"
        self._pod_seq += 1
        make_sched_pod(self.cluster, name,
                       template="tmpl" if n == 1 else f"tmpl{n}")
        self.live[name] = None
        self.pod_chips[name] = n
        self.report.prepares += 1

    # -- failure-domain ops --------------------------------------------------

    @staticmethod
    def _strip_meta(obj: Dict) -> Dict:
        from tpu_dra.k8s.client import json_deepcopy
        out = json_deepcopy(obj)
        for key in ("resourceVersion", "uid", "creationTimestamp"):
            out["metadata"].pop(key, None)
        return out

    def _op_kill_node(self) -> None:
        """Node death: the Node object AND its ResourceSlices vanish
        (kubelet gone, slice GC done). At least half the fleet stays
        alive so quiesce retains surviving capacity to re-drive onto."""
        from tpu_dra.k8s import NODES, RESOURCESLICES
        candidates = sorted(self._nodes_alive())
        if len(candidates) <= max(1, self.nodes // 2):
            return
        name = self.rng.choice(candidates)
        node_obj = next(n for n in self.cluster.list(NODES)
                        if n["metadata"]["name"] == name)
        slices = [sl for sl in self.cluster.list(RESOURCESLICES)
                  if (sl.get("spec") or {}).get("nodeName") == name]
        self.dead_nodes[name] = {
            "node": self._strip_meta(node_obj),
            "slices": [self._strip_meta(sl) for sl in slices]}
        for sl in slices:
            self.cluster.delete(RESOURCESLICES, sl["metadata"]["name"],
                                None)
        self.cluster.delete(NODES, name, None)
        self.report.crashes += 1
        log.info("node-death chaos: killed node %s", name)

    def _op_revive_node(self) -> None:
        from tpu_dra.k8s import NODES, RESOURCESLICES
        if not self.dead_nodes:
            return
        name = self.rng.choice(sorted(self.dead_nodes))
        saved = self.dead_nodes.pop(name)
        self.cluster.create(NODES, saved["node"])
        for sl in saved["slices"]:
            self.cluster.create(RESOURCESLICES, sl)
        log.info("node-death chaos: revived node %s", name)

    def _op_quarantine_chip(self) -> None:
        """The driver-quarantine republish analog: one whole chip drops
        out of its node's published ResourceSlice."""
        from tpu_dra.k8s import RESOURCESLICES
        alive = sorted(self._nodes_alive())
        if not alive:
            return
        node = self.rng.choice(alive)
        for sl in self.cluster.list(RESOURCESLICES):
            if (sl.get("spec") or {}).get("nodeName") != node:
                continue
            devices = sl["spec"].get("devices", [])
            if len(devices) <= 1:
                return  # keep the node publishing something
            dev = self.rng.choice(sorted(d["name"] for d in devices))
            saved = next(d for d in devices if d["name"] == dev)
            sl["spec"]["devices"] = [d for d in devices
                                     if d["name"] != dev]
            self.cluster.update(RESOURCESLICES, sl)
            self.dead_chips.setdefault(node, {})[dev] = saved
            self.report.health_events += 1
            return

    def _op_restore_chip(self) -> None:
        from tpu_dra.k8s import RESOURCESLICES
        nodes = [n for n in sorted(self.dead_chips)
                 if n in self._nodes_alive() and self.dead_chips[n]]
        if not nodes:
            return
        node = self.rng.choice(nodes)
        dev = self.rng.choice(sorted(self.dead_chips[node]))
        saved = self.dead_chips[node].pop(dev)
        for sl in self.cluster.list(RESOURCESLICES):
            if (sl.get("spec") or {}).get("nodeName") != node:
                continue
            sl["spec"]["devices"] = sorted(
                sl["spec"].get("devices", []) + [saved],
                key=lambda d: d["name"])
            self.cluster.update(RESOURCESLICES, sl)
            return

    def _ops(self):
        return super()._ops() + [
            (self._op_kill_node, 2), (self._op_revive_node, 1),
            (self._op_quarantine_chip, 2), (self._op_restore_chip, 1)]

    # -- convergence ---------------------------------------------------------

    def _placeable(self, n_chips: int, published: Dict[str, set],
                   alive: set) -> bool:
        """Can a contiguous n-chip cuboid be placed on ANY live node's
        free coordinates (claims of LIVE pods taken; dead pods' claims
        drain via GC)? The same proof _prune_wedged runs — here it
        decides whether Pending-with-reason is legitimate."""
        from tpu_dra import topology
        from tpu_dra.k8s import RESOURCESLICES
        from tpu_dra.simcluster.scheduler import (
            _parent_of, claim_entries,
        )

        pods = {p["metadata"]["name"]
                for p in self.cluster.list(PODS, namespace="default")}
        taken: Dict[str, set] = {}
        for claim in self.cluster.list(RESOURCECLAIMS,
                                       namespace="default"):
            owner = (claim["metadata"].get("annotations") or {}).get(
                "sim/owner-pod")
            if owner and owner not in pods:
                continue  # GC will free these
            for _drv, pool, dev in claim_entries(claim):
                taken.setdefault(pool, set()).add(_parent_of(dev))
        for sl in self.cluster.list(RESOURCESLICES):
            node = (sl.get("spec") or {}).get("nodeName")
            if node not in alive:
                continue
            topo = topology.node_topology_from_slices([sl])
            if topo is None:
                continue
            free = {c for dev, c in topo.coord_of.items()
                    if dev not in taken.get(node, set())}
            if topology.best_placement(topo.mesh, free, n_chips) \
                    is not None:
                return True
        return False

    def _converged(self) -> List[str]:
        from tpu_dra.simcluster.scheduler import claim_entries

        problems = []
        pods = {p["metadata"]["name"]: p
                for p in self.cluster.list(PODS, namespace="default")}
        claims = self.cluster.list(RESOURCECLAIMS, namespace="default")
        published = self._published()
        alive = self._nodes_alive()
        by_owner = {}
        for claim in claims:
            owner = (claim["metadata"].get("annotations") or {}).get(
                "sim/owner-pod")
            if owner:
                by_owner[owner] = claim
        for name in sorted(self.live):
            pod = pods.get(name)
            if pod is None:
                problems.append(f"live pod {name} missing from cluster")
                continue
            claim = by_owner.get(name)
            node = pod["spec"].get("nodeName")
            entries = claim_entries(claim) if claim else ()
            if node:
                if not entries:
                    problems.append(f"bound pod {name} claim unallocated")
                    continue
                if {e[1] for e in entries} != {node}:
                    problems.append(
                        f"pod {name} bound to {node} but claim on "
                        f"{sorted({e[1] for e in entries})}")
                if node not in alive:
                    problems.append(f"pod {name} bound to DEAD node "
                                    f"{node} (eviction missing)")
                dead = [e[2] for e in entries
                        if e[2] not in published.get(node, set())]
                if dead:
                    problems.append(
                        f"claim of pod {name} allocated to dead/"
                        f"quarantined devices {dead} on {node}")
            else:
                if entries:
                    # Mid-eviction or mid-bind: not converged yet.
                    problems.append(f"unbound pod {name} still holds an "
                                    "allocation")
                    continue
                if self._placeable(self.pod_chips.get(name, 1),
                                   published, alive):
                    problems.append(f"pod {name} placeable on surviving "
                                    "capacity but still pending")
                    continue
                cond = next(
                    (c for c in (pod.get("status") or {}).get(
                        "conditions") or []
                     if c.get("type") == "PodScheduled"), None)
                if not (cond and cond.get("status") == "False"
                        and cond.get("reason")):
                    problems.append(f"pod {name} pending WITHOUT a "
                                    "recorded reason")
        alive_pods = set(self.live)
        for claim in claims:
            owner = (claim["metadata"].get("annotations") or {}).get(
                "sim/owner-pod")
            if owner and owner not in alive_pods:
                problems.append(f"claim {claim['metadata']['name']} "
                                f"leaked after pod {owner} death")
        if self.sched._index.dirty:
            problems.append("index dirty (resync pending)")
        else:
            problems.extend(self.sched.verify_index())
        return problems

    def quiesce_and_verify(self) -> None:
        # The base quiesce polls _converged (ours) then asserts
        # chip_conflicts/index/witness + topology/mesh invariants; on
        # top, the failure-domain hard invariant: NO allocated claim —
        # any claim, owned or not — references a dead node or an
        # unpublished device.
        super().quiesce_and_verify()
        from tpu_dra.simcluster.scheduler import claim_entries
        published = self._published()
        alive = self._nodes_alive()
        for claim in self.cluster.list(RESOURCECLAIMS,
                                       namespace="default"):
            for _drv, pool, dev in claim_entries(claim):
                if pool not in alive:
                    self.report.violations.append(
                        f"claim {claim['metadata']['name']} allocated "
                        f"on dead node {pool} at quiesce")
                elif dev not in published.get(pool, set()):
                    self.report.violations.append(
                        f"claim {claim['metadata']['name']} bound to "
                        f"unpublished device {dev} on {pool} at quiesce")


def run_nodedeath_schedule(seed: int, n_events: int = 60) -> ChaosReport:
    """One seeded node-death-racing-churn walk to quiesce."""
    return NodeDeathChaosHarness(seed).run(n_events)


def run_nodedeath_matrix(seeds: List[int], n_events: int = 60) -> Dict:
    return _pod_matrix_summary(
        [run_nodedeath_schedule(seed, n_events) for seed in seeds])


def run_topo_matrix(seeds: List[int], n_events: int = 60) -> Dict:
    return _pod_matrix_summary(
        [run_topo_schedule(seed, n_events) for seed in seeds])


def _pod_matrix_summary(reports: List[ChaosReport]) -> Dict:
    """Aggregate a pod-churn walk matrix (scheduler + topology walks
    share this shape: prepares/unprepares count pod lifecycles)."""
    injected: Dict[str, int] = {}
    for r in reports:
        for site, n in r.injected.items():
            injected[site] = injected.get(site, 0) + n
    return {
        "schedules": len(reports),
        "events": sum(r.events for r in reports),
        "pod_creates": sum(r.prepares for r in reports),
        "pod_deletes": sum(r.unprepares for r in reports),
        "injected": injected,
        "violations": [f"seed {r.seed}: {msg}"
                       for r in reports for msg in r.violations],
    }


def run_sched_matrix(seeds: List[int], n_events: int = 60) -> Dict:
    return _pod_matrix_summary(
        [run_sched_schedule(seed, n_events) for seed in seeds])


# ---------------------------------------------------------------------------
# Dropped-watch + API-flake scenario
# ---------------------------------------------------------------------------

def run_watch_flake_scenario(seed: int = 0, n_objects: int = 30,
                             timeout: float = 10.0) -> List[str]:
    """An informer over the retrying client while the watch stream keeps
    dying and API requests flake: after faults clear, the cache must
    match cluster truth with NO manual relist — the resilient watch's
    RV-resume and the informer's 410-relist path do all the recovery.
    Returns violations (empty = recovered)."""
    violations: List[str] = []
    rng = random.Random(seed)
    cluster = FakeCluster()
    cluster.EVENT_LOG_CAP = 16  # tight history: dropped resumes hit 410s
    client = RetryingApiClient(cluster, max_attempts=4, base_delay=0.001,
                               max_delay=0.01,
                               rng=random.Random(seed ^ 0xF1A3))
    inf = Informer(client, PODS, namespace="default")
    inf.RELIST_BACKOFF_BASE = 0.01  # keep the chaos tier fast
    live: set = set()
    with FAULTS.armed("k8s.watch.drop", Probabilistic(0.2, rng)), \
         FAULTS.armed("k8s.api.request",
                      Probabilistic(0.25, random.Random(seed + 7))):
        inf.start()
        inf.wait_for_sync(timeout)
        for i in range(n_objects):
            name = f"p-{i}"
            cluster.create(PODS, {"apiVersion": "v1", "kind": "Pod",
                                  "metadata": {"name": name,
                                               "namespace": "default"}})
            live.add(name)
            if live and rng.random() < 0.3:
                victim = rng.choice(sorted(live))
                cluster.delete(PODS, victim, "default")
                live.discard(victim)
    # Quiesce (context managers disarmed the sites): cache must converge.
    try:
        deadline = time.monotonic() + timeout
        truth = {o["metadata"]["name"]
                 for o in cluster.list(PODS, namespace="default")}
        assert truth == live
        while time.monotonic() < deadline:
            cached = {o["metadata"]["name"] for o in inf.lister.list()}
            if cached == truth:
                break
            time.sleep(0.02)
        else:
            cached = {o["metadata"]["name"] for o in inf.lister.list()}
            violations.append(
                f"informer cache did not converge: cached-truth="
                f"{sorted(cached - truth)} truth-cached="
                f"{sorted(truth - cached)}")
    finally:
        inf.stop()
    return violations


# ---------------------------------------------------------------------------
# Crash-recovery latency probe (bench.py chaos_recovery_p50_ms)
# ---------------------------------------------------------------------------

def measure_daemon_crash_recovery(n: int = 7, seed: int = 1234) -> Dict:
    """Median wall ms from an injected plugin-daemon crash to the
    affected claim prepared (ready) again: unclean teardown, full stack
    rebuild from disk (checkpoint load + orphan GC + standard CDI spec +
    DRA server + initial publish), then the idempotent re-prepare that
    hands kubelet the claim's devices back."""
    h = ChaosHarness(seed)
    samples: List[float] = []
    try:
        obj = h.make_claim(list(range(h.n_chips)))
        err = h.attempt_prepare(obj)
        if err is not None:
            raise RuntimeError(f"baseline prepare failed: {err}")
        for _ in range(n):
            t0 = time.perf_counter()
            h.crash_restart()
            err = h.attempt_prepare(obj)
            if err is not None:
                raise RuntimeError(f"post-crash prepare failed: {err}")
            samples.append((time.perf_counter() - t0) * 1e3)
    finally:
        h.close()
    samples.sort()
    return {
        "chaos_recovery_p50_ms": round(statistics.median(samples), 3),
        "chaos_recovery_p95_ms": round(
            samples[int(0.95 * (len(samples) - 1))], 3),
        "chaos_recovery_crashes": len(samples),
    }


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="seeded chaos schedule matrix (hack/chaos.sh)")
    ap.add_argument("--seeds", type=int, default=25,
                    help="number of schedules")
    ap.add_argument("--seed-start", type=int, default=0)
    ap.add_argument("--events", type=int, default=40,
                    help="lifecycle events per schedule")
    args = ap.parse_args(argv)

    seeds = list(range(args.seed_start, args.seed_start + args.seeds))
    summary = run_matrix(seeds, n_events=args.events)
    summary["watch_flake_violations"] = run_watch_flake_scenario(
        seed=args.seed_start)
    # Scheduler-churn walk over the same seed matrix: the event-driven
    # control plane (informers + incremental allocation index + guarded
    # resync) under the sched.* fault sites.
    summary["scheduler"] = run_sched_matrix(seeds, n_events=args.events)
    # Topology walk over the same seed matrix: contiguity + free-set
    # invariants with the TopologyAwareScheduling gate on.
    summary["topology"] = run_topo_matrix(seeds, n_events=args.events)
    # Node-death walk over the same seed matrix (SURVEY §18): node loss
    # and chip quarantine racing pod churn — eviction must converge
    # (Allocated-on-live-chips or Pending-with-reason, no claim pinned
    # to dead hardware, no double allocation).
    summary["node_death"] = run_nodedeath_matrix(seeds,
                                                 n_events=args.events)
    # HA leader-kill walk over the same seed matrix (SURVEY §22):
    # leader kills racing pod churn and eviction, standby takeover via
    # lease expiry + fenced resync — never two acting leaders' commits
    # both landing, no double allocation, no claim leaked across
    # takeover.
    summary["leader_kill"] = run_leaderkill_matrix(seeds,
                                                   n_events=args.events)
    failed = bool(summary["violations"]
                  or summary["watch_flake_violations"]
                  or summary["scheduler"]["violations"]
                  or summary["topology"]["violations"]
                  or summary["node_death"]["violations"]
                  or summary["leader_kill"]["violations"])
    if failed:
        # Any matrix violation ships its evidence (SURVEY §19): the
        # flight recorder holds the recent spans, fault firings and
        # queue events around whatever went wrong. hack/chaos.sh pins
        # the path via TPU_DRA_FLIGHTREC_DUMP so failed seeds leave an
        # artifact next to the logs.
        summary["flight_recorder_dump"] = trace.dump_flight_recorder(
            "chaos-violation",
            path=os.environ.get("TPU_DRA_FLIGHTREC_DUMP"))
    print(json.dumps(summary, indent=2))
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
