"""DRA scheduler sim: claims-from-templates, device allocation, binding.

Stands in for the upstream kube-scheduler's DRA plugin + the
kube-controller-manager's resourceclaim controller (neither is driver
code — SURVEY §1: "there is no scheduler code to rebuild"). Allocation
follows the real algorithm's observable behavior: DeviceClass CEL
selectors are matched against device attributes published in
ResourceSlices, devices already referenced by any allocated claim are
excluded, and the pod binds to a node that can satisfy every claim.

Two drive modes (SURVEY §10):

- **event mode** (``start()``) — the production shape, mirroring the
  reference's informer/workqueue controllers: informers watch Pods /
  ResourceClaims / ResourceSlices / DeviceClasses / Nodes, only dirty
  pods are enqueued, and the allocated-device set lives in an
  **incremental AllocationIndex** maintained from claim watch events
  (plus the scheduler's own writes, mutation-cache style) instead of
  being recomputed from a full claim list per attempt. Claim GC runs
  from pod-delete events with a low-frequency sweep as the safety net.
  Steady state performs ZERO full relists (metrics:
  ``tpu_dra_sched_full_relists``); the index falls back to a guarded
  full resync only when an event is known-dropped or an index apply
  fails (fault sites ``sched.watch_event`` / ``sched.index_apply``).

- **sync mode** (``reconcile_once()`` on an unstarted scheduler, or
  ``start(mode="poll")``) — the poll-and-scan path kept for unit tests
  and as the ultimate fallback: full-lists Pods and ResourceClaims and
  rebuilds a transient index per pass. Every pass counts as a full
  relist.

**Parallel scheduler core** (SURVEY §15): event mode runs a
multi-worker WorkQueue pool (per-key serialization: two items sharing
a key — ``pod/<ns/name>``, ``gc/<ns/name>``, ``resync`` — never run
concurrently), the ``AllocationIndex`` is sharded by node pool
(per-shard locks, RV high-water marks and dirty flags), and candidate
scans read an immutable per-attempt :class:`PoolView` snapshot instead
of hitting the index lock per device. Allocation commits optimistically:
``try_commit`` reserves the picked devices all-or-nothing under the one
shard lock (a conflict — another worker took a device, or the
``sched.snapshot_commit`` fault — re-scans against a fresh snapshot,
bounded before backoff-requeue; ``tpu_dra_sched_snapshot_conflicts_total``
counts them), the claim statuses are written, and the reservation is
released once the real allocation is applied mutation-cache style.

CEL selector evaluation is compile-cached (simcluster.cel): expressions
parse once per distinct source string; allocation evaluates the cached
AST per candidate device. Per-DeviceClass selector sources are
additionally cached keyed by the class's resourceVersion.
"""

from __future__ import annotations

import itertools
import logging
import os
import sys
import threading
import time
import zlib
from collections import deque
from typing import Dict, Iterable, List, Optional, Set, Tuple

from tpu_dra.infra import featuregates
from tpu_dra.infra.faults import FAULTS, FaultInjected
from tpu_dra.infra.metrics import (
    SCHED_CLAIMS_GCED, SCHED_EVICTIONS, SCHED_FULL_RELISTS,
    SCHED_PODS_BOUND, SCHED_SHARD_RESYNCS, SCHED_SNAPSHOT_CONFLICTS,
    SCHED_WATCH_EVENTS, SCHED_WORKERS, TOPO_ALLOCS, TOPO_FREE_CUBOID,
    TOPO_SCORE_SECONDS, Timer,
)
from tpu_dra.infra.leaderelect import FENCING_ANNOTATION
from tpu_dra.infra.trace import TRACEPARENT_ANNOTATION, TRACER
from tpu_dra.infra.workqueue import (
    ExponentialFailureRateLimiter, WorkQueue,
)
from tpu_dra.k8s.client import (
    AlreadyExistsError, ApiClient, ConflictError, NotFoundError,
    json_deepcopy,
)
from tpu_dra.k8s.informer import Informer
from tpu_dra.k8s.resources import (
    DEVICECLASSES, NODES, PODS, RESOURCECLAIMS, RESOURCECLAIMTEMPLATES,
    RESOURCESLICES,
)
from tpu_dra.simcluster import cel
from tpu_dra import topology

log = logging.getLogger("simcluster.scheduler")

# sys.setswitchinterval is interpreter-global: refcount the raise so
# overlapping Scheduler lifecycles in one process can neither revert
# the interval under a still-running sibling nor leak the raised value
# past the last stop() (see Scheduler.start for the why).
_switch_lock = threading.Lock()
_switch_refs = 0
_switch_saved = 0.0


def _raise_switch_interval() -> None:
    global _switch_refs, _switch_saved
    with _switch_lock:
        _switch_refs += 1
        if _switch_refs == 1:
            _switch_saved = sys.getswitchinterval()
            sys.setswitchinterval(max(
                _switch_saved,
                float(os.environ.get(
                    "TPU_DRA_SCHED_SWITCH_INTERVAL", "0.02"))))


def _restore_switch_interval() -> None:
    global _switch_refs
    with _switch_lock:
        _switch_refs -= 1
        if _switch_refs == 0:
            sys.setswitchinterval(_switch_saved)

_Entry = Tuple[str, str, str]  # (driver, pool, device)


def _parent_of(device: str) -> str:
    """Subslice devices ('chip-N-ss...') partition their parent chip
    ('chip-N'); everything else is its own parent."""
    return device.split("-ss")[0] if "-ss" in device else device


def _expand(entries: Iterable[_Entry]) -> List[_Entry]:
    """Allocation entries plus their partition-semantics block markers
    (the DRA partitionable-device counter analog): a whole-chip
    allocation blocks its subslices (marker '<chip>-ss*') and a subslice
    blocks the whole chip (marker = parent name), while two different
    subslices of one chip can coexist (MIG-style)."""
    out: List[_Entry] = []
    for driver, pool, name in entries:
        out.append((driver, pool, name))
        parent = _parent_of(name)
        out.append((driver, pool, parent) if parent != name
                   else (driver, pool, f"{name}-ss*"))
    return out


def claim_key(obj: Dict) -> str:
    meta = obj.get("metadata", {})
    return f"{meta.get('namespace', 'default')}/{meta['name']}"


def claim_entries(claim: Dict) -> Tuple[_Entry, ...]:
    """The (driver, pool, device) results of a claim's allocation
    (empty when unallocated)."""
    alloc = (claim.get("status") or {}).get("allocation") or {}
    return tuple(
        (r.get("driver", ""), r.get("pool", ""), r.get("device", ""))
        for r in (alloc.get("devices") or {}).get("results") or [])


def _taken_in(taken, driver: str, pool: str, name: str) -> bool:
    """The partition-aware membership test every allocated-set reader
    shares (live shard maps, reservation maps, snapshots, overlays):
    the exact entry, or — for a subslice — its parent chip's whole-chip
    marker. `taken` is any container of _Entry keys."""
    if (driver, pool, name) in taken:
        return True
    parent = _parent_of(name)
    if parent != name and (driver, pool, f"{parent}-ss*") in taken:
        return True  # parent chip wholly claimed
    return False


class PoolView:
    """Immutable allocated-set snapshot for ONE pool, built per
    scheduling attempt (``AllocationIndex.snapshot``): candidate scans
    read it lock-free instead of taking the shard lock per device. The
    scan's picks are validated by the optimistic ``try_commit`` — the
    view may go stale the instant it is built; stale picks surface as
    commit conflicts, never as double allocations."""

    __slots__ = ("pool", "taken", "mutations")

    def __init__(self, pool: str, taken: frozenset, mutations: int):
        self.pool = pool
        self.taken = taken
        self.mutations = mutations  # shard generation at snapshot time

    def is_taken(self, driver: str, name: str,
                 overlay: Optional[Set[_Entry]] = None) -> bool:
        if _taken_in(self.taken, driver, self.pool, name):
            return True
        return bool(overlay) and _taken_in(overlay, driver, self.pool, name)


class _IndexShard:
    """One pool-hash shard of the AllocationIndex: its own lock, claim
    map, refcounted taken set (keyed pool → entry → count), per-claim
    RV high-water marks, mutation generation, reservation overlay and
    dirty flag. All ``*_locked`` methods run under ``self._lock``."""

    RV_RETENTION = 4096  # evicted-claim watermarks kept (FIFO)

    def __init__(self):
        self._lock = threading.Lock()
        self._by_claim: Dict[str, Tuple[_Entry, ...]] = {}
        self._taken: Dict[str, Dict[_Entry, int]] = {}  # pool -> counts
        self._nreal: Dict[str, int] = {}  # pool -> live device results
        # Per-claim resourceVersion high-water mark: the scheduler
        # applies its OWN writes synchronously (mutation-cache style),
        # so the watch event for an EARLIER state of the same claim can
        # arrive afterwards on the informer thread — applying it would
        # roll the allocation back and let another pod double-allocate
        # the device. Numeric-RV monotonicity guards every apply/remove.
        self._rv: Dict[str, int] = {}
        # FIFO of keys whose allocation is gone but whose watermark is
        # retained (anti-resurrection for in-flight stale events). The
        # steady state is designed to NEVER resync, so without eviction
        # one watermark per claim-ever-seen would leak; beyond the
        # horizon a stale event for the claim can no longer be in
        # flight, so the oldest watermarks are safe to drop.
        self._removed: "deque[str]" = deque()
        # Bumped on every EFFECTIVE mutation: lets a resync detect that
        # an informer-thread apply/remove landed between its lister
        # snapshot and its swap (which would otherwise be silently
        # resurrected by the wholesale replace), and stamps PoolView
        # snapshots.
        self._mutations = 0
        # In-flight optimistic commits: claim key -> (pool, entries).
        # Reservations hold picked devices between try_commit and the
        # post-write apply; they are NOT part of _by_claim (a stale
        # watch replay must not be able to evict one) and resyncs
        # preserve them (cluster truth does not know them yet).
        self._reserved: Dict[str, Tuple[str, Tuple[_Entry, ...]]] = {}
        self._reserved_taken: Dict[str, Dict[_Entry, int]] = {}
        self.dirty = False
        self.dirty_reason = ""
        # True between begin_resync clearing the dirty flag and the
        # rebuilt state swapping in: the shard is KNOWN-divergent but no
        # longer flagged, so optimistic commits must keep refusing it
        # (a missed-allocation divergence makes the index vouch for a
        # taken device as free — try_commit's live re-validation checks
        # the index itself, which is exactly what cannot be trusted
        # here). Scans stay lock-free and unblocked; only the commit
        # step conflicts, bounded by the caller's requeue discipline.
        self.resyncing = False

    # -- refcounted taken bookkeeping (callers hold self._lock) -------------

    def _bump_locked(self, table: Dict[str, Dict[_Entry, int]],
                     expanded: List[_Entry], delta: int) -> None:
        for e in expanded:
            counts = table.setdefault(e[1], {})
            n = counts.get(e, 0) + delta
            if n > 0:
                counts[e] = n
            else:
                counts.pop(e, None)
                if not counts:
                    table.pop(e[1], None)

    def _set_entries_locked(self, key: str,
                            old: Optional[Tuple[_Entry, ...]],
                            new: Tuple[_Entry, ...]) -> None:
        self._mutations += 1
        if old:
            self._bump_locked(self._taken, _expand(old), -1)
            for e in old:
                self._nreal[e[1]] = self._nreal.get(e[1], 1) - 1
        if new:
            self._bump_locked(self._taken, _expand(new), +1)
            for e in new:
                self._nreal[e[1]] = self._nreal.get(e[1], 0) + 1
            self._by_claim[key] = new
        elif old is not None:
            self._by_claim.pop(key, None)

    def _note_removed_locked(self, key: str) -> List[str]:
        """Returns watermark keys evicted past the retention horizon
        (the caller drops their routing homes outside this lock)."""
        evicted: List[str] = []
        self._removed.append(key)
        while len(self._removed) > self.RV_RETENTION:
            old = self._removed.popleft()
            if old not in self._by_claim:  # not re-created since
                self._rv.pop(old, None)
                evicted.append(old)
        return evicted

    def _stale_locked(self, key: str, rv: Optional[int]) -> bool:
        if rv is None:
            return False
        if rv < self._rv.get(key, 0):
            return True
        self._rv[key] = rv
        return False

    def mark_dirty(self, reason: str) -> None:
        with self._lock:
            self.dirty = True
            self.dirty_reason = reason


class AllocationIndex:
    """Incremental allocated-device index, maintained from ResourceClaim
    add/update/delete events instead of re-listing all claims per
    scheduling attempt — **sharded by node pool** (SURVEY §15): entries
    route to ``crc32(pool) % n_shards``, each shard with its own lock,
    RV high-water marks, mutation generation and dirty flag, so a
    resync on one shard never blocks scans or applies on another.

    Holds only extracted string tuples (never references to cache
    objects), refcounted so that two subslice claims on one chip keep
    the parent-chip block marker alive until BOTH release. ``apply`` is
    idempotent per claim key (replace semantics), which makes informer
    relists — which re-dispatch adds for every object — safe to feed
    straight in.

    A claim's entries all live on one pool (allocation is per-node), so
    one claim maps to one shard; ``_homes`` remembers the routing for
    entry-less applies/removes (deallocations, deletes) whose pool is
    no longer derivable from the claim body. ``dirty`` (per shard)
    flags a known divergence (a dropped watch event, a failed apply):
    allocation must not proceed until the dirty shards are rebuilt from
    a full claim listing (the guarded fallback)."""

    def __init__(self, n_shards: int = 8):
        self._n_shards = max(1, int(n_shards))
        self._shards = [_IndexShard() for _ in range(self._n_shards)]
        # claim key -> pool, for routing entry-less mutations.
        # Deliberately UNLOCKED: every access is a single CPython dict
        # op (get/set/pop/C-level copy/update), each atomic under the
        # GIL, and no invariant spans two of them — a lock here sat on
        # the hot path of every apply/remove from every worker AND the
        # informer thread, and measured as a top convoy point.
        self._homes: Dict[str, str] = {}

    # ONE resourceVersion parse for both halves of the mutation-cache
    # discipline: the informer's STALE guard and this index's watermark
    # must agree on ordering or one layer accepts what the other rejects.
    _rv_int = staticmethod(Informer._rv_int)

    @property
    def n_shards(self) -> int:
        return self._n_shards

    def shard_of(self, pool: str) -> int:
        return zlib.crc32(pool.encode()) % self._n_shards

    # -- routing -------------------------------------------------------------

    def _drop_homes(self, keys: List[str], shard_id: int) -> None:
        """Drop routing for keys whose watermark was evicted from
        ``shard_id`` — but only while the recorded home still routes
        THERE. After a cross-pool move the claim lives in another
        shard; churn in the old shard must not delete the live claim's
        routing, or later entry-less deallocs/deletes become
        unroutable and leave phantom entries no resync ever flags."""
        for key in keys:
            pool = self._homes.get(key)
            if pool is not None and self.shard_of(pool) == shard_id:
                self._homes.pop(key, None)

    # -- mutation -----------------------------------------------------------

    def _checked_shard(self, key: str, pool: str) -> _IndexShard:
        """Consult the per-shard fault seam; a fired fault marks the
        target shard dirty (it is about to diverge from the event the
        caller drops) and raises for the caller's resync path."""
        shard = self._shards[self.shard_of(pool)]
        try:
            FAULTS.check("sched.shard_apply", claim=key, pool=pool)
        except FaultInjected:
            shard.mark_dirty("shard apply fault")
            raise
        return shard

    def apply(self, claim: Dict) -> None:
        """Add/replace one claim's allocation. Consults the
        ``sched.index_apply`` (pre-routing) and ``sched.shard_apply``
        (post-routing) fault sites — a raised fault leaves the shard
        UNCHANGED (the caller resyncs; shard_apply marks the shard
        dirty itself). Applies carrying an older resourceVersion than
        already indexed are ignored (see _IndexShard._rv).

        A claim whose allocation MOVED pools (deallocated out-of-band,
        re-allocated elsewhere) routes to the new pool's shard; the
        previous home's shard is purged of the leftover entries — but
        only AFTER the new shard accepted the apply, so a stale replay
        carrying the old pool can neither repoint the routing nor evict
        the live state."""
        key = claim_key(claim)
        FAULTS.check("sched.index_apply", claim=key)
        entries = claim_entries(claim)
        prev = self._homes.get(key)
        pool = entries[0][1] if entries else prev
        if pool is None:
            return  # never allocated: no entries, no watermark to guard
        shard = self._checked_shard(key, pool)
        rv = self._rv_int(claim)
        evicted: List[str] = []
        with shard._lock:
            if shard._stale_locked(key, rv):
                return
            old = shard._by_claim.get(key)
            if old != entries:
                shard._set_entries_locked(key, old, entries)
                if not entries and old is not None:
                    evicted = shard._note_removed_locked(key)
        # Accepted: commit the routing, then clean a cross-pool move's
        # leftovers out of the previous home's shard (same shard was
        # handled by the replace above). The purged key cannot drop its
        # own just-committed home: that home routes to the new shard,
        # which _drop_homes's shard check excludes.
        if entries:
            self._homes[key] = pool
            if prev is not None and self.shard_of(prev) != self.shard_of(pool):
                self._drop_homes(self._purge_shard(prev, key, rv),
                                 self.shard_of(prev))
        self._drop_homes(evicted, self.shard_of(pool))

    def _purge_shard(self, pool: str, key: str, rv: Optional[int],
                     force: bool = False) -> List[str]:
        """Drop `key`'s entries from `pool`'s shard (cross-pool move
        cleanup), guarded by that shard's OWN watermark: template claims
        reuse deterministic names, so a delayed DELETED replay from a
        deleted-and-recreated claim's prior incarnation routes here via
        its old body and must not evict the recreated claim's live
        allocation. ``force`` mirrors remove()'s own-delete semantics.
        Returns watermark keys evicted past retention."""
        shard = self._shards[self.shard_of(pool)]
        with shard._lock:
            if force:
                if rv:
                    shard._rv[key] = max(shard._rv.get(key, 0), rv)
            elif shard._stale_locked(key, rv):
                return []
            shard._mutations += 1  # watermark advance alone must also
            #   invalidate an in-flight resync snapshot
            old = shard._by_claim.get(key)
            if old is None:
                return []
            shard._set_entries_locked(key, old, ())
            return shard._note_removed_locked(key)

    def remove(self, claim: Dict, force: bool = False) -> None:
        """Drop a claim's allocation. ``force=True`` is for the
        scheduler mirroring its OWN client.delete (the delete's RV is
        unknowable — the verb returns nothing), so the staleness guard
        is bypassed and the high-water mark advanced to at least the
        deleted object's RV; single-writer discipline makes that safe."""
        key = claim_key(claim)
        FAULTS.check("sched.index_apply", claim=key)
        entries = claim_entries(claim)
        prev = self._homes.get(key)
        pool = entries[0][1] if entries else prev
        if pool is None:
            return
        shard = self._checked_shard(key, pool)
        rv = self._rv_int(claim)
        with shard._lock:
            if force:
                if rv:
                    shard._rv[key] = max(shard._rv.get(key, 0), rv)
            elif shard._stale_locked(key, rv):
                return
            shard._mutations += 1  # watermark advance alone must also
            #   invalidate an in-flight resync snapshot
            old = shard._by_claim.get(key)
            if old is not None:
                shard._set_entries_locked(key, old, ())
            evicted = shard._note_removed_locked(key)
        # A deleted claim is gone everywhere: if the event's entries and
        # the recorded home disagree on the shard (a cross-pool move
        # whose cleanup raced this delete), purge the home's shard too.
        if prev is not None and self.shard_of(prev) != self.shard_of(pool):
            self._drop_homes(self._purge_shard(prev, key, rv, force),
                             self.shard_of(prev))
        self._drop_homes(evicted, self.shard_of(pool))

    # -- optimistic snapshot commit (SURVEY §15) -----------------------------

    def snapshot(self, pool: str) -> PoolView:
        """Immutable allocated-set view of `pool` (live entries plus
        in-flight reservations) for one lock-free candidate scan."""
        shard = self._shards[self.shard_of(pool)]
        with shard._lock:
            taken = frozenset(shard._taken.get(pool, ())) | frozenset(
                shard._reserved_taken.get(pool, ()))
            return PoolView(pool, taken, shard._mutations)

    def try_commit(self, pool: str,
                   staged: List[Tuple[str, Tuple[_Entry, ...]]]
                   ) -> Optional[bool]:
        """Atomically reserve every staged (claim key, entries) pick on
        `pool`, all-or-nothing, re-validating each device against the
        LIVE shard state (the snapshot the picks came from may have
        gone stale). False = device-level conflict: a device is taken
        or reserved by another claim, the shard is dirty/mid-rebuild,
        or the ``sched.snapshot_commit`` fault fired — a re-scan
        against a fresh snapshot can win. None = CLAIM-level conflict
        (also falsy): a staged key another worker already committed
        DIFFERENT entries for, or holds an in-flight reservation on
        (two pods sharing one unallocated claim) — overwriting the
        live reservation would strand its devices' refcounts, and
        re-scanning cannot help because the caller's claim COPY is
        stale; only a re-fetch resolves it. Entries the shard already
        holds for the same key (an idempotent retry after a partial
        write) pass vacuously and are not re-reserved."""
        if FAULTS.fires("sched.snapshot_commit"):
            SCHED_SNAPSHOT_CONFLICTS.inc()
            return False
        shard = self._shards[self.shard_of(pool)]
        with shard._lock:
            if shard.dirty or shard.resyncing:
                # Known-divergent shard: the live re-validation below
                # would check the very state that cannot be trusted.
                # Refuse; the requeued attempt lands after the rebuild.
                SCHED_SNAPSHOT_CONFLICTS.inc()
                return False
            pending: Set[_Entry] = set()
            to_reserve: List[Tuple[str, Tuple[_Entry, ...]]] = []
            taken = shard._taken.get(pool, {})
            reserved = shard._reserved_taken.get(pool, {})
            for key, entries in staged:
                cur = shard._by_claim.get(key)
                if cur == entries:
                    continue  # already committed (idempotent retry)
                if cur is not None or key in shard._reserved:
                    # The claim is allocated to other devices, or a
                    # sibling worker's reservation is in flight: the
                    # caller's copy was stale.
                    SCHED_SNAPSHOT_CONFLICTS.inc()
                    return None
                for driver, _pool, name in entries:
                    if (_taken_in(taken, driver, pool, name)
                            or _taken_in(reserved, driver, pool, name)
                            or _taken_in(pending, driver, pool, name)):
                        SCHED_SNAPSHOT_CONFLICTS.inc()
                        return False
                pending.update(_expand(entries))
                to_reserve.append((key, entries))
            for key, entries in to_reserve:
                shard._reserved[key] = (pool, entries)
                shard._bump_locked(shard._reserved_taken,
                                   _expand(entries), +1)
        return True

    def release(self, pool: str, keys: Iterable[str]) -> None:
        """Drop the reservations `try_commit` took for `keys` — after
        the real allocations were applied (the entries now live in
        ``_by_claim``), or after the claim write failed (the devices
        return to the free set)."""
        shard = self._shards[self.shard_of(pool)]
        with shard._lock:
            for key in keys:
                held = shard._reserved.pop(key, None)
                if held is not None:
                    shard._bump_locked(shard._reserved_taken,
                                       _expand(held[1]), -1)

    def allocated_count(self, pool: str) -> int:
        """Live device results on `pool` (committed + reserved) — the
        busy-node skip: a candidate whose count already matches its
        published device count cannot fit anything, no scan needed."""
        shard = self._shards[self.shard_of(pool)]
        with shard._lock:
            n = shard._nreal.get(pool, 0)
            for key, (held_pool, entries) in shard._reserved.items():
                # A key already applied to _by_claim (the window between
                # _after_claim_write and the caller's release) is in
                # _nreal — counting its reservation too would double it
                # and make the busy-node skip pass over free capacity.
                if held_pool == pool and key not in shard._by_claim:
                    n += len(entries)
            return n

    # -- dirty flags + resync ------------------------------------------------

    @property
    def dirty(self) -> bool:
        return any(s.dirty for s in self._shards)

    @property
    def dirty_reason(self) -> str:
        for s in self._shards:
            if s.dirty and s.dirty_reason:
                return s.dirty_reason
        return ""

    def mark_all_dirty(self, reason: str) -> None:
        """A divergence that cannot be attributed to one shard (a
        dropped watch event for an unknown claim): every shard must
        resync before allocation proceeds."""
        for s in self._shards:
            s.mark_dirty(reason)

    def mark_shard_dirty(self, shard_id: int, reason: str) -> None:
        self._shards[shard_id].mark_dirty(reason)

    def dirty_shards(self) -> List[int]:
        return [i for i, s in enumerate(self._shards) if s.dirty]

    def begin_resync(self, shard_id: Optional[int] = None) -> None:
        """Clear the dirty flag(s) BEFORE the caller takes its claim
        snapshot: a concurrent mark_dirty whose dropped event postdates
        the snapshot then re-dirties the shard and its queued resync
        re-runs — clearing after the swap would clobber that mark and
        leave the shard divergent forever."""
        shards = (self._shards if shard_id is None
                  else [self._shards[shard_id]])
        for shard in shards:
            with shard._lock:
                shard.dirty = False
                shard.dirty_reason = ""
                # Commits stay refused until the rebuilt state swaps in
                # (cleared by _swap_shard; re-marking dirty also covers
                # the swap-refused tail — see _full_resync).
                shard.resyncing = True

    def mutation_count(self, shard_id: Optional[int] = None) -> int:
        if shard_id is not None:
            shard = self._shards[shard_id]
            with shard._lock:
                return shard._mutations
        total = 0
        for shard in self._shards:
            with shard._lock:
                total += shard._mutations
        return total

    def _shard_state_from(self, claims: Iterable[Dict],
                          shard_id: Optional[int]):
        """Fresh (by_claim, taken, nreal, rvs, homes) rebuilt from a
        claim listing — restricted to `shard_id` when given. Watermarks
        for entry-less claims route via the recorded home (a stale
        allocated event for them would route by its entries' pool, so
        the watermark must live in that same shard)."""
        by_claim: Dict[str, Tuple[_Entry, ...]] = {}
        taken: Dict[str, Dict[_Entry, int]] = {}
        nreal: Dict[str, int] = {}
        rvs: Dict[str, int] = {}
        homes: Dict[str, str] = {}
        old_homes = dict(self._homes)  # C-level copy: atomic under GIL
        for claim in claims:
            key = claim_key(claim)
            entries = claim_entries(claim)
            pool = entries[0][1] if entries else old_homes.get(key)
            if pool is None:
                continue  # never allocated: nothing to rebuild
            if shard_id is not None and self.shard_of(pool) != shard_id:
                continue
            homes[key] = pool
            rv = self._rv_int(claim)
            if rv:
                rvs[key] = rv
            if not entries:
                continue
            by_claim[key] = entries
            for e in entries:
                nreal[e[1]] = nreal.get(e[1], 0) + 1
            for e in _expand(entries):
                counts = taken.setdefault(e[1], {})
                counts[e] = counts.get(e, 0) + 1
        return by_claim, taken, nreal, rvs, homes

    def _swap_shard(self, shard_id: int, state,
                    only_if_mutations: Optional[int]) -> bool:
        shard = self._shards[shard_id]
        by_claim, taken, nreal, rvs, homes = state
        with shard._lock:
            if (only_if_mutations is not None
                    and shard._mutations != only_if_mutations):
                return False
            shard._by_claim = by_claim
            shard._taken = taken
            shard._nreal = nreal
            shard._rv = rvs
            shard._removed.clear()
            # The swap is itself a mutation: a CONCURRENT resync of the
            # same shard holding an older listing must see its
            # only_if_mutations guard trip rather than silently clobber
            # this fresher state.
            shard._mutations += 1
            shard.resyncing = False
        # Routing hygiene: the rebuild is the authoritative home set for
        # this shard. A key routing HERE but absent from the listing was
        # deleted during the divergence window — it never re-enters the
        # eviction FIFO (cleared above), so without this prune its
        # _homes entry leaks for the scheduler's lifetime. Re-read the
        # value at pop time: a concurrent apply may have just repointed
        # the key's routing to another shard (same discipline as
        # _drop_homes).
        for key, pool in list(self._homes.items()):
            if key in homes or self.shard_of(pool) != shard_id:
                continue
            if self._homes.get(key) is pool:
                self._homes.pop(key, None)
        self._homes.update(homes)
        return True

    def resync(self, claims: Iterable[Dict]) -> bool:
        """Rebuild EVERY shard from a full claim listing (sync mode /
        tests; call begin_resync first). Deliberately does NOT consult
        the fault sites: this IS the recovery path — an armed apply
        fault must not be able to starve it. Does NOT touch the dirty
        flags (see begin_resync). Reservations are preserved — cluster
        truth does not know in-flight commits yet."""
        listing = list(claims)
        for sid in range(len(self._shards)):
            self._swap_shard(sid, self._shard_state_from(listing, sid),
                             None)
        return True

    def resync_shard(self, shard_id: int, claims: Iterable[Dict],
                     only_if_mutations: Optional[int] = None) -> bool:
        """Rebuild ONE shard from a full claim listing (the guarded
        fallback's unit: sibling shards keep applying and scanning).

        only_if_mutations: the shard's mutation_count() read BEFORE the
        caller took its claim snapshot; the swap is refused (returns
        False) when a concurrent apply/remove landed in between —
        wholesale replacement would silently resurrect what that
        mutation changed (e.g. an out-of-band claim delete)."""
        return self._swap_shard(
            shard_id,
            self._shard_state_from(claims, shard_id), only_if_mutations)

    # -- queries ------------------------------------------------------------

    def allocated_claims(self) -> List[Tuple[str, Tuple[_Entry, ...]]]:
        """Snapshot of every indexed (claim key, entries) pair, shard by
        shard — the eviction scan's worklist. Each shard is read under
        its own lock; the union is NOT a cross-shard atomic snapshot,
        which the consumer tolerates (a claim mutating mid-scan is
        re-validated against the live lister before any eviction)."""
        out: List[Tuple[str, Tuple[_Entry, ...]]] = []
        for shard in self._shards:
            with shard._lock:
                out.extend(shard._by_claim.items())
        return out

    def is_taken(self, driver: str, pool: str, name: str,
                 overlay: Optional[Set[_Entry]] = None) -> bool:
        shard = self._shards[self.shard_of(pool)]
        with shard._lock:
            if _taken_in(shard._taken.get(pool, ()), driver, pool, name):
                return True
            if _taken_in(shard._reserved_taken.get(pool, ()),
                         driver, pool, name):
                return True
        return bool(overlay) and _taken_in(overlay, driver, pool, name)

    def entries_for(self, key: str) -> Tuple[_Entry, ...]:
        pool = self._homes.get(key)
        if pool is None:
            return ()
        shard = self._shards[self.shard_of(pool)]
        with shard._lock:
            return shard._by_claim.get(key, ())

    def owners_of_pool(self, pool: str) -> Set[str]:
        """Claim keys holding any device on `pool` (diagnostics)."""
        shard = self._shards[self.shard_of(pool)]
        with shard._lock:
            return {k for k, entries in shard._by_claim.items()
                    if any(e[1] == pool for e in entries)}

    def diff_against(self, claims: Iterable[Dict]) -> List[str]:
        """Divergences between the live index and a ground-truth claim
        listing (chaos invariant: after quiesce, empty) — checked PER
        SHARD (a claim indexed in the wrong shard is a divergence even
        if the global union looks right) and globally."""
        want_by_shard: Dict[int, Dict[str, Tuple[_Entry, ...]]] = {}
        for claim in claims:
            entries = claim_entries(claim)
            if entries:
                sid = self.shard_of(entries[0][1])
                want_by_shard.setdefault(sid, {})[claim_key(claim)] = entries
        out = []
        for sid, shard in enumerate(self._shards):
            with shard._lock:
                have = dict(shard._by_claim)
            want = want_by_shard.get(sid, {})
            for key in sorted(set(want) | set(have)):
                if want.get(key) != have.get(key):
                    out.append(f"shard {sid}: index[{key}]="
                               f"{have.get(key)} != truth {want.get(key)}")
        return out


class _Unscheduled(Exception):
    """Internal: transient condition (conflict, missing object) — let the
    workqueue retry with backoff."""


class Scheduler:
    """See module docstring. ``interval`` is the poll-mode cadence (and
    the legacy constructor signature); ``resync_interval`` is the
    event-mode safety-net cadence at which still-pending pods are
    re-nudged; ``gc_sweep_interval`` paces the low-frequency orphan-claim
    sweep backing the event-driven GC; ``workers`` sizes the event-mode
    reconcile pool (default ``TPU_DRA_SCHED_WORKERS`` or 4 — per-key
    serialization keeps same-pod/same-gc items exclusive, the snapshot
    commit step keeps cross-worker picks conflict-free)."""

    SYNC_TIMEOUT = 10.0
    # Fresh-snapshot re-scans after an optimistic commit conflict before
    # the pod item falls back to a backoff requeue.
    COMMIT_RETRIES = 4
    # Distinct nodeSelector keys cached in _cand_cache before stale-rev
    # entries are swept (per-pod-unique selectors would otherwise grow
    # the cache for the scheduler's lifetime).
    CAND_CACHE_MAX = 1024

    def __init__(self, client: ApiClient, interval: float = 0.15, *,
                 resync_interval: float = 2.0,
                 gc_sweep_interval: float = 10.0,
                 workers: Optional[int] = None,
                 index_shards: Optional[int] = None):
        self._client = client
        self._interval = interval
        self._resync_interval = resync_interval
        self._gc_sweep_interval = gc_sweep_interval
        self._workers = (workers if workers is not None else
                         int(os.environ.get("TPU_DRA_SCHED_WORKERS", "4")))
        self._index_shards = (index_shards if index_shards is not None else
                              int(os.environ.get(
                                  "TPU_DRA_SCHED_INDEX_SHARDS", "8")))
        self._stop = threading.Event()
        self._raised_switch = False
        self._thread: Optional[threading.Thread] = None
        self._queue: Optional[WorkQueue] = None
        self._pool: List[threading.Thread] = []
        self._sweeper: Optional[threading.Thread] = None
        self._informers: Dict[str, Informer] = {}
        self._index = AllocationIndex(n_shards=self._index_shards)
        self._pending: Set[str] = set()
        # Subset of _pending that FAILED to place for lack of capacity:
        # the capacity-event fast path re-drives only these. Queued or
        # in-flight pods run against current state anyway, and
        # re-enqueueing the whole pending set per capacity event was
        # the control plane's top write amplifier at churn scale (every
        # claim delete fanned out O(window) queue ops).
        self._waiting: Set[str] = set()
        # Pods fully placed by us: their own bind-event echo must not
        # re-enqueue a full reconcile pass (entries leave on pod delete,
        # so the set is bounded by live placed pods).
        self._done: Set[str] = set()
        self._plock = threading.Lock()
        # DeviceClass name -> (resourceVersion, selector sources): spares
        # re-extracting selector lists per allocation; the compiled
        # programs themselves are cached process-wide in simcluster.cel.
        # Shared by the pool workers: values are immutable tuples and
        # CPython dict item assignment is atomic, so concurrent writers
        # can at worst recompute the same value (benign).
        self._class_cache: Dict[str, Tuple[str, List[str]]] = {}
        # Node -> (slice (name, rv) fingerprint, NodeTopology|None): the
        # per-node fabric view extracted from published ResourceSlices,
        # rebuilt only when a slice's resourceVersion moves. Same
        # immutable-value sharing discipline as _class_cache.
        self._topo_cache: Dict[
            str, Tuple[tuple, Optional[topology.NodeTopology]]] = {}
        # Candidate-node cache: nodeSelector -> (node revision, sorted
        # names). Invalidated wholesale by bumping _nodes_rev from node
        # watch events — per-pod scans stop re-listing + re-sorting the
        # whole node inventory. The cached lists are shared read-only.
        self._cand_cache: Dict[tuple, Tuple[int, List[str]]] = {}
        self._nodes_rev = 0
        # Node -> (slice revision, published device count): the
        # busy-node skip's denominator (see _schedule).
        self._devcount_cache: Dict[str, Tuple[int, int]] = {}
        self._slices_rev = 0
        # Revision source for both caches: next() is atomic, so two
        # racing capacity events always land DISTINCT revisions — a
        # plain += 1 could lose one bump to a read-modify-write race
        # and leave a cache validated against the surviving value.
        self._rev_seq = itertools.count(1)
        self._started = False
        # HA mode (SURVEY §22): a standby replica runs warm informers
        # but leaves the worker pool paused until promote(); the
        # acting leader's fencing generation is stamped into every
        # claim-status/bind write (see _stamp_fence) and deliberately
        # survives deposal — install_fencing refuses the stale stamp.
        self._standby = False
        self._promote_lock = threading.Lock()
        self.lease_generation: Optional[int] = None

    # -- lifecycle ----------------------------------------------------------

    def start(self, mode: str = "events", standby: bool = False) -> None:
        """``standby=True`` (events mode only) brings up everything
        EXCEPT the reconcile workers and sweeper: informers sync and
        keep the index warm, events enqueue into the paused workqueue
        (per-key dedupe bounds it by live object count), and nothing
        writes to the cluster until promote() — the HA replica shape
        (SURVEY §22)."""
        self._stop.clear()  # both modes: a restart after stop() must run
        self._standby = standby and mode == "events"
        if mode == "poll":
            self._thread = threading.Thread(target=self._poll_run,
                                            daemon=True,
                                            name="sim-scheduler")
            self._thread.start()
            return
        # Fresh state for (re)start: informers begin with empty stores,
        # so nothing would ever dispatch deletes for claims that died
        # while the scheduler was stopped — a retained index would keep
        # their devices phantom-allocated forever.
        self._index = AllocationIndex(n_shards=self._index_shards)
        with self._plock:
            self._pending.clear()
            self._waiting.clear()
            self._done.clear()
        self._class_cache.clear()
        self._topo_cache.clear()
        self._cand_cache.clear()
        self._devcount_cache.clear()
        self._queue = WorkQueue(
            # No global token bucket: event enqueues are explicit-delay
            # (after=0) and failures back off per item; a bucket would
            # throttle churn-scale nudge fan-in for no protection (the
            # "apiserver" here is in-process or the fake).
            rate_limiter=ExponentialFailureRateLimiter(0.005, 2.0),
            log=lambda msg: log.debug("workqueue: %s", msg),
            name="sched")

        inf = {}
        for name, gvr in (("pods", PODS), ("claims", RESOURCECLAIMS),
                          ("slices", RESOURCESLICES),
                          ("classes", DEVICECLASSES), ("nodes", NODES)):
            if name == "claims":
                # The claims informer is PARTITIONED by allocation pool,
                # with the same crc32-shard function as AllocationIndex:
                # informer shard i feeds exactly index shard i, so claim
                # deltas of one node pool apply in order on one FIFO
                # while other pools' shards run free, and a shed delta
                # dirties precisely the index shard it would have fed.
                inf[name] = Informer(
                    self._client, gvr,
                    copy_on_read=False, copy_events=False,
                    partitions=self._index_shards,
                    partition_key=self._claim_pool,
                    shard_queue_cap=int(os.environ.get(
                        "TPU_DRA_SCHED_SHARD_QUEUE_CAP", "4096")),
                    on_shard_overflow=self._on_informer_shard_overflow)
            else:
                inf[name] = Informer(self._client, gvr,
                                     copy_on_read=False, copy_events=False)
        inf["claims"].add_indexer("owner", self._owner_index)
        inf["slices"].add_indexer("node", self._slice_node_index)

        inf["pods"].on_add(self._on_pod)
        inf["pods"].on_update(lambda old, new: self._on_pod(new))
        inf["pods"].on_delete(self._on_pod_deleted)
        inf["claims"].on_add(lambda obj: self._on_claim(None, obj))
        inf["claims"].on_update(self._on_claim)
        inf["claims"].on_delete(self._on_claim_deleted)
        for src in ("slices", "nodes"):
            inf[src].on_add(lambda obj, s=src: self._on_capacity(s))
            inf[src].on_update(lambda o, n, s=src: self._on_capacity(s))
            inf[src].on_delete(lambda obj, s=src: self._on_capacity(s))
        inf["classes"].on_add(lambda obj: self._on_class(obj))
        inf["classes"].on_update(lambda o, n: self._on_class(n))
        inf["classes"].on_delete(lambda obj: self._on_class(obj))

        self._informers = inf
        self._started = True
        # CPython GIL tuning for the lock-heavy event control plane:
        # the 5ms default switch interval preempts lock HOLDERS
        # mid-critical-section, convoying every waiter behind them
        # (measured: workers=4 churn throughput collapsed ~5x under
        # it). 20ms lets critical sections complete between forced
        # switches. Process-global by nature, so raise/restore is
        # refcounted module-wide: overlapping scheduler lifecycles
        # (tests, chaos harnesses) must not revert it under each other
        # or leak it past the last stop().
        _raise_switch_interval()
        self._raised_switch = True
        # The reconcile pool: N queue consumers with per-key
        # serialization (infra.workqueue); cross-worker allocation
        # safety comes from the snapshot commit step, not from here.
        # A standby leaves the pool paused — promote() starts it.
        if not self._standby:
            self._pool = self._queue.start_workers(self._workers,
                                                   self._stop)
            SCHED_WORKERS.set(self._workers)
        for i in inf.values():
            i.start()
        for i in inf.values():
            i.wait_for_sync(self.SYNC_TIMEOUT)
        # The initial claim listing flowed through _on_claim adds during
        # informer sync, so the index is already built; the nudge below
        # only covers pods whose add events raced the pending-set wiring.
        self._nudge_all_pending()
        if not self._standby:
            self._sweeper = threading.Thread(target=self._sweep_loop,
                                             daemon=True,
                                             name="sim-scheduler-sweep")
            self._sweeper.start()

    @property
    def is_standby(self) -> bool:
        return self._standby

    def set_lease_generation(self, generation: int) -> None:
        """Adopt the elector's fencing token: every subsequent
        claim-status/bind write carries it (never cleared — a deposed
        leader's stale stamp is exactly what fencing refuses)."""
        self.lease_generation = generation

    def promote(self) -> None:
        """Standby -> acting leader (the elector's on_started_leading).
        The informers are already warm; what takeover owes is DISTRUST:
        every shard of the AllocationIndex is marked dirty and rebuilt
        through the existing guarded _full_resync path before the
        worker pool starts committing — the old leader may have
        allocated right up to its deposal, and commits against a
        pre-takeover index are how devices double-allocate."""
        with self._promote_lock:
            if not self._standby or self._stop.is_set() \
                    or self._queue is None:
                return
            self._standby = False
        t0 = time.monotonic()
        try:
            # Injection site: the takeover rebuild itself fails —
            # promotion must re-drive the resync, never proceed dirty.
            FAULTS.check("sched.takeover_resync")
            self._index.mark_all_dirty("lease takeover")
            self._full_resync()
        except FaultInjected:
            # Declared degradation (sched.takeover_resync): the queued
            # resync item re-runs the rebuild; until it converges,
            # dirty shards refuse try_commit, so the promoted workers
            # degrade to bounded requeues rather than unsafe commits.
            self.request_resync("takeover resync faulted")
        self._pool = self._queue.start_workers(self._workers, self._stop)
        SCHED_WORKERS.set(self._workers)
        self._nudge_all_pending()
        self._sweeper = threading.Thread(target=self._sweep_loop,
                                         daemon=True,
                                         name="sim-scheduler-sweep")
        self._sweeper.start()
        log.info("promoted to acting leader in %.3fs (generation %s)",
                 time.monotonic() - t0, self.lease_generation)

    def stop(self) -> None:
        self._stop.set()
        for i in self._informers.values():
            i.stop()
        if self._queue is not None:
            self._queue.shutdown()
        for t in self._pool + [self._sweeper, self._thread]:
            if t is not None:
                t.join(timeout=5)
        self._pool = []
        if self._raised_switch:
            self._raised_switch = False
            _restore_switch_interval()
        self._started = False

    def _poll_run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.reconcile_once()
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("scheduler reconcile failed")

    # -- event handlers (watch threads: derive keys, enqueue, return) -------

    @staticmethod
    def _owner_index(obj: Dict) -> List[str]:
        owner = (obj.get("metadata", {}).get("annotations") or {}).get(
            "sim/owner-pod")
        if not owner:
            return []
        ns = obj["metadata"].get("namespace", "default")
        return [f"{ns}/{owner}"]

    @staticmethod
    def _slice_node_index(obj: Dict) -> List[str]:
        node = (obj.get("spec") or {}).get("nodeName")
        return [node] if node else []

    def _drop_event(self, resource: str) -> bool:
        """The sched.watch_event chaos seam: a fired site models the
        scheduler mishandling this event. The event is dropped BUT the
        index is marked dirty — the guard knows it dropped something, so
        the full-resync fallback takes over before the next allocation
        (that is what makes the fallback 'guarded')."""
        if FAULTS.fires("sched.watch_event"):
            self._mark_dirty(f"watch event dropped ({resource})")
            return True
        SCHED_WATCH_EVENTS.inc(labels={"resource": resource})
        return False

    def _on_pod(self, pod: Dict) -> None:
        if self._drop_event("pods"):
            return
        if pod["metadata"].get("deletionTimestamp"):
            return
        key = self._pod_key(pod)
        phase = (pod.get("status") or {}).get("phase", "Pending")
        if phase not in ("", "Pending"):
            self._forget_pod(key)
            return
        if pod["spec"].get("nodeName"):
            with self._plock:
                if key in self._done:
                    return  # our own bind/status echo: already placed
        self._enqueue_pod(key)

    def _on_pod_deleted(self, pod: Dict) -> None:
        if self._drop_event("pods"):
            return
        key = self._pod_key(pod)
        self._forget_pod(key)
        # Event-driven claim GC: the resourceclaim controller's ownerRef
        # analog, fired from the delete event instead of a 150ms
        # full-list poll; the periodic sweep stays as the safety net.
        self._queue.enqueue(key, self._gc_pod_claims, key=f"gc/{key}",
                            after=0, dedupe=True)

    def _on_claim(self, old: Optional[Dict], new: Dict) -> None:
        if self._drop_event("resourceclaims"):
            return
        try:
            self._index.apply(new)
        except FaultInjected as e:
            self._mark_dirty_from(e, "index apply failed")
            return
        if old is not None and claim_entries(old) and not claim_entries(new):
            self._nudge_pending_pods()  # deallocation freed devices

    @staticmethod
    def _claim_pool(claim: Dict) -> Optional[str]:
        """Partition key for the partitioned claims informer: the pool
        of the claim's allocation, i.e. exactly what AllocationIndex
        shards by — claim deltas ride the informer shard that feeds
        their index shard. Unallocated claims return None and fall back
        to the informer's name-hash routing (they carry no entries, so
        any shard is equally correct for them)."""
        entries = claim_entries(claim)
        return entries[0][1] if entries else None

    def _on_informer_shard_overflow(self, shard_id: int, reason: str) -> None:
        """Recovery hook for a shed claims-informer delta: the shard's
        slice of the allocation index missed an apply/remove, so mark
        exactly that index shard dirty (try_commit refuses dirty shards
        — no allocation can race the gap) and queue the guarded resync.
        If even this path faults (sched.informer_shard_relist), degrade
        to dirtying the whole index: over-resync is safe, a clean-
        looking shard that lost deltas is not."""
        why = f"informer shard {shard_id} overflow ({reason})"
        try:
            FAULTS.check("sched.informer_shard_relist", shard=shard_id)
            self._index.mark_shard_dirty(shard_id, why)
            self._enqueue_resync(why)
        except FaultInjected:
            self._mark_dirty(why)

    def _on_claim_deleted(self, claim: Dict) -> None:
        if self._drop_event("resourceclaims"):
            return
        try:
            self._index.remove(claim)
        except FaultInjected as e:
            self._mark_dirty_from(e, "index remove failed")
            return
        # A deleted claim may free devices — and if its owner pod is
        # still alive (out-of-band deletion), that pod needs re-driving
        # so its template claim is recreated.
        owner = (claim.get("metadata", {}).get("annotations") or {}).get(
            "sim/owner-pod")
        if owner:
            ns = claim["metadata"].get("namespace", "default")
            self._enqueue_pod(f"{ns}/{owner}")
        self._nudge_pending_pods()

    def _on_capacity(self, resource: str) -> None:
        # Cache invalidation happens even for DROPPED events: the drop
        # models the scheduler mishandling the event downstream, but a
        # candidate/devcount cache left stale here would outlive the
        # guarded resync that recovers everything else.
        if resource == "nodes":
            self._nodes_rev = next(self._rev_seq)
        else:
            self._slices_rev = next(self._rev_seq)
        if self._drop_event(resource):
            return
        self._nudge_pending_pods()
        # Failure-domain reaction (SURVEY §18): the same events that ADD
        # capacity also take it away — a node delete, or a ResourceSlice
        # shrinking because the driver quarantined/yanked a chip. The
        # keyed+deduped evict-scan item sweeps the allocation index for
        # claims whose devices no longer exist and releases them through
        # the real deallocation pipeline.
        if self._queue is not None:
            self._queue.enqueue(resource, lambda _o: self._evict_scan(),
                                key="evict", after=0, dedupe=True)

    def _on_class(self, dc: Dict) -> None:
        if self._drop_event("deviceclasses"):
            return
        self._class_cache.pop(dc["metadata"]["name"], None)
        self._nudge_pending_pods()

    # -- queue plumbing ------------------------------------------------------

    @staticmethod
    def _pod_key(pod: Dict) -> str:
        return (f"{pod['metadata'].get('namespace', 'default')}/"
                f"{pod['metadata']['name']}")

    def _enqueue_pod(self, key: str) -> None:
        with self._plock:
            self._pending.add(key)
            self._waiting.discard(key)  # the enqueue below covers it
            self._done.discard(key)
        self._queue.enqueue(key, self._process_pod, key=f"pod/{key}",
                            after=0, dedupe=True)

    def _forget_pod(self, key: str, done: bool = False) -> None:
        with self._plock:
            self._pending.discard(key)
            self._waiting.discard(key)
            if done:
                self._done.add(key)
            else:
                self._done.discard(key)

    def _nudge_pending_pods(self) -> None:
        """Capacity-event fast path: re-drive the pods a previous
        attempt could NOT place (see _waiting). dedupe=True collapses
        event-storm fan-in to one queued item per pod. A free landing
        while a pod's failing attempt is still mid-flight can slip past
        this (the pod joins _waiting only after the attempt returns) —
        the periodic sweep re-drives the whole pending set to close
        that window."""
        with self._plock:
            if not self._waiting:
                return
            keys = sorted(self._waiting)
            self._waiting.clear()
        for key in keys:
            self._queue.enqueue(key, self._process_pod, key=f"pod/{key}",
                                after=0, dedupe=True)

    def _nudge_all_pending(self) -> None:
        """The sweep's safety net: re-drive EVERY still-pending pod
        (the pre-§15 nudge semantics, now off the event fast path)."""
        with self._plock:
            keys = sorted(self._pending)
        for key in keys:
            self._queue.enqueue(key, self._process_pod, key=f"pod/{key}",
                                after=0, dedupe=True)

    def _mark_dirty(self, reason: str, *, attributed: bool = False) -> None:
        """attributed=True: the divergence already marked its OWN shard
        dirty (the sched.shard_apply seam does so before raising), so
        only the resync item needs queueing. Otherwise the divergence
        cannot be pinned to one shard — a dropped watch event for a
        claim whose pool we never saw — and every shard must rebuild."""
        if not attributed:
            self._index.mark_all_dirty(reason)
        self._enqueue_resync(reason)

    def _mark_dirty_from(self, e: FaultInjected, reason: str) -> None:
        """The FaultInjected catch sites' shared attribution rule:
        sched.shard_apply self-marks its shard (see _checked_shard);
        anything else cannot be pinned to one shard."""
        self._mark_dirty(reason, attributed=e.site == "sched.shard_apply")

    def _enqueue_resync(self, reason: str) -> None:
        if self._queue is not None:
            self._queue.enqueue(reason, lambda _: self._full_resync(),
                                key="resync", after=0, dedupe=True)

    def request_resync(self, reason: str = "requested") -> None:
        """Public seam (chaos op): force the guarded full-resync path."""
        self._mark_dirty(reason)

    def _full_resync(self) -> None:
        """The guarded fallback, per shard: rebuild every DIRTY shard of
        the allocation index from the informer caches (which self-heal
        via relist even when the SCHEDULER mishandled events) and
        re-drive everything pending. Clean shards are untouched — their
        scans and commits flow throughout the rebuild. Counted — the
        bench asserts steady state never comes here."""
        dirty = self._index.dirty_shards()
        if not dirty:
            return
        SCHED_FULL_RELISTS.inc()
        reason = self._index.dirty_reason
        # Clear-dirty BEFORE the snapshot: a drop landing after the
        # listing re-dirties the shard and its own queued resync
        # re-runs. `resyncing` stays set until the swap, so optimistic
        # commits keep refusing the shards meanwhile.
        for sid in dirty:
            self._index.begin_resync(sid)
        # ONE claim listing per retry round, shared by every dirty
        # shard (an unattributed divergence dirties all of them — at
        # fleet scale per-shard listings multiplied the recovery cost
        # by the shard count). The per-shard only_if_mutations guard
        # still reads each shard's generation before the listing.
        failed = list(dirty)
        for _ in range(8):
            gens = {sid: self._index.mutation_count(sid) for sid in failed}
            listing = self._list_claims()
            failed = [sid for sid in failed
                      if not self._index.resync_shard(
                          sid, listing, only_if_mutations=gens[sid])]
            if not failed:
                break
        SCHED_SHARD_RESYNCS.inc(len(dirty) - len(failed))
        if failed:
            # Concurrent mutations kept invalidating the snapshots
            # (effective handler-side changes are rare, so this is an
            # extreme tail): re-mark just those shards and retry through
            # the queue rather than spin.
            for sid in failed:
                self._index.mark_shard_dirty(
                    sid, "resync raced concurrent index mutations")
            self._enqueue_resync("resync raced concurrent index mutations")
            return
        with self._plock:
            self._pending.clear()
            self._waiting.clear()  # subset of _pending; a stale key here
            #   would spuriously re-drive a placed pod on capacity events
            self._done.clear()  # conservatively re-verify placed pods
        for pod in self._list_pods():
            if pod["metadata"].get("deletionTimestamp"):
                continue
            phase = (pod.get("status") or {}).get("phase", "Pending")
            if phase in ("", "Pending"):
                self._enqueue_pod(self._pod_key(pod))
        log.info("resync of shards %s completed (%s)", dirty, reason)

    def _sweep_loop(self) -> None:
        next_gc = time.monotonic() + self._gc_sweep_interval
        while not self._stop.wait(self._resync_interval):
            self._nudge_all_pending()
            if time.monotonic() >= next_gc:
                next_gc = time.monotonic() + self._gc_sweep_interval
                self._queue.enqueue(
                    "sweep", lambda _: self._gc_sweep(),
                    key="gc-sweep", after=0, dedupe=True)
                # Eviction safety net, same shape as the GC sweep: a
                # DROPPED capacity event (sched.watch_event) would
                # otherwise be the last trigger a dead chip's claims
                # ever get — the periodic sweep guarantees the evict
                # scan converges regardless.
                self._queue.enqueue(
                    "sweep", lambda _: self._evict_scan(),
                    key="evict", after=0, dedupe=True)

    # -- data access (lister-backed when started, client-backed sync) --------

    def _list_pods(self) -> List[Dict]:
        if self._started:
            return self._informers["pods"].lister.list()
        return self._client.list(PODS)

    def _list_claims(self) -> List[Dict]:
        if self._started:
            return self._informers["claims"].lister.list()
        return self._client.list(RESOURCECLAIMS)

    def _get_pod(self, ns: str, name: str) -> Optional[Dict]:
        if self._started:
            return self._informers["pods"].lister.get(name, ns)
        try:
            return self._client.get(PODS, name, ns)
        except NotFoundError:
            return None

    def _get_claim(self, ns: str, name: str) -> Optional[Dict]:
        if self._started:
            return self._informers["claims"].lister.get(name, ns)
        try:
            return self._client.get(RESOURCECLAIMS, name, ns)
        except NotFoundError:
            return None

    def _iter_nodes(self) -> List[Dict]:
        nodes = (self._informers["nodes"].lister.list() if self._started
                 else self._client.list(NODES))
        return sorted(nodes, key=lambda n: n["metadata"]["name"])

    def _slices_for_node(self, node: str) -> List[Dict]:
        if self._started:
            return self._informers["slices"].get_by_index("node", node)
        return [sl for sl in self._client.list(RESOURCESLICES)
                if (sl.get("spec") or {}).get("nodeName") == node]

    def _get_class(self, name: str) -> Optional[Dict]:
        if self._started:
            return self._informers["classes"].lister.get(name)
        try:
            return self._client.get(DEVICECLASSES, name)
        except NotFoundError:
            return None

    # -- sync mode -----------------------------------------------------------

    def reconcile_once(self) -> None:
        """One poll-and-scan pass (sync/poll mode): full-list Pods and
        ResourceClaims, rebuild a transient allocation index, GC orphans,
        drive every pending pod. Event mode makes this the exception —
        each call counts on tpu_dra_sched_full_relists."""
        SCHED_FULL_RELISTS.inc()
        pods = self._client.list(PODS)
        claims = self._client.list(RESOURCECLAIMS)
        gced = self._gc_orphan_claims(pods, claims, path="sweep")
        self._index.begin_resync()
        self._index.resync(c for c in claims if claim_key(c) not in gced)
        for pod in pods:
            if pod["metadata"].get("deletionTimestamp"):
                continue
            phase = (pod.get("status") or {}).get("phase", "Pending")
            if phase not in ("", "Pending"):
                continue
            try:
                pod = self._ensure_claims_from_templates(pod)
                self._schedule(pod)
            except (ConflictError, _Unscheduled):
                continue  # racing another write: next pass retries

    # -- claim GC -------------------------------------------------------------

    def _gc_pod_claims(self, key: str) -> None:
        """Event path: the pod named by `key` is gone; delete the claims
        it owns (owner index lookup, no listing)."""
        for claim in self._informers["claims"].get_by_index("owner", key):
            self._delete_claim(claim, path="event")

    def _gc_sweep(self) -> None:
        """Safety-net sweep over the informer caches (NOT an apiserver
        list): catches claims whose pod-delete event was missed."""
        self._gc_orphan_claims(self._list_pods(), self._list_claims(),
                               path="sweep")

    def _gc_orphan_claims(self, pods: List[Dict], claims: List[Dict],
                          path: str = "sweep") -> Set[str]:
        """The resourceclaim controller's ownerRef GC analog: a claim
        generated from a template dies with its pod — otherwise exclusive
        devices (channel-0, the daemon device) stay allocated forever and
        the next workload can never schedule. Returns the keys of the
        claims deleted (so a sync pass excludes them from its index)."""
        alive = {(p["metadata"].get("namespace", "default"),
                  p["metadata"]["name"]) for p in pods
                 if not p["metadata"].get("deletionTimestamp")}
        gced: Set[str] = set()
        for claim in claims:
            owner = (claim["metadata"].get("annotations") or {}).get(
                "sim/owner-pod")
            if not owner:
                continue
            ns = claim["metadata"].get("namespace", "default")
            if (ns, owner) not in alive:
                self._delete_claim(claim, path=path)
                gced.add(claim_key(claim))
        return gced

    def _delete_claim(self, claim: Dict, path: str) -> None:
        ns = claim["metadata"].get("namespace", "default")
        name = claim["metadata"]["name"]
        try:
            self._client.delete(RESOURCECLAIMS, name, ns)
        except NotFoundError:
            return
        # Mirror our own delete into the index synchronously (the write
        # half of the mutation-cache discipline): with creates, status
        # writes AND deletes all applied on the worker thread, the
        # informer-thread handlers only ever replay states the index has
        # already seen — so a full resync can never race a real mutation.
        try:
            self._index.remove(claim, force=True)
        except FaultInjected as e:
            self._mark_dirty_from(e, "index remove failed (own delete)")
        SCHED_CLAIMS_GCED.inc(labels={"path": path})
        log.info("GC claim %s/%s via %s (owner pod gone)", ns, name, path)

    # -- failure-domain eviction (worker thread, SURVEY §18) ------------------

    def _evict_scan(self) -> None:
        """Sweep the allocation index for claims whose allocated devices
        no longer exist — the node is gone, or the device vanished from
        the node's published ResourceSlices (chip quarantined/yanked by
        the driver's health pipeline) — and evict them through the REAL
        deallocation pipeline: a claim-status write (allocation removed,
        eviction reason recorded) mirrored via _after_claim_write, then
        the owner pod unbound and re-driven. The index is never edited
        directly: the write IS the eviction, exactly like GC's delete.

        Raises on a per-claim failure (sched.evict fault, write
        conflict): the keyed evict item retries with backoff and
        re-scans — eviction must converge, not half-apply."""
        nodes_alive = {n["metadata"]["name"] for n in self._iter_nodes()}
        published: Dict[str, Set[str]] = {}
        for key, entries in self._index.allocated_claims():
            reason = None
            for _driver, pool, dev in entries:
                if pool not in nodes_alive:
                    reason = "node_lost"
                    break
                devs = published.get(pool)
                if devs is None:
                    devs = {d["name"]
                            for sl in self._slices_for_node(pool)
                            for d in (sl.get("spec") or {}).get(
                                "devices") or []}
                    published[pool] = devs
                if dev not in devs:
                    reason = "device_lost"
                    break
            if reason is None:
                continue
            # Injection site: the eviction itself fails mid-flight — the
            # scan item must retry until the claim is released, never
            # leave it half-evicted or pinned to the dead chip.
            FAULTS.check("sched.evict", claim=key, reason=reason)
            self._evict_claim(key, entries, reason)
        # Healing pass: an eviction is two writes (claim deallocation,
        # pod unbind) and only the first is found by the index scan
        # above — if the unbind failed (write conflict) or the pod
        # re-bound against a claim the scan had not deallocated yet,
        # the owner is left bound to an evicted, unallocated claim and
        # NOTHING above would ever revisit it. Every scan therefore
        # re-enforces the second half: evicted + unallocated + owner
        # still bound -> unbind and re-drive. Idempotent and O(claims).
        for claim in self._list_claims():
            status = claim.get("status") or {}
            if status.get("allocation") or "evicted" not in status:
                continue
            owner = (claim["metadata"].get("annotations") or {}).get(
                "sim/owner-pod")
            if not owner:
                continue
            ns = claim["metadata"].get("namespace", "default")
            pod = self._get_pod(ns, owner)
            if pod is not None and pod["spec"].get("nodeName"):
                self._release_pod_binding(
                    f"{ns}/{owner}",
                    (status["evicted"] or {}).get("reason", "evicted"))

    def _evict_claim(self, key: str,
                     entries: Tuple[_Entry, ...], reason: str) -> None:
        ns, name = key.split("/", 1)
        claim = self._get_claim(ns, name)
        if claim is None or claim_entries(claim) != entries:
            return  # stale scan entry: the claim already moved on
        upd = json_deepcopy(claim)
        status = upd.setdefault("status", {})
        status.pop("allocation", None)
        status["evicted"] = {
            "reason": reason,
            "message": f"allocated devices lost ({reason}): "
                       f"{sorted(e[2] for e in entries)}"}
        self._stamp_fence(upd)
        try:
            updated = self._client.update_status(RESOURCECLAIMS, upd, ns)
        except (ConflictError, NotFoundError) as e:
            raise _Unscheduled(f"evict {key}: {e}") from e
        # Mutation-cache discipline, same as every scheduler write: the
        # index learns the deallocation from the write, not from a
        # direct shard edit.
        self._after_claim_write(updated)
        SCHED_EVICTIONS.inc(labels={"reason": reason})
        log.warning("evicted claim %s (%s): devices %s no longer "
                    "published", key, reason,
                    sorted(e[2] for e in entries))
        owner = (claim["metadata"].get("annotations") or {}).get(
            "sim/owner-pod")
        if owner:
            self._release_pod_binding(f"{ns}/{owner}", reason)

    def _release_pod_binding(self, key: str, reason: str) -> None:
        """Unbind the evicted claim's owner pod and re-drive it: it
        re-enters the scheduling loop and ends Allocated on surviving
        capacity, or Pending with the PodScheduled=False reason when
        nothing fits (strict topology refusal — never a silent
        shrink)."""
        ns, name = key.split("/", 1)
        pod = self._get_pod(ns, name)
        if pod is None or pod["metadata"].get("deletionTimestamp"):
            return
        if pod["spec"].get("nodeName"):
            upd = json_deepcopy(pod)
            upd["spec"]["nodeName"] = ""
            try:
                updated = self._client.update(PODS, upd, ns)
            except (ConflictError, NotFoundError) as e:
                raise _Unscheduled(f"unbind {key}: {e}") from e
            if self._started:
                self._informers["pods"].update_cache(updated)
            self._set_pod_reason(
                key, "Evicted",
                f"allocated devices lost ({reason}); rescheduling")
        self._enqueue_pod(key)

    @staticmethod
    def _pod_sched_condition(pod: Dict) -> Optional[Dict]:
        for cond in (pod.get("status") or {}).get("conditions") or []:
            if cond.get("type") == "PodScheduled":
                return cond
        return None

    def _set_pod_reason(self, key: str, reason: str, message: str) -> None:
        """Record why the pod is not scheduled as a PodScheduled=False
        condition (Pending-with-reason). Reason/message are only written
        when they change — the failed-attempt path runs repeatedly and
        must not amplify writes. Best-effort: a conflict is retried by
        the next failed attempt."""
        ns, name = key.split("/", 1)
        pod = self._get_pod(ns, name)
        if pod is None or pod["metadata"].get("deletionTimestamp"):
            return
        cur = self._pod_sched_condition(pod)
        if cur is not None and cur.get("status") == "False" \
                and cur.get("reason") == reason:
            return
        upd = json_deepcopy(pod)
        conds = [c for c in (upd.setdefault("status", {}).get(
            "conditions") or []) if c.get("type") != "PodScheduled"]
        conds.append({"type": "PodScheduled", "status": "False",
                      "reason": reason, "message": message})
        upd["status"]["conditions"] = conds
        try:
            updated = self._client.update_status(PODS, upd, ns)
        except (ConflictError, NotFoundError):
            return
        if self._started:
            self._informers["pods"].update_cache(updated)

    def _clear_pod_reason(self, pod: Dict) -> None:
        """The pod bound: flip its PodScheduled condition True (drop the
        stale Pending/Evicted reason). Skipped when no False condition
        was ever recorded — the common placement path stays one write."""
        cur = self._pod_sched_condition(pod)
        if cur is None or cur.get("status") == "True":
            return
        ns = pod["metadata"].get("namespace", "default")
        upd = json_deepcopy(pod)
        conds = [c for c in (upd.setdefault("status", {}).get(
            "conditions") or []) if c.get("type") != "PodScheduled"]
        conds.append({"type": "PodScheduled", "status": "True"})
        upd["status"]["conditions"] = conds
        try:
            updated = self._client.update_status(PODS, upd, ns)
        except (ConflictError, NotFoundError):
            return
        if self._started:
            self._informers["pods"].update_cache(updated)

    # -- per-pod reconcile (worker thread) ------------------------------------

    def _process_pod(self, key: str) -> None:
        # A known-divergent shard must rebuild before its commits flow;
        # the inline call keeps single-worker tests converging without
        # waiting for the queued resync item — but ONLY single-worker:
        # on a pool, every worker inlining it would race concurrent
        # full listings and swap-thrash each other's only_if_mutations
        # guards (the keyed+deduped "resync" queue item, enqueued by
        # every dirty path, already serializes recovery). Scheduling
        # proceeds regardless: clean shards commit normally, and a
        # still-dirty (or mid-rebuild) shard refuses try_commit — so a
        # pod whose pool is divergent degrades to a bounded
        # conflict/requeue, it never allocates against untrusted state.
        if self._workers <= 1 and self._index.dirty:
            self._full_resync()
        ns, name = key.split("/", 1)
        pod = self._get_pod(ns, name)
        if pod is None or pod["metadata"].get("deletionTimestamp"):
            self._forget_pod(key)
            return
        phase = (pod.get("status") or {}).get("phase", "Pending")
        if phase not in ("", "Pending"):
            self._forget_pod(key)
            return
        try:
            pod = self._ensure_claims_from_templates(pod)
            done = self._schedule(pod)
        except (ConflictError, _Unscheduled) as e:
            raise _Unscheduled(str(e)) from e  # workqueue retries w/ backoff
        if done:
            self._forget_pod(key, done=True)
        else:
            # Stays pending; capacity events (via _waiting) / the
            # periodic sweep re-drive it — no busy retry for genuinely
            # unschedulable pods.
            with self._plock:
                if key in self._pending:
                    self._waiting.add(key)
            # Pending-with-reason (SURVEY §18): the refusal is recorded
            # on the pod, so "waiting for capacity" is observable —
            # strict topology refusal must read as a reasoned Pending,
            # never a silent hang. Written only on change.
            self._set_pod_reason(
                key, "Unschedulable",
                "no node can satisfy the pod's claims (insufficient "
                "free capacity or no contiguous topology cuboid)")

    # -- resourceclaim controller analog --------------------------------------

    def _ensure_claims_from_templates(self, pod: Dict) -> Dict:
        """Create template-backed claims the pod is missing; returns the
        (possibly refreshed) pod object. Zero-copy discipline: `pod` may
        be a lister view — it is deepcopied before any mutation."""
        ns = pod["metadata"].get("namespace", "default")
        statuses = ((pod.get("status") or {})
                    .get("resourceClaimStatuses") or [])
        known = {s["name"]: s["resourceClaimName"] for s in statuses}
        changed = False
        for entry in (pod["spec"].get("resourceClaims") or []):
            if entry.get("resourceClaimName"):
                continue
            tmpl_name = entry.get("resourceClaimTemplateName")
            if not tmpl_name:
                continue
            if entry["name"] in known:
                # Status says the claim exists; recreate it if it was
                # deleted out-of-band while the pod lives on.
                if self._get_claim(ns, known[entry["name"]]) is not None:
                    continue
            try:
                rct = self._client.get(RESOURCECLAIMTEMPLATES, tmpl_name, ns)
            except NotFoundError:
                continue  # template not stamped yet; retried by nudge
            claim_name = known.get(entry["name"]) or (
                f"{pod['metadata']['name']}-{entry['name']}")
            claim = {
                "apiVersion": "resource.k8s.io/v1", "kind": "ResourceClaim",
                "metadata": {
                    "name": claim_name, "namespace": ns,
                    "labels": dict((rct["metadata"].get("labels") or {})),
                    "annotations": {
                        "resource.kubernetes.io/pod-claim-name":
                            entry["name"],
                        "sim/owner-pod": pod["metadata"]["name"]},
                },
                "spec": (rct.get("spec") or {}).get("spec") or {},
            }
            try:
                created = self._client.create(RESOURCECLAIMS, claim,
                                              namespace=ns)
                self._after_claim_write(created)
            except (ConflictError, AlreadyExistsError):
                pass  # racing create (retry, superseded worker): converged
            known[entry["name"]] = claim_name
            changed = True
        if changed:
            upd = json_deepcopy(pod)
            upd.setdefault("status", {})["resourceClaimStatuses"] = [
                {"name": k, "resourceClaimName": v}
                for k, v in sorted(known.items())]
            pod = self._client.update_status(PODS, upd, ns)
            if self._started:
                self._informers["pods"].update_cache(pod)
        return pod

    # -- allocation + binding -------------------------------------------------

    def _schedule(self, pod: Dict) -> bool:
        """Returns True when the pod is fully placed (bound, claims
        allocated); False when it must wait for capacity."""
        ns = pod["metadata"].get("namespace", "default")
        claims = self._pod_claims(pod, ns)
        if claims is None:
            raise _Unscheduled("claim object missing")  # retried
        needs_alloc = any(
            not (c.get("status") or {}).get("allocation") for c in claims)
        node_name = pod["spec"].get("nodeName")
        candidates = ([node_name] if node_name
                      else self._candidate_nodes(pod))
        for node in candidates:
            if (needs_alloc and not node_name
                    and self._index.allocated_count(node)
                    >= self._published_device_count(node)):
                # Busy-node skip: every published device on this node is
                # consumed (each allocated result takes at least one
                # distinct published device, so count >= published means
                # full) — no snapshot scan or CEL evaluation needed. At
                # fleet scale the sorted candidate walk otherwise burns
                # its time re-scanning the same leading busy nodes.
                continue
            if self._try_allocate_all(claims, node):
                if not node_name:
                    upd = json_deepcopy(pod)
                    upd["spec"]["nodeName"] = node
                    updated = self._client.update(PODS, upd, ns)
                    if self._started:
                        self._informers["pods"].update_cache(updated)
                    SCHED_PODS_BOUND.inc()
                    # A pod that carried a Pending/Evicted reason is now
                    # placed: flip the condition so "Pending-with-reason"
                    # only ever describes pods that are actually waiting.
                    self._clear_pod_reason(updated)
                return True
        return False

    def _pod_claims(self, pod: Dict, ns: str) -> Optional[List[Dict]]:
        statuses = {s["name"]: s["resourceClaimName"] for s in
                    ((pod.get("status") or {})
                     .get("resourceClaimStatuses") or [])}
        out = []
        for entry in (pod["spec"].get("resourceClaims") or []):
            name = entry.get("resourceClaimName") or statuses.get(
                entry["name"])
            if name is None:
                # Template-backed claim not created yet.
                if entry.get("resourceClaimTemplateName"):
                    return None
                continue
            claim = self._get_claim(ns, name)
            if claim is None:
                return None
            out.append(claim)
        return out

    def _candidate_nodes(self, pod: Dict) -> List[str]:
        selector = pod["spec"].get("nodeSelector") or {}
        ck = tuple(sorted(selector.items()))
        names: Optional[List[str]] = None
        # The selector->names cache spares re-listing + re-sorting the
        # whole node inventory per scheduling attempt (O(n log n) at 5k
        # nodes). `rev` is read BEFORE the listing: an event landing
        # mid-listing stores the entry under the pre-event revision, so
        # the next lookup recomputes rather than trusting a torn view.
        # Event mode only — sync mode has no events to bump revisions.
        rev = self._nodes_rev
        if self._started:
            cached = self._cand_cache.get(ck)
            if cached is not None and cached[0] == rev:
                names = cached[1]
        if names is None:
            names = []
            for node in self._iter_nodes():
                labels = node["metadata"].get("labels") or {}
                if all(labels.get(k) == v for k, v in selector.items()):
                    names.append(node["metadata"]["name"])
            if self._started:
                if len(self._cand_cache) >= self.CAND_CACHE_MAX:
                    # Sweep superseded-revision entries (dead weight —
                    # lookups miss on the rev check); if every entry is
                    # current the workload really has this many live
                    # selectors, so start over rather than grow without
                    # bound. list() snapshots atomically under the GIL
                    # (sibling workers insert concurrently).
                    for k, v in list(self._cand_cache.items()):
                        if v[0] != rev:
                            self._cand_cache.pop(k, None)
                    if len(self._cand_cache) >= self.CAND_CACHE_MAX:
                        self._cand_cache.clear()
                self._cand_cache[ck] = (rev, names)
        if (len(names) > 1
                and featuregates.enabled(
                    featuregates.TopologyAwareScheduling)):
            # Inter-node ICI adjacency: group candidates by the physical
            # slice their chips report, biggest slice group first, worker
            # order within — the pods of a multi-node ComputeDomain then
            # fill ONE slice in rank order instead of scattering across
            # slices in node-name order.
            infos = []
            for name in names:
                topo = self._node_topology(name)
                infos.append((name, topo.slice_id if topo else "",
                              topo.worker_index if topo else 0))
            return topology.rank_candidate_nodes(infos)
        return names

    def _node_topology(self, node: str) -> Optional[topology.NodeTopology]:
        """This node's fabric view (mesh + device-name<->coord maps) from
        its published ResourceSlices; None when the node publishes no
        usable coordinates. Cached against the slices' resourceVersions.
        Worker-thread only."""
        slices = self._slices_for_node(node)
        key = tuple(sorted(
            (sl["metadata"]["name"],
             sl["metadata"].get("resourceVersion", "")) for sl in slices))
        cached = self._topo_cache.get(node)
        if cached is not None and cached[0] == key:
            return cached[1]
        topo = topology.node_topology_from_slices(slices)
        self._topo_cache[node] = (key, topo)
        return topo

    def _published_device_count(self, node: str) -> int:
        """Total devices this node's ResourceSlices publish — the
        busy-node skip's denominator. Cached against the slice revision
        in event mode (sync mode recomputes: nothing bumps the revision
        there)."""
        rev = self._slices_rev
        if self._started:
            cached = self._devcount_cache.get(node)
            if cached is not None and cached[0] == rev:
                return cached[1]
        count = sum(len((sl.get("spec") or {}).get("devices") or ())
                    for sl in self._slices_for_node(node))
        if self._started:
            self._devcount_cache[node] = (rev, count)
        return count

    def _try_allocate_all(self, claims: List[Dict], node: str) -> bool:
        """Allocate every unallocated claim on `node`; all-or-nothing per
        pod (claims already allocated elsewhere pin the pod implicitly:
        a shared pre-allocated claim simply must exist on this node).

        Snapshot discipline (SURVEY §15): availability is read from one
        immutable PoolView built per attempt — no index lock is held
        across the scan — plus a staging overlay for this pod's own
        picks. The picks then commit optimistically: ``try_commit``
        re-validates every device against the live shard and reserves
        them all-or-nothing. A conflict (another worker took a device
        first, the shard is mid-resync, or the sched.snapshot_commit
        fault fired) re-scans against a fresh snapshot — which now sees
        the winner's reservation — up to COMMIT_RETRIES times before
        the pod item falls back to a backoff requeue."""
        for _attempt in range(self.COMMIT_RETRIES):
            view = self._index.snapshot(node)
            overlay: Set[_Entry] = set()
            staged: List[Tuple[Dict, Dict, str, Tuple[_Entry, ...]]] = []
            for claim in claims:
                alloc = (claim.get("status") or {}).get("allocation")
                if alloc:
                    # Shared claim already allocated: usable only if it
                    # landed on this node's pool.
                    pools = {r.get("pool") for r in
                             (alloc.get("devices") or {}).get("results")
                             or []}
                    if pools and node not in pools:
                        return False
                    continue
                allocation = self._allocate(claim, node, view, overlay)
                if allocation is None:
                    return False
                entries = tuple(
                    (r["driver"], r["pool"], r["device"])
                    for r in allocation["devices"]["results"])
                staged.append((claim, allocation, claim_key(claim),
                               entries))
            if not staged:
                return True  # nothing to place: already allocated
            committed = self._index.try_commit(
                node, [(k, e) for _c, _a, k, e in staged])
            if committed:
                break
            if committed is None:
                # Claim-level conflict: a sibling worker allocated or
                # reserved one of these very claims, so the local claim
                # bodies are stale — every retry would stage the same
                # outdated copy and conflict deterministically (the
                # fresh snapshot changes the DEVICE picks, not the
                # claim). Skip the guaranteed-futile rescans; the
                # backoff requeue's claim re-fetch resolves it.
                raise _Unscheduled(
                    f"claim copies went stale under commit on {node}")
            # Device conflict: the shard moved underneath the snapshot.
            # Loop — the fresh view includes whatever won.
        else:
            raise _Unscheduled(
                f"snapshot commit kept conflicting on {node}")
        try:
            for claim, allocation, _k, _e in staged:
                # Per-claim trace root (SURVEY §19): sched.pod_seen →
                # sched.allocate, the allocate span's traceparent
                # stamped into the claim annotations in the SAME status
                # write (K8s status subresource carries metadata) — the
                # node driver, prepare pipeline, CDI env export and
                # mesh builder all continue this trace.
                t_root = TRACER.begin(
                    "sched.pod_seen", root=True,
                    attributes={"claim": claim_key(claim), "node": node})
                t_alloc = TRACER.begin("sched.allocate", parent=t_root)
                written = False
                try:
                    upd = json_deepcopy(claim)
                    upd.setdefault("status", {})["allocation"] = \
                        allocation
                    # Re-allocation supersedes a prior eviction: the
                    # marker must describe the claim's CURRENT state or
                    # not exist.
                    upd["status"].pop("evicted", None)
                    tp = t_alloc.traceparent()
                    if tp:
                        upd["metadata"].setdefault(
                            "annotations", {})[TRACEPARENT_ANNOTATION] \
                            = tp
                    # The commit: fenced — a deposed leader reaching
                    # here late gets a ConflictError, not a landed
                    # allocation (SURVEY §22).
                    self._stamp_fence(upd)
                    updated = self._client.update_status(
                        RESOURCECLAIMS, upd,
                        upd["metadata"].get("namespace"))
                    self._after_claim_write(updated)
                    written = True
                finally:
                    if written:
                        t_alloc.end()
                        t_root.end()
                    else:
                        t_alloc.abandon("allocation write failed")
                        t_root.abandon("allocation write failed")
        finally:
            # Reservations end when the real allocations are indexed
            # (success: _after_claim_write applied them) or when the
            # write failed (the devices return to the free set and the
            # requeued attempt re-picks).
            self._index.release(node, [k for _c, _a, k, _e in staged])
        return True

    def _stamp_fence(self, upd: Dict) -> None:
        """Stamp the acting leader's lease generation into a
        claim-status write the fencing reactor guards (allocation +
        evict — the scheduler's commits; ResourceClaims have no other
        status writer, so the stamp only ever meets fencing-aware
        paths). Pod writes stay unstamped: pods are co-written by
        nodesim, and a stale stamp riding a deepcopy round-trip would
        fence an innocent writer. No-op outside HA mode (no elector
        ever set a generation) — the single-process paths pay
        nothing."""
        if self.lease_generation is not None:
            upd["metadata"].setdefault("annotations", {})[
                FENCING_ANNOTATION] = str(self.lease_generation)

    def _after_claim_write(self, obj: Dict) -> None:
        """Mutation-cache discipline for the scheduler's own writes: the
        informer cache AND the allocation index see the write before the
        watch event lands — the index never lags the scheduler's own
        allocations, which is what makes single-writer allocation safe
        on an event-driven cache. (In sync mode the index update keeps
        later pods in the SAME pass from re-picking the devices.)"""
        if self._started:
            self._informers["claims"].update_cache(obj)
        try:
            self._index.apply(obj)
        except FaultInjected as e:
            self._mark_dirty_from(e, "index apply failed (own write)")

    def _allocate(self, claim: Dict, node: str, view: PoolView,
                  overlay: Set[_Entry]) -> Optional[Dict]:
        devices = (claim.get("spec") or {}).get("devices") or {}
        results = []
        for req in devices.get("requests") or []:
            exact = req.get("exactly") or req  # v1 wrapper or flat
            class_name = exact.get("deviceClassName", "")
            count = int(exact.get("count") or 1)
            sources = self._class_selector_sources(class_name)
            if sources is None:
                return None
            # Per-request selectors AND with the class's (the real
            # allocator's semantics: every selector must match;
            # gpu-test6-style attribute selection rides here).
            sources = sources + [
                (sel.get("cel") or {}).get("expression", "")
                for sel in exact.get("selectors") or []]
            progs = cel.compile_many(sources)
            if progs is None:
                return None  # a broken selector selects nothing
            picked = self._pick_devices(node, progs, count, view, overlay)
            if picked is None:
                return None
            for driver, dev in picked:
                overlay.update(_expand([(driver, node, dev)]))
                results.append({"request": req["name"], "driver": driver,
                                "pool": node, "device": dev})
        if not results:
            return None
        config = [{"source": "FromClaim", **entry}
                  for entry in devices.get("config") or []]
        return {"devices": {"results": results, "config": config},
                "nodeSelector": {"nodeSelectorTerms": [{"matchFields": [
                    {"key": "metadata.name", "operator": "In",
                     "values": [node]}]}]}}

    def _class_selector_sources(self, name: str) -> Optional[List[str]]:
        """All CEL expressions of the DeviceClass (None if the class does
        not exist — the claim is unallocatable, not unconstrained),
        cached per (name, resourceVersion)."""
        dc = self._get_class(name)
        if dc is None:
            self._class_cache.pop(name, None)
            return None
        rv = dc["metadata"].get("resourceVersion", "")
        cached = self._class_cache.get(name)
        if cached is not None and cached[0] == rv:
            return cached[1]
        sources = [(sel.get("cel") or {}).get("expression", "")
                   for sel in (dc.get("spec") or {}).get("selectors") or []]
        self._class_cache[name] = (rv, sources)
        return sources

    def _pick_devices(self, node: str, progs: List["cel.Program"],
                      count: int, view: PoolView, overlay: Set[_Entry]
                      ) -> Optional[List[Tuple[str, str]]]:
        """Devices on `node` matching EVERY compiled CEL program, as
        (driver, name) pairs. CEL is evaluated for real against the
        published attributes (simcluster.cel): a wrong attribute name or
        type mismatch selects nothing instead of everything.
        Availability reads the caller's immutable PoolView — the scan
        holds no index lock; stale reads surface as commit conflicts.

        Iteration is deterministic — slices and devices are scanned in
        name order — so first-fit picks and topology scores reproduce
        across runs and chaos seeds regardless of dict/watch ordering.

        With the TopologyAwareScheduling gate on, multi-chip requests on
        a node that publishes chip coordinates take the topology-scored
        path: the pick must be an ICI-contiguous cuboid, chosen by the
        fragmentation score (tpu_dra.topology.best_placement). No cuboid
        fits -> the claim WAITS (None) rather than degrade to a
        scattered allocation; nodes without usable topology keep
        first-fit (counted as fallback)."""
        gate_on = (count > 1 and featuregates.enabled(
            featuregates.TopologyAwareScheduling))
        # A node with no usable topology keeps the first-fit early exit
        # even under the gate: scanning its whole inventory just to fall
        # back would turn O(count) picks into O(devices) on every
        # coordinate-less node (mixed fleets, sysfs without topology/).
        topo = self._node_topology(node) if gate_on else None
        topo_path = topo is not None
        available: List[Tuple[str, str]] = []
        for sl in sorted(self._slices_for_node(node),
                         key=lambda s: s["metadata"]["name"]):
            spec = sl.get("spec") or {}
            driver = spec.get("driver", "")
            for dev in sorted(spec.get("devices") or [],
                              key=lambda d: d["name"]):
                if not all(p.matches(dev, driver) for p in progs):
                    continue
                if view.is_taken(driver, dev["name"], overlay=overlay):
                    continue
                available.append((driver, dev["name"]))
                if not topo_path and len(available) == count:
                    if gate_on:
                        TOPO_ALLOCS.inc(labels={"outcome": "fallback"})
                    return available  # first-fit: done at count
        if len(available) < count:
            return None
        if not topo_path:
            return available[:count]
        return self._pick_topology(topo, available, count)

    def _pick_topology(self, topo: "topology.NodeTopology",
                       available: List[Tuple[str, str]],
                       count: int) -> Optional[List[Tuple[str, str]]]:
        """Topology-scored pick over the CEL-matched free devices."""
        if any(name not in topo.coord_of for _d, name in available):
            # The match includes devices the chip mesh cannot lay out
            # (subslices, foreign drivers): no fabric model for this
            # request — first-fit, honestly counted.
            TOPO_ALLOCS.inc(labels={"outcome": "fallback"})
            return available[:count]
        free = {topo.coord_of[name] for _d, name in available}
        with Timer(TOPO_SCORE_SECONDS):
            placed = topology.best_placement(topo.mesh, free, count)
            if placed is not None:
                # Observed inside the timed region: the free-cuboid scan
                # is the same order of work as the placement scan, and
                # leaving it outside would under-attribute the topology
                # path's real per-pick overhead.
                TOPO_FREE_CUBOID.observe(topology.max_free_cuboid(
                    topo.mesh, free.difference(placed)))
        if placed is None:
            TOPO_ALLOCS.inc(labels={"outcome": "unplaceable"})
            return None  # wait for a contiguous window, never scatter
        TOPO_ALLOCS.inc(labels={"outcome": "contiguous"})
        driver_of = dict((name, drv) for drv, name in available)
        return [(driver_of[topo.name_of[c]], topo.name_of[c])
                for c in placed]

    # -- introspection --------------------------------------------------------

    def verify_index(self) -> List[str]:
        """Divergences between the incremental index and cluster truth
        (a fresh apiserver claim listing); empty = consistent. Chaos
        invariant after quiesce."""
        return self._index.diff_against(self._client.list(RESOURCECLAIMS))

    def verify_topology(self) -> List[str]:
        """Topology invariants against cluster truth (chaos, after
        quiesce): (1) every allocated multi-chip claim on a node that
        publishes coordinates is an ICI-contiguous cuboid; (2) for each
        such node, the free coordinate set DERIVED from the incremental
        AllocationIndex equals the one derived from a fresh claim
        listing — the index owns allocation state (SURVEY §11), so a
        divergent derived free-set means the topology view (mesh/coord
        cache) broke, not the bookkeeping."""
        claims = self._client.list(RESOURCECLAIMS)
        slices = self._client.list(RESOURCESLICES)
        out = topology.allocation_violations(claims, slices)
        taken_truth: Dict[str, Set[str]] = {}
        for claim in claims:
            for _driver, pool, dev in claim_entries(claim):
                taken_truth.setdefault(pool, set()).add(_parent_of(dev))
        by_node: Dict[str, List[Dict]] = {}
        for sl in slices:
            node = (sl.get("spec") or {}).get("nodeName")
            if node:
                by_node.setdefault(node, []).append(sl)
        for node in sorted(by_node):
            topo = topology.node_topology_from_slices(by_node[node])
            if topo is None:
                continue
            free_truth = {c for name, c in topo.coord_of.items()
                          if name not in taken_truth.get(node, set())}
            free_index = {c for name, c in topo.coord_of.items()
                          if not self._index.is_taken(
                              topo.driver_of[name], node, name)}
            if free_truth != free_index:
                out.append(
                    f"topology free-set on {node} diverges from the "
                    f"allocation index: index-only "
                    f"{sorted(free_index - free_truth)}, truth-only "
                    f"{sorted(free_truth - free_index)}")
        return out

    def pending_pods(self) -> Set[str]:
        with self._plock:
            return set(self._pending)
