"""DRA scheduler sim: claims-from-templates, device allocation, binding.

Stands in for the upstream kube-scheduler's DRA plugin + the
kube-controller-manager's resourceclaim controller (neither is driver
code — SURVEY §1: "there is no scheduler code to rebuild"). Allocation
follows the real algorithm's observable behavior: DeviceClass CEL
selectors are matched against device attributes published in
ResourceSlices, devices already referenced by any allocated claim are
excluded, and the pod binds to a node that can satisfy every claim.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Set, Tuple

from tpu_dra.k8s.client import ApiClient, ConflictError, NotFoundError
from tpu_dra.k8s.resources import (
    DEVICECLASSES, NODES, PODS, RESOURCECLAIMS, RESOURCECLAIMTEMPLATES,
    RESOURCESLICES,
)
from tpu_dra.simcluster.cel import device_matches

log = logging.getLogger("simcluster.scheduler")


class Scheduler:
    def __init__(self, client: ApiClient, interval: float = 0.15):
        self._client = client
        self._interval = interval
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="sim-scheduler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)

    def _run(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.reconcile_once()
            except Exception:  # noqa: BLE001 — the loop must survive
                log.exception("scheduler reconcile failed")

    # ------------------------------------------------------------------

    def reconcile_once(self) -> None:
        pods = self._client.list(PODS)
        self._gc_orphan_claims(pods)
        for pod in pods:
            if pod["metadata"].get("deletionTimestamp"):
                continue
            phase = (pod.get("status") or {}).get("phase", "Pending")
            if phase not in ("", "Pending"):
                continue
            try:
                self._ensure_claims_from_templates(pod)
                self._schedule(pod)
            except ConflictError:
                continue  # racing another write: next tick retries

    def _gc_orphan_claims(self, pods: List[Dict]) -> None:
        """The resourceclaim controller's ownerRef GC analog: a claim
        generated from a template dies with its pod — otherwise exclusive
        devices (channel-0, the daemon device) stay allocated forever and
        the next workload can never schedule."""
        alive = {(p["metadata"].get("namespace", "default"),
                  p["metadata"]["name"]) for p in pods}
        for claim in self._client.list(RESOURCECLAIMS):
            owner = (claim["metadata"].get("annotations") or {}).get(
                "sim/owner-pod")
            if not owner:
                continue
            ns = claim["metadata"].get("namespace", "default")
            if (ns, owner) not in alive:
                try:
                    self._client.delete(RESOURCECLAIMS,
                                        claim["metadata"]["name"], ns)
                    log.info("GC claim %s/%s (pod %s gone)", ns,
                             claim["metadata"]["name"], owner)
                except NotFoundError:
                    pass

    # -- resourceclaim controller analog --------------------------------

    def _ensure_claims_from_templates(self, pod: Dict) -> None:
        ns = pod["metadata"].get("namespace", "default")
        statuses = ((pod.get("status") or {})
                    .get("resourceClaimStatuses") or [])
        known = {s["name"]: s["resourceClaimName"] for s in statuses}
        changed = False
        for entry in (pod["spec"].get("resourceClaims") or []):
            if entry.get("resourceClaimName") or entry["name"] in known:
                continue
            tmpl_name = entry.get("resourceClaimTemplateName")
            if not tmpl_name:
                continue
            try:
                rct = self._client.get(RESOURCECLAIMTEMPLATES, tmpl_name, ns)
            except NotFoundError:
                continue  # template not stamped yet; retry next tick
            claim_name = f"{pod['metadata']['name']}-{entry['name']}"
            claim = {
                "apiVersion": "resource.k8s.io/v1", "kind": "ResourceClaim",
                "metadata": {
                    "name": claim_name, "namespace": ns,
                    "labels": dict((rct["metadata"].get("labels") or {})),
                    "annotations": {
                        "resource.kubernetes.io/pod-claim-name":
                            entry["name"],
                        "sim/owner-pod": pod["metadata"]["name"]},
                },
                "spec": (rct.get("spec") or {}).get("spec") or {},
            }
            try:
                self._client.create(RESOURCECLAIMS, claim, namespace=ns)
            except ConflictError:
                pass
            known[entry["name"]] = claim_name
            changed = True
        if changed:
            pod.setdefault("status", {})["resourceClaimStatuses"] = [
                {"name": k, "resourceClaimName": v}
                for k, v in sorted(known.items())]
            self._client.update_status(PODS, pod, ns)

    # -- allocation + binding -------------------------------------------

    def _schedule(self, pod: Dict) -> None:
        ns = pod["metadata"].get("namespace", "default")
        claims = self._pod_claims(pod, ns)
        if claims is None:
            return  # some claim object missing; retry next tick
        node_name = pod["spec"].get("nodeName")
        candidates = ([node_name] if node_name
                      else self._candidate_nodes(pod))
        for node in candidates:
            if self._try_allocate_all(claims, node):
                if not node_name:
                    pod["spec"]["nodeName"] = node
                    self._client.update(PODS, pod, ns)
                return

    def _pod_claims(self, pod: Dict, ns: str) -> Optional[List[Dict]]:
        statuses = {s["name"]: s["resourceClaimName"] for s in
                    ((pod.get("status") or {})
                     .get("resourceClaimStatuses") or [])}
        out = []
        for entry in (pod["spec"].get("resourceClaims") or []):
            name = entry.get("resourceClaimName") or statuses.get(
                entry["name"])
            if name is None:
                # Template-backed claim not created yet.
                if entry.get("resourceClaimTemplateName"):
                    return None
                continue
            try:
                out.append(self._client.get(RESOURCECLAIMS, name, ns))
            except NotFoundError:
                return None
        return out

    def _candidate_nodes(self, pod: Dict) -> List[str]:
        selector = pod["spec"].get("nodeSelector") or {}
        names = []
        for node in self._client.list(NODES):
            labels = node["metadata"].get("labels") or {}
            if all(labels.get(k) == v for k, v in selector.items()):
                names.append(node["metadata"]["name"])
        return names

    def _try_allocate_all(self, claims: List[Dict], node: str) -> bool:
        """Allocate every unallocated claim on `node`; all-or-nothing per
        pod (claims already allocated elsewhere pin the pod implicitly:
        a shared pre-allocated claim simply must exist on this node)."""
        taken = self._allocated_devices()
        staged: List[Tuple[Dict, Dict]] = []
        for claim in claims:
            alloc = (claim.get("status") or {}).get("allocation")
            if alloc:
                # Shared claim already allocated: usable only if it landed
                # on this node's pool.
                pools = {r.get("pool") for r in
                         (alloc.get("devices") or {}).get("results") or []}
                if pools and node not in pools:
                    return False
                continue
            allocation = self._allocate(claim, node, taken)
            if allocation is None:
                return False
            staged.append((claim, allocation))
        for claim, allocation in staged:
            claim.setdefault("status", {})["allocation"] = allocation
            self._client.update_status(RESOURCECLAIMS, claim,
                                       claim["metadata"].get("namespace"))
        return True

    @staticmethod
    def _parent_of(device: str) -> str:
        """Subslice devices ('chip-N-ss...') partition their parent chip
        ('chip-N'); everything else is its own parent."""
        return device.split("-ss")[0] if "-ss" in device else device

    def _allocated_devices(self) -> Set[Tuple[str, str, str]]:
        """Names in use, expanded with partition semantics (the DRA
        partitionable-device counter analog): a whole-chip allocation
        blocks its subslices and vice versa, while two different
        subslices of one chip can coexist (MIG-style)."""
        taken = set()
        for claim in self._client.list(RESOURCECLAIMS):
            alloc = (claim.get("status") or {}).get("allocation") or {}
            for r in (alloc.get("devices") or {}).get("results") or []:
                key = (r.get("driver", ""), r.get("pool", ""))
                name = r.get("device", "")
                taken.add((*key, name))
                parent = self._parent_of(name)
                if parent != name:
                    # Subslice in use: the WHOLE chip is unavailable, but
                    # sibling subslices stay allocatable.
                    taken.add((*key, parent))
                else:
                    # Whole chip in use: all of its subslices are too.
                    taken.add((*key, f"{name}-ss*"))
        return taken

    def _is_taken(self, taken: Set[Tuple[str, str, str]], driver: str,
                  pool: str, name: str) -> bool:
        if (driver, pool, name) in taken:
            return True
        parent = self._parent_of(name)
        if parent != name and (driver, pool, f"{parent}-ss*") in taken:
            return True  # parent chip wholly claimed
        return False

    def _allocate(self, claim: Dict, node: str,
                  taken: Set[Tuple[str, str, str]]) -> Optional[Dict]:
        devices = (claim.get("spec") or {}).get("devices") or {}
        results = []
        for req in devices.get("requests") or []:
            exact = req.get("exactly") or req  # v1 wrapper or flat
            class_name = exact.get("deviceClassName", "")
            count = int(exact.get("count") or 1)
            exprs = self._class_selectors(class_name)
            if exprs is None:
                return None
            # Per-request selectors AND with the class's (the real
            # allocator's semantics: every selector must match;
            # gpu-test6-style attribute selection rides here).
            exprs = exprs + [
                (sel.get("cel") or {}).get("expression", "")
                for sel in exact.get("selectors") or []]
            picked = self._pick_devices(node, exprs, count, taken)
            if picked is None:
                return None
            for driver, dev in picked:
                taken.add((driver, node, dev))
                parent = self._parent_of(dev)
                taken.add((driver, node, parent) if parent != dev
                          else (driver, node, f"{dev}-ss*"))
                results.append({"request": req["name"], "driver": driver,
                                "pool": node, "device": dev})
        if not results:
            return None
        config = [{"source": "FromClaim", **entry}
                  for entry in devices.get("config") or []]
        return {"devices": {"results": results, "config": config},
                "nodeSelector": {"nodeSelectorTerms": [{"matchFields": [
                    {"key": "metadata.name", "operator": "In",
                     "values": [node]}]}]}}

    def _class_selectors(self, name: str) -> Optional[List[str]]:
        """All CEL expressions of the DeviceClass (None if the class does
        not exist — the claim is unallocatable, not unconstrained)."""
        try:
            dc = self._client.get(DEVICECLASSES, name)
        except NotFoundError:
            return None
        return [(sel.get("cel") or {}).get("expression", "")
                for sel in (dc.get("spec") or {}).get("selectors") or []]

    def _pick_devices(self, node: str, exprs: List[str], count: int,
                      taken: Set[Tuple[str, str, str]]
                      ) -> Optional[List[Tuple[str, str]]]:
        """Devices on `node` matching EVERY CEL expression, as
        (driver, name) pairs. CEL is evaluated for real against the
        published attributes (simcluster.cel): a wrong attribute name or
        type mismatch selects nothing instead of everything."""
        available = []
        for sl in self._client.list(RESOURCESLICES):
            spec = sl.get("spec") or {}
            if spec.get("nodeName") != node:
                continue
            driver = spec.get("driver", "")
            for dev in spec.get("devices") or []:
                if not all(device_matches(e, dev, driver)
                           for e in exprs):
                    continue
                if self._is_taken(taken, driver, node, dev["name"]):
                    continue
                available.append((driver, dev["name"]))
        if len(available) < count:
            return None
        return available[:count]
